"""Benchmark entry: one JSON line on stdout for the round driver.

Measures the framework's primary throughput metric (BASELINE.json):
candidate route evaluations per second per chip, on the X-n200-k36-
shaped synthetic CVRP (200 nodes, 36 vehicles — CVRPLIB files can't be
fetched in this zero-egress container; vrpms_tpu.io.synth generates the
same statistical shape deterministically).

vs_baseline = accelerator throughput / single-host CPU throughput of the
identical compiled search. The reference publishes no solver numbers at
all (BASELINE.md: every endpoint is a stub), so the honest baseline is
the same workload on the host CPU — the hardware class the reference's
pure-Python/serverless design targets.

Diagnostics go to stderr; stdout carries exactly one JSON line.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp


def _pick_device():
    try:
        dev = jax.devices()[0]
        return dev, dev.platform
    except RuntimeError as e:
        print(f"[bench] default backend unavailable ({e}); forcing CPU", file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        dev = jax.devices()[0]
        return dev, dev.platform


def _throughput(inst, device, n_chains: int, n_iters: int, seed: int = 0):
    """routes/sec of the compiled SA sweep on `device` (compile excluded)."""
    from vrpms_tpu.core.cost import CostWeights, objective_batch_mode
    from vrpms_tpu.moves import knn_table
    from vrpms_tpu.solvers.sa import (
        _auto_temps,
        initial_giants,
        sa_chain_step,
        SAParams,
    )

    w = CostWeights.make()
    t0, t1 = _auto_temps(inst, SAParams())
    knn = knn_table(inst.durations[0], SAParams().knn_k)
    inst = jax.device_put(inst, device)
    knn = jax.device_put(knn, device)
    # fused pallas kernel on any accelerator, flat-gather on CPU
    # (core.cost.resolve_eval_mode rationale; 'axon' aliases tpu here)
    mode = "gather" if device.platform == "cpu" else "pallas"

    def chunk(giants, costs, key, start):
        def body(state, i):
            giants, costs = state
            return sa_chain_step(
                giants, costs, key, start + i, t0, t1, n_iters, inst, w, mode, knn
            ), None

        (giants, costs), _ = jax.lax.scan(
            body, (giants, costs), jnp.arange(n_iters)
        )
        return giants, costs

    run = jax.jit(chunk, device=device)
    key = jax.random.key(seed)
    # production init: perturbed nearest-neighbor seeds (SAParams.init)
    giants = jax.device_put(
        initial_giants(key, n_chains, inst, SAParams(), mode), device
    )
    costs = objective_batch_mode(giants, inst, w, mode)

    # Warmup/compile
    g, c = run(giants, costs, key, jnp.int32(0))
    jax.block_until_ready(c)
    t_start = time.perf_counter()
    g, c = run(g, c, key, jnp.int32(n_iters))
    jax.block_until_ready(c)
    elapsed = time.perf_counter() - t_start
    routes_per_sec = n_chains * n_iters / elapsed
    return routes_per_sec, elapsed, float(jnp.min(c))


def main():
    dev, platform = _pick_device()
    print(f"[bench] device: {dev} ({platform})", file=sys.stderr)

    from vrpms_tpu.io.synth import synth_cvrp

    inst = synth_cvrp(200, 36, seed=0)

    cpu_baseline = "measured"
    if platform == "cpu":
        value, elapsed, best = _throughput(inst, dev, n_chains=256, n_iters=200)
        cpu_rps = value
    else:
        # 16k chains: throughput saturates ~16% above the 4k-chain point
        # (3.5M vs 3.0M routes/s on v5e) and more parallel chains also
        # help search quality; VMEM still fits via the kernel's autotiler
        value, elapsed, best = _throughput(inst, dev, n_chains=16384, n_iters=1000)
        try:
            cpu_dev = jax.devices("cpu")[0]
            cpu_rps, _, _ = _throughput(inst, cpu_dev, n_chains=256, n_iters=100)
        except Exception as e:  # CPU fallback baseline unavailable
            print(f"[bench] cpu baseline failed: {e}", file=sys.stderr)
            # vs_baseline degenerates to 1.0; the flag below keeps a
            # fabricated ratio distinguishable from a real measurement
            cpu_rps = value
            cpu_baseline = "unavailable"

    result = {
        "metric": "candidate_routes_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "routes/s/chip",
        "vs_baseline": round(value / cpu_rps, 3),
        "device": platform,
        "instance": "synth-X-n200-k36",
        "best_cost": round(best, 1),
        "measure_seconds": round(elapsed, 3),
        "cpu_routes_per_sec": round(cpu_rps, 1),
        "cpu_baseline": cpu_baseline,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
