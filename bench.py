"""Benchmark entry: one JSON line on stdout for the round driver.

Headline metric (since round 5): the TRUE gap-to-BKS at a 10 s solve
budget on the largest REAL embedded CVRPLIB instance (E-n51-k5,
published optimum 521) — the metric the framework actually optimizes
(BASELINE.json north star), measured on data with a published answer
instead of the synthetic stand-in that fronted rounds 1-4.
vs_baseline = same-budget host-CPU cost / TPU cost on that instance
(>1 means the accelerator finds strictly better tours in equal
wall-clock; the reference publishes no solver numbers at all — every
endpoint is a stub — so its target hardware class is the baseline).

The `families` map carries everything else — one entry per solver
family (ga / aco / vrptw one-hot / delta kernels / time-dependent /
scale / real instances incl. full R101), plus `raw_sweep`, the
candidate-routes/s/chip line that was the rounds-1-4 headline, kept for
round-over-round continuity with its roofline fields. Diagnostics go to
stderr; stdout carries exactly one JSON line.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp


def _pick_device():
    try:
        dev = jax.devices()[0]
        return dev, dev.platform
    except RuntimeError as e:
        print(f"[bench] default backend unavailable ({e}); forcing CPU", file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        dev = jax.devices()[0]
        return dev, dev.platform


def _throughput(
    inst, device, n_chains: int, n_iters: int, seed: int = 0, mode: str | None = None
):
    """routes/sec of the compiled SA sweep on `device` (compile excluded).

    `mode` None picks the production default for the device platform
    (fused pallas kernel on accelerators — degrading per-call to the XLA
    one-hot path where the kernel doesn't apply, e.g. timed instances —
    flat-gather on CPU; core.cost.resolve_eval_mode rationale)."""
    from vrpms_tpu.core.cost import CostWeights, objective_batch_mode
    from vrpms_tpu.moves import knn_table
    from vrpms_tpu.solvers.sa import (
        _auto_temps,
        initial_giants,
        sa_chain_step,
        SAParams,
    )

    w = CostWeights.make()
    t0, t1 = _auto_temps(inst, SAParams())
    knn = knn_table(inst.durations[0], SAParams().knn_k)
    inst = jax.device_put(inst, device)
    knn = jax.device_put(knn, device)
    if mode is None:
        mode = "gather" if device.platform == "cpu" else "pallas"

    def chunk(giants, costs, key, start):
        def body(state, i):
            giants, costs = state
            return sa_chain_step(
                giants, costs, key, start + i, t0, t1, n_iters, inst, w, mode, knn
            ), None

        (giants, costs), _ = jax.lax.scan(
            body, (giants, costs), jnp.arange(n_iters)
        )
        return giants, costs

    run = jax.jit(chunk, device=device)
    key = jax.random.key(seed)
    # production init: perturbed nearest-neighbor seeds (SAParams.init)
    giants = jax.device_put(
        initial_giants(key, n_chains, inst, SAParams(), mode), device
    )
    costs = objective_batch_mode(giants, inst, w, mode)

    # Warmup/compile
    g, c = run(giants, costs, key, jnp.int32(0))
    jax.block_until_ready(c)
    t_start = time.perf_counter()
    g, c = run(g, c, key, jnp.int32(n_iters))
    jax.block_until_ready(c)
    elapsed = time.perf_counter() - t_start
    routes_per_sec = n_chains * n_iters / elapsed
    return routes_per_sec, elapsed, float(jnp.min(c))


def _timed(fn, *args):
    """(result, steady-state seconds): run once for compile, once timed."""
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def _family_ga(device):
    """GA: pop 512, 50 generations, n=100 (BASELINE.md measured row)."""
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.solvers import GAParams, solve_ga

    inst = jax.device_put(synth_cvrp(100, 12, seed=12), device)
    p = GAParams(population=512, generations=50, elites=8)

    res, warm_s = _timed(lambda: solve_ga(inst, key=0, params=p))
    return {
        "seconds": round(warm_s, 3),
        "cost": round(float(res.breakdown.distance), 1),
        "evals_per_sec": round(int(res.evals) / warm_s, 1),
    }


def _family_aco(device):
    """ACO with KNN candidate lists: 128 ants x 200 iterations, n=100 —
    the same 25.6k genome evaluations as the GA family (512 x 50), so
    the two quality numbers compare at equal budget. With the round-3
    deposit schedule (global-best alternation + delta-polished deposit
    tours + rho 0.15) this lands at/below the GA line on the shared
    seed (18899 vs 19089)."""
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.solvers import ACOParams, solve_aco

    inst = jax.device_put(synth_cvrp(100, 12, seed=12), device)
    p = ACOParams(n_ants=128, n_iters=200)

    res, warm_s = _timed(lambda: solve_aco(inst, key=0, params=p))
    return {
        "seconds": round(warm_s, 3),
        "cost": round(float(res.breakdown.distance), 1),
        "tours_per_sec": round(int(res.evals) / warm_s, 1),
    }


def _family_vrptw(device):
    """VRPTW sweep (one-hot max-plus-scan TW path), Solomon-R101 shape."""
    from vrpms_tpu.io.synth import synth_vrptw

    inst = synth_vrptw(101, 19, seed=13)
    rps, elapsed, best = _throughput(inst, device, n_chains=4096, n_iters=300)
    return {
        "routes_per_sec": round(rps, 1),
        "seconds": round(elapsed, 3),
        "best_cost": round(best, 1),
    }


def _family_td(device):
    """Time-dependent sweep (lean-scan hot path), T=24 slices, n=200 —
    plus, since round 5, the TD DELTA path (kernels.sa_delta_td: frozen
    factor-weight surrogate, launch-boundary exact resyncs) on the same
    instance."""
    import numpy as np

    from vrpms_tpu.core import make_instance
    from vrpms_tpu.io.synth import synth_cvrp

    base = synth_cvrp(200, 36, seed=0)
    d = np.asarray(base.durations[0])
    t_slices = 24
    # rush-hour profile: +-30% per slice over the day
    factors = 1.0 + 0.3 * np.sin(np.linspace(0, 2 * np.pi, t_slices, endpoint=False))
    slices = d[None, :, :] * factors[:, None, None]
    inst = make_instance(
        slices,
        demands=np.asarray(base.demands),
        capacities=np.asarray(base.capacities).tolist(),
        slice_axis="first",
        slice_minutes=60.0,
    )
    # B=4096 matches the vrptw_onehot family so the TD-vs-untimed ratio
    # in BENCH_r*.json is batch-for-batch (round-2 bar: within ~3x).
    rps, elapsed, best = _throughput(inst, device, n_chains=4096, n_iters=100)
    out = {
        "routes_per_sec": round(rps, 1),
        "seconds": round(elapsed, 3),
        "best_cost": round(best, 1),
        "n_slices": t_slices,
        "td_rank": int(inst.td_rank),
    }
    from vrpms_tpu.core.cost import CostWeights
    from vrpms_tpu.solvers.sa import SAParams, _delta_supported, solve_sa_delta

    if device.platform != "cpu" and _delta_supported(
        inst, CostWeights.make(), "pallas"
    ):
        B, iters = 4096, 4096
        p = SAParams(n_chains=B, n_iters=iters)
        res, warm_s = _timed(lambda: solve_sa_delta(inst, key=1, params=p))
        row = sorted(int(x) for x in np.asarray(res.giant) if x)
        assert row == list(range(1, inst.n_customers + 1)), (
            "TD delta champion is not a valid tour"
        )
        out["delta_moves_per_sec"] = round(B * iters / warm_s, 1)
        out["delta_seconds"] = round(warm_s, 2)
        out["delta_cost"] = round(float(res.breakdown.distance), 1)
        out["delta_cap_excess"] = float(res.breakdown.cap_excess)
    return out


def _family_polish(device):
    """Delta-descent polish: cost drop + wall on 32 NN-seeded tours."""
    from vrpms_tpu.core.cost import CostWeights, resolve_eval_mode
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.solvers.delta_ls import delta_polish_batch
    from vrpms_tpu.solvers.sa import SAParams, initial_giants

    inst = jax.device_put(synth_cvrp(200, 36, seed=0), device)
    w = CostWeights.make()
    mode = resolve_eval_mode("auto")
    giants = initial_giants(jax.random.key(3), 32, inst, SAParams(), mode)
    from vrpms_tpu.core.cost import objective_batch_mode

    before = float(jnp.min(objective_batch_mode(giants, inst, w, mode)))

    def run():
        g, c, e = delta_polish_batch(giants, inst, w, max_sweeps=16)
        return c

    (costs, warm_s) = _timed(lambda: run())
    return {
        "seconds": round(warm_s, 3),
        "cost_before": round(before, 1),
        "cost_after": round(float(jnp.min(costs)), 1),
    }


def _family_sa_delta(device):
    """The fused delta-step anneal (kernels.sa_delta): one Pallas kernel
    per move does proposal decode + apply + closed-form distance delta +
    capacity recompute + Metropolis, VMEM-resident. VERDICT round-2
    item 2's ask: effective moves/s >= 10x the full-eval step at
    indistinguishable quality-vs-sweeps (A/B across seeds: means within
    0.2%, wins split)."""
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.solvers.sa import SAParams, solve_sa_delta

    inst = synth_cvrp(200, 36, seed=0)
    B, iters = 16384, 8192
    p = SAParams(n_chains=B, n_iters=iters)
    res, warm_s = _timed(lambda: solve_sa_delta(inst, key=1, params=p))
    moves_per_sec = B * iters / warm_s
    # Honest roofline for the delta path (VERDICT round-3 item 8): the
    # algorithmically NECESSARY work per move is ~12 d-table reads plus
    # an O(L) capacity recompute — about 2L+26 flops — so the useful
    # FLOP rate is tiny by design: the kernel's value is deleting the
    # one-hot selection overhead, not saturating the MXU. HBM traffic
    # per move is the presampled param streams (5 x i32/f32) plus the
    # block-amortized state round trip; everything else is VMEM-resident.
    length = inst.n_customers + inst.n_vehicles + 1
    lhat = 1 << (length - 1).bit_length()
    useful_flops = 2.0 * length + 26.0
    bytes_per_move = 5 * 4 + (3 * lhat * 4 * 2 + 6 * 4 * 2) / 512.0
    return {
        "effective_moves_per_sec": round(moves_per_sec, 1),
        "seconds": round(warm_s, 2),
        "cost": round(float(res.breakdown.distance), 1),
        "cap_excess": float(res.breakdown.cap_excess),
        "useful_flops_per_move": round(useful_flops, 1),
        "useful_gflops_per_sec": round(moves_per_sec * useful_flops / 1e9, 2),
        "hbm_bytes_per_move_est": round(bytes_per_move, 1),
        "hbm_gb_per_sec_est": round(moves_per_sec * bytes_per_move / 1e9, 2),
        "hbm_utilization_vs_v5e_819gbs_pct": round(
            100 * moves_per_sec * bytes_per_move / 819e9, 2
        ),
    }


def _family_sa_delta_tw(device):
    """The fused VRPTW delta anneal (kernels.sa_delta_tw; VERDICT
    round-3 item 2): per-position attribute/leg state + in-VMEM
    max-plus lateness recompute per move. Target: >= 5x the full-eval
    TW step at statistically indistinguishable quality."""
    from vrpms_tpu.core.cost import CostWeights
    from vrpms_tpu.io.synth import synth_vrptw
    from vrpms_tpu.solvers.sa import (
        SAParams,
        _delta_supported,
        solve_sa,
        solve_sa_delta,
    )

    w = CostWeights.make()
    inst = synth_vrptw(101, 19, seed=13)
    assert _delta_supported(inst, w, "pallas")
    # PRODUCTION config (VERDICT r4 weak-1: the 5x bar was stated at
    # B=16384 but recorded at B=4096, where launch overhead halves the
    # ratio): 16k chains, a 32-launch schedule (launches pipeline
    # asynchronously in the deadline-free loop, so longer schedules
    # amortize dispatch further). Recorded r5 on v5e at THIS config:
    # 39.6M eff. moves/s, 5.27x the equal-sweeps full-eval step
    # (16-launch runs ranged 4.9-5.8x).
    B, iters = 16384, 16384
    p = SAParams(n_chains=B, n_iters=iters)
    res, warm_s = _timed(lambda: solve_sa_delta(inst, key=1, params=p, weights=w))
    # equal-sweeps full-eval reference for the speedup ratio
    _, full_s = _timed(lambda: solve_sa(inst, key=1, params=p, weights=w))
    # ... and the old B=4096 point for round-over-round continuity
    B2, iters2 = 4096, 4096
    p2 = SAParams(n_chains=B2, n_iters=iters2)
    res2, warm2_s = _timed(
        lambda: solve_sa_delta(inst, key=1, params=p2, weights=w)
    )
    _, full2_s = _timed(lambda: solve_sa(inst, key=1, params=p2, weights=w))
    return {
        "effective_moves_per_sec": round(B * iters / warm_s, 1),
        "seconds": round(warm_s, 2),
        "cost": round(float(res.cost), 1),
        "tw_lateness": round(float(res.breakdown.tw_lateness), 2),
        "cap_excess": float(res.breakdown.cap_excess),
        "speedup_vs_full_eval": round(full_s / warm_s, 2),
        "batch": B,
        "effective_moves_per_sec_b4k": round(B2 * iters2 / warm2_s, 1),
        "speedup_vs_full_eval_b4k": round(full2_s / warm2_s, 2),
        "cost_b4k": round(float(res2.cost), 1),
    }


def _family_n500(device):
    """Scale proof (VERDICT round-2 item 9 / round-3 item 5): the
    X-n502-k39 shape, measured on the path production actually takes.
    The delta kernel's n<=512 gate admits this size, so the family
    reports the DELTA path's effective moves/s at the gate boundary
    (it had only ever been measured at n=200) alongside the raw-scan
    sweep; eval_path names what really ran, with the delta attempt's
    failure disclosed if the kernel refuses the shape."""
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.kernels import sa_eval

    inst = synth_cvrp(502, 39, seed=7)
    length = inst.n_customers + inst.n_vehicles + 1
    nhat = sa_eval._padded_n(inst.n_nodes)
    lhat = sa_eval.padded_length(length, 8)
    b = 2048
    tile = sa_eval._auto_tile(b, nhat, lhat, False)
    path = f"pallas tile_b={tile[0]} chunk={tile[1]}" if tile else "onehot (VMEM refusal)"
    rps, elapsed, best = _throughput(inst, device, n_chains=b, n_iters=50)
    out = {
        "routes_per_sec": round(rps, 1),
        "seconds": round(elapsed, 3),
        "best_cost": round(best, 1),
        "n_nodes": inst.n_nodes,
        "eval_path": path,
    }
    from vrpms_tpu.core.cost import CostWeights
    from vrpms_tpu.solvers.sa import SAParams, _delta_supported, solve_sa_delta

    if _delta_supported(inst, CostWeights.make(), "pallas"):
        try:
            iters = 1024
            p = SAParams(n_chains=b, n_iters=iters)
            res, warm_s = _timed(lambda: solve_sa_delta(inst, key=2, params=p))
            # guard the published number: an id-corrupting regression at
            # this size must show up as an invalid tour, not a silently
            # wrong cost (the class of bug the EXACT precision fix
            # killed — node ids > 256 bf16-truncate under XLA:TPU's
            # default dot precision)
            import numpy as _np

            row = sorted(int(x) for x in _np.asarray(res.giant) if x)
            assert row == list(range(1, inst.n_customers + 1)), (
                "n=502 delta champion is not a valid tour"
            )
            out["delta_moves_per_sec"] = round(b * iters / warm_s, 1)
            out["delta_seconds"] = round(warm_s, 2)
            out["delta_cost"] = round(float(res.breakdown.distance), 1)
            out["delta_cap_excess"] = float(res.breakdown.cap_excess)
        except Exception as e:  # disclose, don't sink the family
            out["delta_error"] = f"{type(e).__name__}: {str(e)[:200]}"
    else:
        out["delta_error"] = "gate refused (n/demands/symmetry)"
    return out


def _family_n1001(device):
    """The X-series top end (X-n1001-k43 shape) through the round-5
    raised delta gate (n<=1024, lhat=2048, tile_b=128): proves the
    fast path holds at the largest size the public series reaches.
    The champion validity assert doubles as the id-exactness check at
    ids 513..1000 (the round-4 bf16-truncation lesson: test exactly
    where the representable range ends)."""
    import numpy as np

    from vrpms_tpu.core.cost import CostWeights
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.solvers.sa import SAParams, _delta_supported, solve_sa_delta

    inst = synth_cvrp(1001, 43, seed=11)
    out = {"n_nodes": inst.n_nodes}
    if not _delta_supported(inst, CostWeights.make(), "pallas"):
        out["delta_error"] = "gate refused (n/demands/symmetry)"
        return out
    b, iters = 1024, 512
    p = SAParams(n_chains=b, n_iters=iters)
    res, warm_s = _timed(lambda: solve_sa_delta(inst, key=2, params=p))
    row = sorted(int(x) for x in np.asarray(res.giant) if x)
    assert row == list(range(1, inst.n_customers + 1)), (
        "n=1001 delta champion is not a valid tour (id corruption?)"
    )
    out["delta_moves_per_sec"] = round(b * iters / warm_s, 1)
    out["delta_seconds"] = round(warm_s, 2)
    out["delta_cost"] = round(float(res.breakdown.distance), 1)
    out["delta_cap_excess"] = float(res.breakdown.cap_excess)
    return out


def _family_quality(device):
    """Cost-at-10 s on synth X-n200 — the north-star budget metric
    (BASELINE.json: <=2% of best-known in <10 s on one chip), measured
    at steady state (one 2 s warm solve loads/compiles the programs,
    then one clean 10 s-budget ILS solve). Reported relative to the
    123 s round-1 record (36803)."""
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.solvers.ils import ILSParams, solve_ils
    from vrpms_tpu.solvers.sa import SAParams

    inst = jax.device_put(synth_cvrp(200, 36, seed=0), device)
    rounds = 9
    p = ILSParams.from_budget(
        rounds, SAParams(n_chains=4096, n_iters=0), rounds * 1536, pool=32
    )
    # warm EVERY program the measured run needs (anneal block, polish,
    # exact eval, ruin reseed): two full small rounds, no deadline (a
    # deadline-truncated warm run never reaches the reseed)
    solve_ils(
        inst,
        key=99,
        params=ILSParams.from_budget(
            2, SAParams(n_chains=4096, n_iters=0), 2 * 512, pool=32
        ),
    )
    # ... and the rate-fitted SHRUNK block shapes (run_blocked trims the
    # final block to 128-multiples): uncompiled, each costs a one-time
    # tunnel compile that would masquerade as budget overshoot. The
    # shared startup warm (also run by service.warmup and the ladder
    # budget path) compiles every block shape and persists sweep rates.
    from vrpms_tpu.solvers.sa import warm_anneal_blocks

    warm_anneal_blocks(inst, 4096)
    budget = 10.0
    t0 = time.perf_counter()
    res = solve_ils(inst, key=0, params=p, deadline_s=budget)
    el = time.perf_counter() - t0
    cost = float(res.breakdown.distance)
    cap_excess = float(res.breakdown.cap_excess)
    # a headline quality family that silently reported an infeasible
    # champion would flatter itself — surface feasibility and budget
    # fidelity (VERDICT round-2 items 4/6) right in the artifact
    assert cap_excess == 0.0, f"infeasible champion: cap_excess={cap_excess}"
    return {
        "cost_at_10s": round(cost, 1),
        "solve_seconds": round(el, 2),
        "budget_s": budget,
        "overshoot_pct": round(100 * (el / budget - 1), 1),
        "cap_excess": cap_excess,
        "vs_round1_123s_record_pct": round(100 * (cost / 36803.0 - 1), 2),
    }


def _budget_ils(inst, chains: int, budget: float, key: int = 0,
                mode: str = "auto"):
    """Warm + one clean budgeted ILS solve -> (res, wall_seconds).

    `mode` must be "gather" when the target device is the host CPU
    inside a TPU process: "auto" resolves by default backend (tpu ->
    pallas), and Mosaic kernels only interpret on CPU."""
    from vrpms_tpu.solvers.ils import ILSParams, solve_ils
    from vrpms_tpu.solvers.sa import SAParams, warm_anneal_blocks

    rounds = 9
    p = ILSParams.from_budget(
        rounds, SAParams(n_chains=chains, n_iters=0), rounds * 1536, pool=32
    )
    solve_ils(
        inst,
        key=99,
        params=ILSParams.from_budget(
            2, SAParams(n_chains=chains, n_iters=0), 2 * 512, pool=32
        ),
        mode=mode,
    )
    # warm the deadline-block shapes in the SAME eval mode the timed
    # solve will run, or the first timed solve pays compile against its
    # budget (that tax would bias the CPU-vs-TPU cost ratio)
    warm_anneal_blocks(inst, chains, mode=mode)
    t0 = time.perf_counter()
    res = solve_ils(inst, key=key, params=p, deadline_s=budget, mode=mode)
    return res, time.perf_counter() - t0


def _family_real(device):
    """TRUE gap-to-BKS at a 10 s budget on the REAL embedded public
    instances (VERDICT r4 missing-1/2: the flagship quality claim had
    only ever been measured against the build's own records on
    synthetic data). Every gap below is against a published literature
    value a user can check, on data certified by the fixture
    cross-check trail (io/fixtures.py docstring, BASELINE.md)."""
    from vrpms_tpu.io.fixtures import FIXTURES, load_fixture
    from vrpms_tpu.io.metrics import gap_percent

    budget = 10.0
    out = {}
    from vrpms_tpu.io.fixtures import FIXTURES_XL

    for name, chains in (
        ("A-n32-k5", 4096), ("E-n51-k5", 4096), ("R101", 8192)
    ):
        if name not in FIXTURES and name not in FIXTURES_XL:
            continue
        inst, meta = load_fixture(name)
        inst = jax.device_put(inst, device)
        pool_best = None
        if meta["kind"] == "vrptw":
            # tight-TW instances take the TW delta anneal directly: the
            # ILS pipeline's polish ranks by distance deltas and cannot
            # repair lateness, so its rounds waste the budget (R101 at
            # 10 s: lateness 138 via ILS vs 0.2 via one B=16k anneal
            # with the TW-aware candidate lists — round-5 measurement)
            from vrpms_tpu.core.cost import best_feasible_pool
            from vrpms_tpu.solvers.sa import (
                SAParams,
                solve_sa_delta,
                warm_anneal_blocks,
            )

            p = SAParams(n_chains=16384, n_iters=1_000_000)
            # warm_anneal_blocks routes through solve_sa_delta with the
            # deadline path engaged, so every shrunk block shape
            # compiles AND the sweep-rate cache seeds before the timed
            # solve; one tiny pooled solve warms the elite-gather
            # program too
            warm_anneal_blocks(inst, 16384)
            solve_sa_delta(
                inst, key=99,
                params=SAParams(n_chains=16384, n_iters=512), pool=32,
            )
            t0 = time.perf_counter()
            # key=1 matches the ladder's config-5 line; the solve-trail
            # record documents the seed sensitivity at this budget
            res = solve_sa_delta(
                inst, key=1, params=p, deadline_s=budget, pool=32
            )
            jax.block_until_ready(res.cost)
            el = time.perf_counter() - t0
            pool_best = best_feasible_pool(res.pool, inst)
        else:
            res, el = _budget_ils(inst, chains, budget)
        dist = float(res.breakdown.distance)
        late = float(res.breakdown.tw_lateness)
        cape = float(res.breakdown.cap_excess)
        entry = {
            "bks": meta["bks"],
            "cost_at_10s": round(dist, 1),
            "solve_seconds": round(el, 2),
            "cap_excess": cape,
            "tw_lateness": round(late, 2),
        }
        # a gap against BKS is only meaningful for a FEASIBLE solution;
        # the cost-optimal champion may carry epsilon lateness while a
        # feasible elite sits in the pool — the gap line takes the best
        # FEASIBLE tour found
        if cape == 0.0 and late == 0.0:
            entry["gap_to_bks_pct"] = round(gap_percent(dist, meta["bks"]), 2)
        elif pool_best is not None:
            entry["feasible_pool_dist"] = round(pool_best, 1)
            entry["gap_to_bks_pct"] = round(
                gap_percent(pool_best, meta["bks"]), 2
            )
        else:
            entry["gap_to_bks_pct"] = None
        out[name] = entry
    return out


def main():
    from vrpms_tpu.utils import enable_compile_cache

    enable_compile_cache()
    dev, platform = _pick_device()
    print(f"[bench] device: {dev} ({platform})", file=sys.stderr)

    from vrpms_tpu.io.synth import synth_cvrp

    inst = synth_cvrp(200, 36, seed=0)

    cpu_baseline = "measured"
    if platform == "cpu":
        value, elapsed, best = _throughput(inst, dev, n_chains=256, n_iters=200)
        cpu_rps = value
    else:
        # 16k chains: throughput saturates ~16% above the 4k-chain point
        # (3.5M vs 3.0M routes/s on v5e) and more parallel chains also
        # help search quality; VMEM still fits via the kernel's autotiler
        value, elapsed, best = _throughput(inst, dev, n_chains=16384, n_iters=1000)
        try:
            cpu_dev = jax.devices("cpu")[0]
            cpu_rps, _, _ = _throughput(inst, cpu_dev, n_chains=256, n_iters=100)
        except Exception as e:  # CPU fallback baseline unavailable
            print(f"[bench] cpu baseline failed: {e}", file=sys.stderr)
            # vs_baseline degenerates to 1.0; the flag below keeps a
            # fabricated ratio distinguishable from a real measurement
            cpu_rps = value
            cpu_baseline = "unavailable"

    families = {}
    fam_fns = {
        "ga": _family_ga,
        "aco": _family_aco,
        "vrptw_onehot": _family_vrptw,
        "delta_polish": _family_polish,
        "time_dependent": _family_td,
        "scale_n502": _family_n500,
    }
    if platform != "cpu":
        # the 4096-chain ILS budget solve is minutes per block on CPU
        fam_fns["quality_at_10s"] = _family_quality
        fam_fns["sa_delta"] = _family_sa_delta  # Mosaic kernels: TPU only
        fam_fns["sa_delta_tw"] = _family_sa_delta_tw
        fam_fns["real_instances"] = _family_real  # headline source
        fam_fns["scale_n1001"] = _family_n1001
    for fam, fn in fam_fns.items():
        try:
            t0 = time.perf_counter()
            families[fam] = fn(dev)
            print(
                f"[bench] {fam}: {families[fam]} "
                f"({time.perf_counter() - t0:.1f}s incl. compile)",
                file=sys.stderr,
            )
        except Exception as e:  # one family must not sink the headline
            print(f"[bench] {fam} FAILED: {e}", file=sys.stderr)
            families[fam] = {"error": f"{type(e).__name__}: {e}"}

    # Headline (VERDICT r4 weak-2/next-9: the raw-scan sweep had been
    # flat for four rounds and nothing in production runs it alone):
    # the TRUE gap-to-BKS at the 10 s budget on the largest REAL
    # embedded CVRP instance — the metric the framework actually
    # optimizes, on data with a published answer. vs_baseline is the
    # same-budget CPU-vs-TPU COST ratio on that instance (>1 = the
    # accelerator finds better tours in the same wall-clock); the raw
    # sweep continues as the families.raw_sweep line for continuity.
    real = families.get("real_instances") or {}
    head = real.get("E-n51-k5") or {}
    head_gap = head.get("gap_to_bks_pct")
    vs_b = None
    if platform != "cpu" and head.get("cost_at_10s"):
        try:
            from vrpms_tpu.io.fixtures import load_fixture

            cpu_dev = jax.devices("cpu")[0]
            inst_c, _ = load_fixture("E-n51-k5")
            inst_c = jax.device_put(inst_c, cpu_dev)
            with jax.default_device(cpu_dev):
                res_c, _el = _budget_ils(inst_c, 256, 10.0, mode="gather")
            cpu_cost = float(res_c.breakdown.distance)
            vs_b = round(cpu_cost / head["cost_at_10s"], 3)
            head["cpu_cost_at_10s"] = round(cpu_cost, 1)
        except Exception as e:
            print(f"[bench] cpu quality baseline failed: {e}", file=sys.stderr)

    # -999.0 = "headline unavailable" (CPU run, family error, or an
    # infeasible 10 s champion): unmistakable, unlike a plausible small
    # negative gap (code review r5)
    result = {
        "metric": "true_gap_to_bks_pct_at_10s",
        "value": head_gap if head_gap is not None else -999.0,
        "unit": "% over BKS 521 (E-n51-k5, real, published optimum)",
        "vs_baseline": vs_b if vs_b is not None else -999.0,
        "device": platform,
        "instance": "E-n51-k5 (real CVRPLIB; families.real_instances for the rest)",
        "best_cost": head.get("cost_at_10s", round(best, 1)),
        "measure_seconds": head.get("solve_seconds", round(elapsed, 3)),
        "families": families,
    }
    families["raw_sweep"] = {
        "metric": "candidate_routes_per_sec_per_chip",
        "routes_per_sec": round(value, 1),
        "vs_cpu": round(value / cpu_rps, 3),
        "instance": "synth-X-n200-k36",
        "best_cost": round(best, 1),
        "seconds": round(elapsed, 3),
        "cpu_routes_per_sec": round(cpu_rps, 1),
        "cpu_baseline": cpu_baseline,
    }
    if platform != "cpu":
        rs = families["raw_sweep"]
        # Roofline (VERDICT round-3 item 8: make every basis explicit).
        # The one-hot/Pallas objective EXECUTES ~2*L*N_pad^2 bf16 MACs
        # per candidate route (N padded to the 256 lane tile) — real MXU
        # work, but mostly one-hot *selection* overhead rather than
        # algorithmically necessary math (the delta path deletes exactly
        # that). So the MFU figure is executed-MAC utilization on the
        # one-hot basis, NOT useful-work efficiency; the useful-work
        # numbers beside it are the defensible ones (2L flops per route:
        # L distance adds + L demand adds).
        length = inst.n_customers + inst.n_vehicles + 1
        flops_per_route = 2.0 * length * 256 * 256
        achieved = value * flops_per_route
        v5e_bf16_peak = 197e12
        rs["onehot_tflops_executed_est"] = round(achieved / 1e12, 1)
        rs["mfu_onehot_basis_pct"] = round(100 * achieved / v5e_bf16_peak, 1)
        useful = 2.0 * length
        lhat_b = 1 << (length - 1).bit_length()
        rs["useful_flops_per_route"] = useful
        rs["useful_gflops_per_sec"] = round(value * useful / 1e9, 2)
        # HBM per route: the (L-hat) i32 tour column in, the f32 cost out
        # (one-hot intermediates stay in VMEM in the fused kernel)
        bytes_per_route = lhat_b * 4 + 4
        rs["hbm_gb_per_sec_est"] = round(value * bytes_per_route / 1e9, 2)
        rs["hbm_utilization_vs_v5e_819gbs_pct"] = round(
            100 * value * bytes_per_route / 819e9, 2
        )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
