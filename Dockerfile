# Deploy recipe: the self-hosted equivalent of the reference's Vercel
# plane (reference vercel.json + README.md:69-72). Serves the 9-endpoint
# contract via service.app on :8080.
#
#   docker build -t vrpms-tpu .
#   docker run -p 8080:8080 -e VRPMS_STORE=memory vrpms-tpu
#
# For TPU hosts, base on a TPU-enabled JAX image instead and install
# jax[tpu]; the service code is identical (backend selection is
# runtime). SUPABASE_URL/SUPABASE_KEY (or a mounted .env) switch
# persistence to the hosted store; VRPMS_WARMUP pre-traces expected
# instance shapes at startup so first requests answer at steady-state
# latency; the XLA compile cache persists under /cache across restarts
# when mounted.

FROM python:3.12-slim

WORKDIR /app
COPY requirements.txt .
RUN pip install --no-cache-dir -r requirements.txt

COPY vrpms_tpu/ vrpms_tpu/
COPY service/ service/
COPY store/ store/
COPY benchmarks/ benchmarks/
COPY pyproject.toml .

ENV PYTHONPATH=/app \
    VRPMS_COMPILE_CACHE=/cache/xla
VOLUME ["/cache"]

EXPOSE 8080
CMD ["python", "-m", "service.app", "--port", "8080"]
