"""In-memory store: the fake database for tests and local serving.

A process-wide table dict mirrors the reference's Supabase tables
(locations / durations / solutions — reference api/database.py:28,40,80)
and a token registry stands in for JWT auth: a token maps to an email,
which becomes the solution's `owner` exactly like the reference derives
it from the JWT session (reference api/database.py:54-55).

Seed programmatically (seed_locations / seed_durations / register_token)
or from a JSON fixture file via VRPMS_FIXTURES:

    {"locations": {"key": [...]},
     "durations": {"key": [[...]]},
     "tokens": {"token": "user@example.com"}}
"""

from __future__ import annotations

import json
import os
import threading

from store.base import (
    Database,
    DatabaseTSP,
    DatabaseVRP,
    cache_cap,
    notify_cache_evictions,
)

_lock = threading.Lock()
_tables: dict = {
    "locations": {},
    "durations": {},
    "solutions": [],
    "warmstarts": {},
    "jobs": {},
    "solution_cache": {},
}
_tokens: dict = {}
_fixtures_loaded = False


def reset():
    with _lock:
        _tables["locations"].clear()
        _tables["durations"].clear()
        _tables["solutions"].clear()
        _tables["warmstarts"].clear()
        _tables["jobs"].clear()
        _tables["solution_cache"].clear()
        _tokens.clear()
        global _fixtures_loaded
        _fixtures_loaded = False


def seed_locations(key, locations: list):
    with _lock:
        _tables["locations"][str(key)] = {"id": key, "locations": locations}


def seed_durations(key, matrix: list):
    with _lock:
        _tables["durations"][str(key)] = {"id": key, "matrix": matrix}


def register_token(token: str, email: str):
    with _lock:
        _tokens[token] = email


def saved_solutions() -> list:
    return list(_tables["solutions"])


_fixtures_lock = threading.Lock()


def _ensure_fixtures():
    global _fixtures_loaded
    if _fixtures_loaded:
        return
    with _fixtures_lock:  # serialize first loads; flag only set on success
        if _fixtures_loaded:
            return
        path = os.environ.get("VRPMS_FIXTURES")
        if path:
            with open(path) as f:
                fx = json.load(f)
            for key, locs in fx.get("locations", {}).items():
                seed_locations(key, locs)
            for key, matrix in fx.get("durations", {}).items():
                seed_durations(key, matrix)
            for token, email in fx.get("tokens", {}).items():
                register_token(token, email)
        _fixtures_loaded = True


class _InMemoryMixin(Database):
    def _fetch_row(self, table: str, row_id):
        _ensure_fixtures()
        return _tables[table].get(str(row_id))

    def _insert_solution(self, data: dict):
        with _lock:
            _tables["solutions"].append(data)
        return data

    def _owner_email(self):
        _ensure_fixtures()
        return _tokens.get(self.auth) if self.auth else None

    def _fetch_warmstart(self, owner, name):
        return _tables["warmstarts"].get((owner, str(name)))

    # retained job records: dicts preserve insertion order, so eviction
    # below drops the OLDEST job first. Bounds the jobs table for a
    # long-lived service (every async request writes a record holding
    # its full result; unbounded it grows with request count forever).
    MAX_JOBS = 10_000

    def _fetch_job(self, job_id):
        return _tables["jobs"].get(str(job_id))

    def _upsert_job(self, job_id, record: dict):
        with _lock:
            jobs = _tables["jobs"]
            jobs.pop(str(job_id), None)  # refresh insertion order
            jobs[str(job_id)] = {"id": job_id, "record": record}
            while len(jobs) > self.MAX_JOBS:
                jobs.pop(next(iter(jobs)))

    # -- solution cache: LRU-bounded in-memory tier -------------------------
    # Insertion order is recency: writes re-insert and a keyed read
    # refreshes, so eviction drops the least-recently-USED entry, not
    # merely the oldest-written. A family SCAN deliberately does not
    # refresh — scanning is not using, and a large family's misses must
    # not evict other entries' genuinely hot rows; the one row a scan's
    # winner actually seeds from is re-read by key (service.cache) and
    # refreshes there. The cap re-reads VRPMS_CACHE per upsert (tests
    # and live tuning change it at runtime).
    def _fetch_cache_family(self, family):
        with _lock:
            return [
                r for r in _tables["solution_cache"].values()
                if r["family"] == family
            ]

    def _fetch_cached_solution(self, key):
        with _lock:
            cache = _tables["solution_cache"]
            row = cache.pop(str(key), None)
            if row is None:
                return None
            cache[str(key)] = row  # refresh recency
            return row

    def _upsert_cached_solution(self, key, family, entry: dict):
        cap = cache_cap()
        if cap <= 0:
            # VRPMS_CACHE flipped to off after this request attached:
            # skip the write rather than clamp the cap to 1, which
            # would mass-evict every existing entry
            return
        evicted = 0
        with _lock:
            cache = _tables["solution_cache"]
            cache.pop(str(key), None)  # refresh insertion order
            cache[str(key)] = {"key": key, "family": family, "entry": entry}
            while len(cache) > cap:
                cache.pop(next(iter(cache)))
                evicted += 1
        notify_cache_evictions(evicted)

    def _upsert_warmstart(self, owner, name, state: dict):
        with _lock:
            _tables["warmstarts"][(owner, str(name))] = {
                "owner": owner,
                "name": name,
                "state": state,
            }

    def _upsert_warmstart_guarded(self, owner, name, state, better_than):
        # Atomic keep-best: fetch, compare and write under the table
        # lock so concurrent solves can't regress the stored best.
        with _lock:
            if better_than is not None:
                row = _tables["warmstarts"].get((owner, str(name)))
                prev = None if row is None else row.get("state")
                if prev is not None and not better_than(prev):
                    return False
            _tables["warmstarts"][(owner, str(name))] = {
                "owner": owner,
                "name": name,
                "state": state,
            }
        return True


class InMemoryDatabaseVRP(_InMemoryMixin, DatabaseVRP):
    pass


class InMemoryDatabaseTSP(_InMemoryMixin, DatabaseTSP):
    pass
