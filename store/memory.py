"""In-memory store: the fake database for tests and local serving.

A process-wide table dict mirrors the reference's Supabase tables
(locations / durations / solutions — reference api/database.py:28,40,80)
and a token registry stands in for JWT auth: a token maps to an email,
which becomes the solution's `owner` exactly like the reference derives
it from the JWT session (reference api/database.py:54-55).

Seed programmatically (seed_locations / seed_durations / register_token)
or from a JSON fixture file via VRPMS_FIXTURES:

    {"locations": {"key": [...]},
     "durations": {"key": [[...]]},
     "tokens": {"token": "user@example.com"}}
"""

from __future__ import annotations

import json
import threading

import time

from vrpms_tpu import config
from store.base import (
    Database,
    DatabaseTSP,
    DatabaseVRP,
    JobQueueStore,
    Q_LEASED,
    Q_QUEUED,
    cache_cap,
    notify_cache_evictions,
)

_lock = threading.Lock()
_tables: dict = {  # guarded-by: _lock
    "locations": {},
    "durations": {},
    "solutions": [],
    "warmstarts": {},
    "jobs": {},
    "solution_cache": {},
    "job_queue": {},
    "replicas": {},
    "trace_spans": {},
    "flight_records": {},
    "checkpoints": {},
    "subscriptions": {},
}
_tokens: dict = {}  # guarded-by: _lock
_fixtures_loaded = False  # guarded-by: _fixtures_lock


def reset():
    with _lock:
        _tables["locations"].clear()
        _tables["durations"].clear()
        _tables["solutions"].clear()
        _tables["warmstarts"].clear()
        _tables["jobs"].clear()
        _tables["solution_cache"].clear()
        _tables["job_queue"].clear()
        _tables["replicas"].clear()
        _tables["trace_spans"].clear()
        _tables["flight_records"].clear()
        _tables["checkpoints"].clear()
        _tables["subscriptions"].clear()
        _tokens.clear()
    global _fixtures_loaded
    with _fixtures_lock:
        _fixtures_loaded = False


def seed_locations(key, locations: list):
    with _lock:
        _tables["locations"][str(key)] = {"id": key, "locations": locations}


def seed_durations(key, matrix: list):
    with _lock:
        _tables["durations"][str(key)] = {"id": key, "matrix": matrix}


def register_token(token: str, email: str):
    with _lock:
        _tokens[token] = email


def saved_solutions() -> list:
    with _lock:
        return list(_tables["solutions"])


_fixtures_lock = threading.Lock()


def _ensure_fixtures():
    global _fixtures_loaded
    if _fixtures_loaded:  # vrpms-lint: disable=lock-discipline (double-checked fast path; the locked re-check below arbitrates, and the flag only ever flips under _fixtures_lock)
        return
    with _fixtures_lock:  # serialize first loads; flag only set on success
        if _fixtures_loaded:
            return
        path = config.get("VRPMS_FIXTURES")
        if path:
            with open(path) as f:
                fx = json.load(f)
            for key, locs in fx.get("locations", {}).items():
                seed_locations(key, locs)
            for key, matrix in fx.get("durations", {}).items():
                seed_durations(key, matrix)
            for token, email in fx.get("tokens", {}).items():
                register_token(token, email)
        _fixtures_loaded = True


class _InMemoryMixin(Database):
    def _fetch_row(self, table: str, row_id):
        _ensure_fixtures()
        with _lock:
            return _tables[table].get(str(row_id))

    def _insert_solution(self, data: dict):
        with _lock:
            _tables["solutions"].append(data)
        return data

    def _owner_email(self):
        _ensure_fixtures()
        with _lock:
            return _tokens.get(self.auth) if self.auth else None

    def _fetch_warmstart(self, owner, name):
        with _lock:
            return _tables["warmstarts"].get((owner, str(name)))

    # retained job records: dicts preserve insertion order, so eviction
    # below drops the OLDEST job first. Bounds the jobs table for a
    # long-lived service (every async request writes a record holding
    # its full result; unbounded it grows with request count forever).
    MAX_JOBS = 10_000

    def _fetch_job(self, job_id):
        with _lock:
            return _tables["jobs"].get(str(job_id))

    def _upsert_job(self, job_id, record: dict):
        with _lock:
            jobs = _tables["jobs"]
            jobs.pop(str(job_id), None)  # refresh insertion order
            jobs[str(job_id)] = {"id": job_id, "record": record}
            while len(jobs) > self.MAX_JOBS:
                jobs.pop(next(iter(jobs)))

    # -- solution cache: LRU-bounded in-memory tier -------------------------
    # Insertion order is recency: writes re-insert and a keyed read
    # refreshes, so eviction drops the least-recently-USED entry, not
    # merely the oldest-written. A family SCAN deliberately does not
    # refresh — scanning is not using, and a large family's misses must
    # not evict other entries' genuinely hot rows; the one row a scan's
    # winner actually seeds from is re-read by key (service.cache) and
    # refreshes there. The cap re-reads VRPMS_CACHE per upsert (tests
    # and live tuning change it at runtime).
    def _fetch_cache_family(self, family):
        with _lock:
            return [
                r for r in _tables["solution_cache"].values()
                if r["family"] == family
            ]

    def _fetch_cached_solution(self, key):
        with _lock:
            cache = _tables["solution_cache"]
            row = cache.pop(str(key), None)
            if row is None:
                return None
            cache[str(key)] = row  # refresh recency
            return row

    def _upsert_cached_solution(self, key, family, entry: dict):
        cap = cache_cap()
        if cap <= 0:
            # VRPMS_CACHE flipped to off after this request attached:
            # skip the write rather than clamp the cap to 1, which
            # would mass-evict every existing entry
            return
        evicted = 0
        with _lock:
            cache = _tables["solution_cache"]
            cache.pop(str(key), None)  # refresh insertion order
            cache[str(key)] = {"key": key, "family": family, "entry": entry}
            while len(cache) > cap:
                cache.pop(next(iter(cache)))
                evicted += 1
        notify_cache_evictions(evicted)

    # -- durable trace export: bounded per-(trace, replica) rows ------------
    # Insertion order is write recency; eviction drops the oldest-
    # written row first (exported traces are debug evidence, not
    # durable state — the Supabase backend pairs its table with a
    # retention job instead, see store/schema.sql).
    MAX_TRACE_ROWS = 2048

    def _put_trace_rows(self, rows: list):
        with _lock:
            table = _tables["trace_spans"]
            for row in rows:
                key = (str(row.get("trace_id")), str(row.get("replica")))
                table.pop(key, None)  # refresh insertion order
                table[key] = dict(row)
            while len(table) > self.MAX_TRACE_ROWS:
                table.pop(next(iter(table)))

    def _fetch_trace_rows(self, trace_id):
        with _lock:
            return [
                dict(row)
                for (tid, _rep), row in _tables["trace_spans"].items()
                if tid == str(trace_id)
            ]

    def _list_trace_rows(self, limit):
        with _lock:
            rows = list(_tables["trace_spans"].values())
        # newest-written first, summary columns only (the doc can be
        # hundreds of KB across a deep list — the slim-scan rule the
        # cache family reads follow)
        return [
            {k: row.get(k) for k in (
                "trace_id", "replica", "started_at", "duration_ms",
                "status", "root", "spans",
            )}
            for row in reversed(rows[-max(1, int(limit)):])
        ]

    # -- durable flight records: bounded per-(job, replica) rows ------------
    # Same recency discipline as the trace rows: pop-to-refresh keeps
    # insertion order equal to write recency, eviction drops the
    # oldest-written row first (flight records are rollup evidence, not
    # durable state — the Supabase backend pairs its table with a
    # retention job instead, see store/schema.sql).
    MAX_FLIGHT_ROWS = 2048

    def _put_flight_rows(self, rows: list):
        with _lock:
            table = _tables["flight_records"]
            for row in rows:
                key = (str(row.get("job_id")), str(row.get("replica")))
                table.pop(key, None)  # refresh insertion order
                table[key] = dict(row)
            while len(table) > self.MAX_FLIGHT_ROWS:
                table.pop(next(iter(table)))

    def _fetch_flight_rows(self, limit):
        with _lock:
            rows = list(_tables["flight_records"].values())
        # newest-written first (the rollup wants the fresh tail)
        return [dict(row) for row in reversed(rows[-max(1, int(limit)):])]

    # -- durable solve checkpoints: bounded per-(job, attempt) rows ---------
    # Insertion order is write recency; eviction drops the oldest-
    # written row first (checkpoints are crash-recovery state for LIVE
    # jobs, not an archive — the Supabase backend pairs its table with
    # a retention sweep instead, see store/schema.sql).
    MAX_CHECKPOINTS = 2048

    def _fetch_checkpoint(self, job_id):
        with _lock:
            rows = [
                row
                for (jid, _att), row in _tables["checkpoints"].items()
                if jid == str(job_id)
            ]
        if not rows:
            return None
        return dict(max(rows, key=lambda r: int(r.get("attempt") or 0)))

    def _upsert_checkpoint(self, job_id, attempt, state: dict):
        with _lock:
            table = _tables["checkpoints"]
            key = (str(job_id), int(attempt))
            table.pop(key, None)  # refresh insertion order
            table[key] = {
                "job_id": str(job_id),
                "attempt": int(attempt),
                "state": state,
            }
            while len(table) > self.MAX_CHECKPOINTS:
                table.pop(next(iter(table)))

    def _delete_checkpoint(self, job_id):
        with _lock:
            table = _tables["checkpoints"]
            for key in [k for k in table if k[0] == str(job_id)]:
                del table[key]

    # -- standing subscriptions: bounded per-id control-plane docs ----------
    # Same recency discipline as checkpoints: pop-to-refresh keeps
    # insertion order equal to write recency, eviction drops the
    # oldest-written doc (a standing fleet of thousands fits; an
    # unbounded one is a leak, not a workload).
    MAX_SUBSCRIPTIONS = 2048

    def _fetch_subscription(self, sub_id):
        with _lock:
            row = _tables["subscriptions"].get(str(sub_id))
            return None if row is None else dict(row)

    def _list_subscriptions(self):
        with _lock:
            return [dict(row) for row in _tables["subscriptions"].values()]

    def _upsert_subscription(self, sub_id, doc: dict):
        with _lock:
            table = _tables["subscriptions"]
            key = str(sub_id)
            table.pop(key, None)  # refresh insertion order
            table[key] = {"id": key, "doc": doc}
            while len(table) > self.MAX_SUBSCRIPTIONS:
                table.pop(next(iter(table)))

    def _delete_subscription(self, sub_id):
        with _lock:
            _tables["subscriptions"].pop(str(sub_id), None)

    def _upsert_warmstart(self, owner, name, state: dict):
        with _lock:
            _tables["warmstarts"][(owner, str(name))] = {
                "owner": owner,
                "name": name,
                "state": state,
            }

    def _upsert_warmstart_guarded(self, owner, name, state, better_than):
        # Atomic keep-best: fetch, compare and write under the table
        # lock so concurrent solves can't regress the stored best.
        with _lock:
            if better_than is not None:
                row = _tables["warmstarts"].get((owner, str(name)))
                prev = None if row is None else row.get("state")
                if prev is not None and not better_than(prev):
                    return False
            _tables["warmstarts"][(owner, str(name))] = {
                "owner": owner,
                "name": name,
                "state": state,
            }
        return True


class InMemoryDatabaseVRP(_InMemoryMixin, DatabaseVRP):
    pass


class InMemoryDatabaseTSP(_InMemoryMixin, DatabaseTSP):
    pass


class InMemoryJobQueue(JobQueueStore):
    """Shared-queue backend on the process-wide tables: every in-process
    replica (tests, the multi-replica bench) sees the SAME queue, and
    the one table lock makes each claim/reclaim a single atomic
    conditional update — the reference semantics the Supabase backend's
    conditional UPDATEs must match. Dicts preserve insertion order, so
    FIFO claim order falls out of iteration — and QoS claim order
    (class rank, then EDF deadline, then arrival) falls out of a
    stable sort over it using the entries' own ordering fields, which
    all default to the FIFO-neutral values when absent (VRPMS_QOS=off
    writes none, so off-path claims are bit-identical to pre-QoS)."""

    def _rows_locked(self) -> dict:
        return _tables["job_queue"]

    @staticmethod
    def _in_slots(slot, slots) -> bool:
        if slots is None:
            return True
        return any(lo <= slot < hi for lo, hi in slots)

    def _queued_ordered_locked(self, slots=None) -> list:
        """QUEUED rows in claim order: class rank first, EDF within
        class, arrival-stable (qos.entry_order_key over the insertion
        order — all-default entries come back in pure FIFO order)."""
        from vrpms_tpu.sched import qos

        rows = [
            row
            for row in self._rows_locked().values()
            if row["state"] == Q_QUEUED
            and self._in_slots(row.get("slot", 0), slots)
        ]
        order = sorted(
            range(len(rows)),
            key=lambda i: (qos.entry_order_key(rows[i]), i),
        )
        return [rows[i] for i in order]

    def enqueue(self, entry: dict) -> None:
        row = dict(entry)
        row.setdefault("state", Q_QUEUED)
        row.setdefault("attempt", 0)
        row.setdefault("slot", 0)
        row.setdefault("submitted_at", time.time())
        row["lease_owner"] = None
        row["lease_expires_at"] = None
        with _lock:
            self._rows_locked()[str(row["id"])] = row

    def claim(self, owner: str, lease_s: float, slots=None) -> dict | None:
        from vrpms_tpu.sched import qos

        now = time.time()
        with _lock:
            # single-row claim: a stable min (arrival tie-break) gives
            # the same winner as the full claim-order sort at O(n) —
            # claim polls run per VRPMS_QUEUE_POLL_MS tick under the
            # one table lock, so no whole-backlog sort here
            rows = [
                row
                for row in self._rows_locked().values()
                if row["state"] == Q_QUEUED
                and self._in_slots(row.get("slot", 0), slots)
            ]
            if not rows:
                return None
            best = min(
                range(len(rows)),
                key=lambda i: (qos.entry_order_key(rows[i]), i),
            )
            row = rows[best]
            row["state"] = Q_LEASED
            row["lease_owner"] = owner
            row["lease_expires_at"] = now + lease_s
            return dict(row)

    def claim_batch(self, owner: str, lease_s: float, k: int,
                    slots=None) -> list:
        """Claim-K-matching under the one table lock: take the FIRST
        QUEUED entry in claim order (class rank, EDF, arrival) within
        `slots`, then fill up to k-1 more QUEUED entries sharing its
        bucket — same-class mates first (their claim order), lower
        classes as free riders (sched.qos.select_mates; entries
        without QoS fields reduce to the old oldest-first sweep) — all
        leased in this one critical section, which is exactly the
        atomicity the Supabase backend's single conditional UPDATE
        provides."""
        from vrpms_tpu.sched import qos

        if k <= 0:
            return []
        now = time.time()
        taken: list = []
        with _lock:
            # ONE ordered sweep: the leader is the first row passing
            # the slot filter (slots filter the leader only — the
            # original contract), mates are same-bucket rows from the
            # whole queue, already in claim order so select_mates'
            # stable preference applies
            ordered = self._queued_ordered_locked(None)
            leader = next(
                (
                    row for row in ordered
                    if self._in_slots(row.get("slot", 0), slots)
                ),
                None,
            )
            if leader is None:
                return []
            batch = [leader]
            leader_bucket = leader.get("bucket")
            if leader_bucket is not None and k > 1:
                mates = [
                    row
                    for row in ordered
                    if row is not leader
                    and row.get("bucket") == leader_bucket
                ]
                batch += qos.select_mates(
                    leader, mates, k - 1, key=qos.entry_order_key
                )
            for row in batch:
                row["state"] = Q_LEASED
                row["lease_owner"] = owner
                row["lease_expires_at"] = now + lease_s
                taken.append(dict(row))
        return taken

    def depth_by_class(self) -> dict:
        from vrpms_tpu.sched import qos

        out = {name: 0 for name in qos.CLASSES}
        with _lock:
            for row in self._rows_locked().values():
                if row["state"] != Q_QUEUED:
                    continue
                cls = row.get("qos")
                out[cls if cls in qos.RANK else qos.DEFAULT_CLASS] += 1
        return out

    def tenant_depths(self) -> dict:
        depths: dict = {}
        with _lock:
            for row in self._rows_locked().values():
                tenant = row.get("tenant")
                if tenant:
                    depths[tenant] = depths.get(tenant, 0) + 1
        return depths

    def _owned_locked(self, owner: str, job_id: str):
        row = self._rows_locked().get(str(job_id))
        if row is None or row["state"] != Q_LEASED:
            return None
        if row["lease_owner"] != owner:
            return None
        return row

    def renew(self, owner: str, job_id: str, lease_s: float) -> bool:
        with _lock:
            row = self._owned_locked(owner, job_id)
            if row is None:
                return False
            row["lease_expires_at"] = time.time() + lease_s
            return True

    def ack(self, owner: str, job_id: str) -> bool:
        with _lock:
            row = self._owned_locked(owner, job_id)
            if row is None:
                return False
            del self._rows_locked()[str(job_id)]
            return True

    def nack(self, owner: str, job_id: str, note: dict | None = None) -> bool:
        with _lock:
            row = self._owned_locked(owner, job_id)
            if row is None:
                return False
            row["state"] = Q_QUEUED
            row["lease_owner"] = None
            row["lease_expires_at"] = None
            if note:
                # drain marker: the next claimant's payload carries it
                # (e.g. {"ckpt": true} — a durable checkpoint exists)
                payload = dict(row.get("payload") or {})
                payload.update(note)
                row["payload"] = payload
            return True

    def reclaim_expired(self, max_attempts: int | None = None):
        if max_attempts is None:
            max_attempts = self.MAX_ATTEMPTS
        now = time.time()
        requeued, dead = [], []
        with _lock:
            rows = self._rows_locked()
            for job_id in list(rows):
                row = rows[job_id]
                if row["state"] != Q_LEASED:
                    continue
                if row["lease_expires_at"] is None:
                    continue
                if row["lease_expires_at"] > now:
                    continue
                row["attempt"] = int(row.get("attempt", 0)) + 1
                row["lease_owner"] = None
                row["lease_expires_at"] = None
                if row["attempt"] >= max_attempts:
                    dead.append(dict(rows.pop(job_id)))
                else:
                    row["state"] = Q_QUEUED
                    requeued.append(dict(row))
        return requeued, dead

    def depth(self) -> int:
        with _lock:
            return sum(
                1 for r in self._rows_locked().values() if r["state"] == Q_QUEUED
            )

    def get_entry(self, job_id: str) -> dict | None:
        with _lock:
            row = self._rows_locked().get(str(job_id))
            return None if row is None else dict(row)

    def register_replica(self, replica_id: str, ttl_s: float,
                         info: dict | None = None) -> None:
        with _lock:
            prev = _tables["replicas"].get(replica_id)
            if info is None and isinstance(prev, tuple):
                # a heartbeat without a status doc keeps the last one
                # (mixed fleets: peers predating the info field)
                info = prev[1]
            _tables["replicas"][replica_id] = (time.time() + ttl_s, info)

    def deregister_replica(self, replica_id: str) -> None:
        with _lock:
            _tables["replicas"].pop(replica_id, None)

    @staticmethod
    def _reg_expiry(value) -> float:
        # rows written before the info field was a (expiry, info) tuple
        return value[0] if isinstance(value, tuple) else value

    def replicas(self) -> list[str]:
        now = time.time()
        with _lock:
            reg = _tables["replicas"]
            for rid in [
                r for r, v in reg.items() if self._reg_expiry(v) <= now
            ]:
                del reg[rid]
            return sorted(reg)

    def replica_infos(self) -> dict:
        """{replica_id: last heartbeat status doc} for live replicas —
        the fleet rollup's cross-replica view (GET /api/debug/fleet)."""
        now = time.time()
        with _lock:
            return {
                rid: dict(v[1]) if isinstance(v, tuple) and v[1] else {}
                for rid, v in _tables["replicas"].items()
                if self._reg_expiry(v) > now
            }
