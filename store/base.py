"""The Database seam: reference row shapes, backend-agnostic.

Shapes preserved from the reference (SURVEY.md §2.2 "persistence"):
  locations row:  {'id': ..., 'locations': [...]}   -> returns row['locations']
                  (reference api/database.py:26-36)
  durations row:  {'id': ..., 'matrix': [[...]]}    -> returns row['matrix']
                  (reference api/database.py:38-48)
  VRP solution:   {'name', 'description', 'owner', 'durationMax',
                   'durationSum', 'locations', 'vehicles'}
                  (reference api/database.py:69-77)
  TSP solution:   {'name', 'description', 'owner', 'duration',
                   'locations', 'vehicle'}
                  (reference api/database.py:102-109)

Errors are accumulated into the caller's mutable list as
{'what': ..., 'reason': ...} dicts — the reference's error idiom.
"""

from __future__ import annotations

import sys


class Database:
    """Abstract store. Subclasses implement _fetch_row / _insert_solution
    and _owner_email; the public methods provide the shared error
    envelope semantics."""

    #: True once any call on this instance was served by a degraded-mode
    #: fallback (store.resilient); the service marks the response
    #: `degraded: true`. Plain backends never flip it.
    degraded = False

    def __init__(self, auth=None):
        self.auth = auth

    # -- backend primitives -------------------------------------------------
    def _fetch_row(self, table: str, row_id):
        raise NotImplementedError

    def _insert_solution(self, data: dict):
        raise NotImplementedError

    def _owner_email(self) -> str | None:
        raise NotImplementedError

    def _fetch_warmstart(self, owner: str, name):
        raise NotImplementedError

    def _upsert_warmstart(self, owner: str, name, state: dict):
        raise NotImplementedError

    def _fetch_job(self, job_id: str):
        raise NotImplementedError

    def _upsert_job(self, job_id: str, record: dict):
        raise NotImplementedError

    # -- async job records (scheduler extension) ----------------------------
    # The jobs API (service.jobs) persists each job's lifecycle record
    # through this seam so `GET /api/jobs/{id}` answers from whichever
    # backend is configured — in-process memory for tests/local, Supabase
    # for the hosted deployment (store/schema.sql `jobs`). Job ids are
    # unguessable uuid4 hex, which is the (reference-parity) access
    # control: like unauthenticated solves, job records are not owner-
    # scoped. Writes are best-effort with a stderr warning (a telemetry/
    # bookkeeping failure must never fail the solve itself); reads
    # surface errors into the caller's envelope list.
    def save_job(self, job_id: str, record: dict) -> bool:
        try:
            self._upsert_job(job_id, record)
            return True
        except Exception as exc:
            print(
                f"[store] job write failed ({type(exc).__name__}: {exc}); "
                "job status may be stale — check store/schema.sql",
                file=sys.stderr,
            )
            return False

    def get_job(self, job_id: str, errors) -> dict | None:
        try:
            row = self._fetch_job(job_id)
            return None if row is None else row.get("record")
        except Exception as exc:
            errors += [{"what": "Database read error", "reason": str(exc)}]
            return None

    # -- warm-start checkpoints (framework extension) -----------------------
    # The reference has no computation checkpointing; its closest analog is
    # the ignored/completed dynamic re-solve inputs (SURVEY.md §5
    # "checkpoint/resume"). This seam persists the best-so-far solution
    # keyed by (owner, solutionName) so a re-solve can seed its population
    # from the previous result. Owner scoping mirrors save_solution's auth
    # rule: without an authenticated owner nothing is stored or returned —
    # otherwise tenants could read or clobber each other's checkpoints
    # through a shared solutionName. Best-effort by design: a miss or store
    # failure must never fail a solve.
    def _warmstart_owner(self) -> str | None:
        # Database instances are per-request; cache the owner so a
        # warm-started solve resolves it once, not once per get + save
        # (on Supabase each resolution is an auth network round-trip).
        if not hasattr(self, "_warmstart_owner_cache"):
            try:
                self._warmstart_owner_cache = self._owner_email()
            except Exception:
                self._warmstart_owner_cache = None
        return self._warmstart_owner_cache

    def _warmstart_warn(self, op: str, exc: Exception) -> None:
        # Best-effort must not mean silent: a store/schema problem (e.g.
        # a warmstarts table missing the owner column — see
        # store/schema.sql) would otherwise disable checkpoints with no
        # trace at all.
        print(
            f"[store] warm-start {op} failed ({type(exc).__name__}: {exc}); "
            "continuing without checkpoint — check store/schema.sql",
            file=sys.stderr,
        )

    def get_warmstart(self, name) -> dict | None:
        owner = self._warmstart_owner()
        if not owner:
            return None
        try:
            row = self._fetch_warmstart(owner, name)
            return None if row is None else row.get("state")
        except Exception as exc:
            self._warmstart_warn("read", exc)
            return None

    def save_warmstart(self, name, state: dict, better_than=None) -> bool:
        """Persist a checkpoint; with `better_than`, only if it improves.

        `better_than(prev_state) -> bool` is evaluated against the
        freshly re-fetched stored state immediately before the upsert
        (the in-memory store runs the whole sequence under its table
        lock; remote stores narrow the race window to one round-trip).
        """
        owner = self._warmstart_owner()
        if not owner:
            return False
        try:
            return self._upsert_warmstart_guarded(owner, name, state, better_than)
        except Exception as exc:
            self._warmstart_warn("write", exc)
            return False

    def _upsert_warmstart_guarded(self, owner, name, state, better_than) -> bool:
        if better_than is not None:
            row = self._fetch_warmstart(owner, name)
            prev = None if row is None else row.get("state")
            if prev is not None and not better_than(prev):
                return False
        self._upsert_warmstart(owner, name, state)
        return True

    # -- reference-shaped API ----------------------------------------------
    def get_locations_by_id(self, id, errors):
        try:
            row = self._fetch_row("locations", id)
            if row is None:
                raise Exception(
                    f"No location set found with given id {id}. "
                    "Make sure you are accessing public data or data owned "
                    "by you. Check if your authentication token has expired."
                )
            return row["locations"]
        except Exception as exception:
            errors += [{"what": "Database read error", "reason": str(exception)}]
            return None

    def get_durations_by_id(self, id, errors):
        try:
            row = self._fetch_row("durations", id)
            if row is None:
                raise Exception(
                    f"No duration matrix found with given id {id}. "
                    "Make sure you are accessing public data or data owned "
                    "by you. Check if your authentication token has expired."
                )
            return row["matrix"]
        except Exception as exception:
            errors += [{"what": "Database read error", "reason": str(exception)}]
            return None

    def _save(self, data: dict, errors):
        try:
            email = self._owner_email()
        except Exception as exception:
            # e.g. supabase get_user() raising on an expired token; must
            # surface as the error envelope, not a dropped connection.
            errors += [{"what": "Database auth error", "reason": str(exception)}]
            return None
        if not email:
            errors += [
                {
                    "what": "Not permitted",
                    "reason": "An authentication token is required to save "
                    "solutions to database. Please provide 'auth' with a "
                    "valid JWT token in the request body. If you have "
                    "already provided a token, it has very likely expired.",
                }
            ]
            return None
        data = dict(data, owner=email)
        try:
            return self._insert_solution(data)
        except Exception as exception:
            errors += [{"what": "Database write error", "reason": str(exception)}]
            return None


class DatabaseVRP(Database):
    def save_solution(
        self, name, description, locations, vehicles, duration_max, duration_sum, errors
    ):
        return self._save(
            {
                "name": name,
                "description": description,
                "durationMax": duration_max,
                "durationSum": duration_sum,
                "locations": locations,
                "vehicles": vehicles,
            },
            errors,
        )


class DatabaseTSP(Database):
    def save_solution(self, name, description, locations, vehicle, duration, errors):
        return self._save(
            {
                "name": name,
                "description": description,
                "duration": duration,
                "locations": locations,
                "vehicle": vehicle,
            },
            errors,
        )
