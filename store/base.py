"""The Database seam: reference row shapes, backend-agnostic.

Shapes preserved from the reference (SURVEY.md §2.2 "persistence"):
  locations row:  {'id': ..., 'locations': [...]}   -> returns row['locations']
                  (reference api/database.py:26-36)
  durations row:  {'id': ..., 'matrix': [[...]]}    -> returns row['matrix']
                  (reference api/database.py:38-48)
  VRP solution:   {'name', 'description', 'owner', 'durationMax',
                   'durationSum', 'locations', 'vehicles'}
                  (reference api/database.py:69-77)
  TSP solution:   {'name', 'description', 'owner', 'duration',
                   'locations', 'vehicle'}
                  (reference api/database.py:102-109)

Errors are accumulated into the caller's mutable list as
{'what': ..., 'reason': ...} dicts — the reference's error idiom.
"""

from __future__ import annotations

import sys

from vrpms_tpu import config


# --- solution-cache configuration (the VRPMS_CACHE knob) -------------------
# One knob controls the whole content-addressed solution cache
# (service.cache): "off"/"0"/"false"/"no" disables everything, an
# integer sets the in-memory backend's LRU entry cap, anything else
# (including unset) means enabled with the default cap. Read per call —
# tests and embedders toggle the env var at runtime.

DEFAULT_CACHE_CAP = 512


def cache_mode() -> str:
    return config.get("VRPMS_CACHE").strip().lower()


def cache_enabled() -> bool:
    return cache_mode() not in ("off", "0", "false", "no")


def cache_cap(default: int = DEFAULT_CACHE_CAP) -> int:
    """In-memory cache tier entry cap (0 = cache disabled)."""
    mode = cache_mode()
    if not cache_enabled():
        return 0
    try:
        return max(1, int(mode))
    except ValueError:
        return default


# Eviction observer seam (service.obs wires a Prometheus counter in;
# the store package stays free of service imports — the tiers
# set_tier_observer pattern).
_cache_observer = None


def set_cache_observer(fn) -> None:
    """fn(evicted: int) — called when the in-memory tier evicts."""
    global _cache_observer
    _cache_observer = fn


def notify_cache_evictions(n: int) -> None:
    if n and _cache_observer is not None:
        try:
            _cache_observer(n)
        except Exception:
            pass  # telemetry must never break an upsert


# per-op "already warned this outage" latches (cleared on any success)
_cache_warned: dict = {}


# --- distributed job queue (scale-out extension) ---------------------------
# Queue lifecycle states for shared-queue entries. A row is QUEUED until
# a replica claims it (LEASED); ack removes it, a crashed replica's
# lease expires and the entry is re-queued exactly once by whichever
# peer's reclaim scan wins the conditional update.

Q_QUEUED = "queued"
Q_LEASED = "leased"

# Observer seam for queue-layer telemetry (service.obs wires Prometheus
# counters in; the store package stays free of service imports — the
# set_cache_observer pattern). Events: "claim_conflict" (a conditional
# claim lost the race to another replica and retried the next row).
_queue_observer = None


def set_queue_observer(fn) -> None:
    """fn(event: str, n: int) — queue-backend telemetry events."""
    global _queue_observer
    _queue_observer = fn


def notify_queue_event(event: str, n: int = 1) -> None:
    if _queue_observer is not None:
        try:
            _queue_observer(event, n)
        except Exception:
            pass  # telemetry must never break a claim


class JobQueueStore:
    """The distributed job-queue seam: N replicas lease work from one
    shared queue (the horizontal-scale-out counterpart of `Database`).

    Contract every backend must honor:

      * `claim` is ATOMIC per entry — implemented as a single
        conditional update (memory: under the table lock; Postgres:
        `update ... where id = X and queue_state = 'queued'`), so two
        replicas can NEVER hold the same job at once;
      * a lease is exclusive but temporary: `renew` heartbeats extend
        it, and an expired lease makes the entry reclaimable by ANY
        replica's `reclaim_expired` scan — exactly once (the same
        conditional-update rule), with the attempt counter carried so
        a twice-crashed entry dies clean instead of crash-looping;
      * `ack`/`nack`/`renew` are conditional on still OWNING the lease:
        a replica that lost its lease learns it from the False return
        and must not publish that job's terminal record (the reclaimer
        owns it now).

    Entries are plain JSON-able dicts:

        {"id", "slot", "bucket", "state", "attempt", "lease_owner",
         "lease_expires_at", "submitted_at", "time_limit", "payload",
         # QoS claim-ordering fields (all optional; absent = FIFO):
         "qos", "deadline_at", "tenant"}

    `slot` is the consistent-hash ring position of the job's tier key
    (vrpms_tpu.sched.ring.slot) — precomputed at enqueue so backends
    can filter claims to a replica's owned arcs with plain range
    predicates. `payload` is opaque to this package (the service stores
    the original request content + trace context so ANY replica can
    rebuild and solve the job). Clocks are epoch seconds (time.time) —
    comparable across processes, unlike monotonic clocks.

    **Claim ordering (QoS extension).** When entries carry the
    claim-ordering fields (`qos` = interactive|standard|batch,
    `deadline_at` = absolute epoch deadline), `claim`/`claim_batch`
    MUST serve the highest class first and earliest-deadline-first
    within a class (vrpms_tpu.sched.qos.entry_order_key; no deadline
    sorts last in its class, ties stay FIFO), and `claim_batch`'s
    mates follow the free-rider rule: same-class mates fill first,
    lower classes top off a launch, a same-class mate is never
    displaced (sched.qos.select_mates). Entries without the fields —
    including everything written with VRPMS_QOS=off — order exactly
    as before: pure FIFO. Backends that predate the ordering columns
    keep working through the base-class fallbacks below (FIFO claims,
    None depth maps), mirroring the `claim_batch` fallback.
    """

    #: default ceiling on completed-claim generations: attempt 0 is the
    #: first claim, a reclaim re-queues at attempt 1, and a SECOND
    #: expiry (attempt would reach 2) fails the job clean — the
    #: cross-replica generalization of sched.worker's at-most-one
    #: requeue rule.
    MAX_ATTEMPTS = 2

    def enqueue(self, entry: dict) -> None:
        """Make a job visible to the shared queue (state=queued)."""
        raise NotImplementedError

    def claim(self, owner: str, lease_s: float, slots=None) -> dict | None:
        """Atomically lease the oldest QUEUED entry (optionally
        restricted to ring-slot ranges `slots` = [(lo, hi), ...),
        half-open) for `owner`; None when nothing matches."""
        raise NotImplementedError

    def claim_batch(self, owner: str, lease_s: float, k: int,
                    slots=None) -> list:
        """Claim-K-matching: lease the oldest QUEUED entry (same slot
        filter as `claim`) PLUS up to k-1 younger QUEUED entries sharing
        its `bucket` (the ring token — equal buckets are what one
        replica can assemble into one batched launch), oldest-first, in
        ONE conditional update. Entries whose bucket is None never
        batch (the leader claims alone).

        Per-entry semantics are IDENTICAL to k sequential `claim`s —
        each returned entry carries its own lease/owner/attempt and is
        renewed/acked/nacked/reclaimed individually, so a crash
        mid-batch re-queues exactly the unfinished members at attempt+1
        — but the leasing itself is one atomic update: racing replicas
        can split a token's backlog, never share an entry. Returns []
        when nothing matches.

        Default: degrade to a single `claim` (a backend that has not
        implemented batched leasing still honors the contract at k=1).
        """
        entry = self.claim(owner, lease_s, slots)
        return [] if entry is None else [entry]

    def renew(self, owner: str, job_id: str, lease_s: float) -> bool:
        """Heartbeat: extend `owner`'s lease; False if the lease is no
        longer theirs (expired and reclaimed)."""
        raise NotImplementedError

    def ack(self, owner: str, job_id: str) -> bool:
        """Terminal: remove the entry if `owner` still holds the lease.
        False means the lease was lost — the caller must NOT publish
        the job's terminal record."""
        raise NotImplementedError

    def nack(self, owner: str, job_id: str, note: dict | None = None) -> bool:
        """Voluntarily return a leased entry to the queue (local
        admission full, shutdown, graceful drain) WITHOUT burning an
        attempt. `note`, when given, merges into the entry's payload so
        the next claimant sees why it came back — the drain path writes
        {"ckpt": true} so a peer knows a durable checkpoint exists and
        resumes from it instead of solving from zero. Backends
        predating the parameter are called without it (the Replica
        falls back on TypeError)."""
        raise NotImplementedError

    def reclaim_expired(self, max_attempts: int | None = None):
        """Re-queue every entry whose lease expired — exactly once per
        expiry across all callers. Returns (requeued, dead): `requeued`
        entries are claimable again at attempt+1; `dead` entries hit
        the attempt ceiling and were REMOVED — the caller owns writing
        their clean failure record."""
        raise NotImplementedError

    def depth(self) -> int:
        """QUEUED (unleased) entries — the shared backpressure signal."""
        raise NotImplementedError

    def depth_by_class(self) -> dict | None:
        """{qos class: queued count} for the readiness probe's
        per-class view. Default None = backend predates the QoS
        columns (callers omit the field, never fail)."""
        return None

    def tenant_depths(self) -> dict | None:
        """{tenant: active (queued + leased) entries} — the fleet-wide
        accounting per-tenant fairness quotas divide by. Anonymous
        entries (no tenant) are excluded: quotas only apply to
        identified tenants. Default None = unknown (admission must not
        block on it)."""
        return None

    def get_entry(self, job_id: str) -> dict | None:
        """One queue entry by job id (no lease taken) — the federated
        read path's owner lookup: `lease_owner` names the replica whose
        live registry holds the solve. Default None = backend predates
        the op (callers fall back to checkpoint-sourced overlays,
        never fail)."""
        return None

    def register_replica(self, replica_id: str, ttl_s: float,
                         info: dict | None = None) -> None:
        """Heartbeat this replica into the ring membership. `info` is
        an optional small status doc (inflight, claim mix, warmed
        tiers — sched.replica publishes it each beat) that
        `replica_infos` serves to the fleet rollup; backends predating
        the parameter may ignore it (callers fall back to the 2-arg
        call on TypeError)."""
        raise NotImplementedError

    def replicas(self) -> list[str]:
        """Replica ids with a live (unexpired) heartbeat, sorted."""
        raise NotImplementedError

    def deregister_replica(self, replica_id: str) -> None:
        """Remove this replica's heartbeat row NOW (graceful drain):
        peers' next ring refresh drops it without waiting out the TTL,
        so its arcs move immediately. Best-effort default no-op —
        membership expiry is the fallback either way."""
        return None

    def replica_infos(self) -> dict | None:
        """{replica_id: heartbeat status doc} for live replicas — the
        GET /api/debug/fleet cross-replica view. Default None = backend
        predates the heartbeat docs (the rollup serves membership ids
        only, never fails)."""
        return None


class Database:
    """Abstract store. Subclasses implement _fetch_row / _insert_solution
    and _owner_email; the public methods provide the shared error
    envelope semantics."""

    #: True once any call on this instance was served by a degraded-mode
    #: fallback (store.resilient); the service marks the response
    #: `degraded: true`. Plain backends never flip it.
    degraded = False

    def __init__(self, auth=None):
        self.auth = auth

    # -- backend primitives -------------------------------------------------
    def _fetch_row(self, table: str, row_id):
        raise NotImplementedError

    def _insert_solution(self, data: dict):
        raise NotImplementedError

    def _owner_email(self) -> str | None:
        raise NotImplementedError

    def _fetch_warmstart(self, owner: str, name):
        raise NotImplementedError

    def _upsert_warmstart(self, owner: str, name, state: dict):
        raise NotImplementedError

    def _fetch_job(self, job_id: str):
        raise NotImplementedError

    def _upsert_job(self, job_id: str, record: dict):
        raise NotImplementedError

    def _fetch_cache_family(self, family: str) -> list:
        raise NotImplementedError

    def _fetch_cached_solution(self, key: str) -> dict | None:
        raise NotImplementedError

    def _upsert_cached_solution(self, key: str, family: str, entry: dict):
        raise NotImplementedError

    # -- content-addressed solution cache (perf extension) ------------------
    # One row per (instance fingerprint + request options) under `key`,
    # grouped by `family` — the hash of the underlying dataset + fleet
    # config + auth scope, which survives customer-subset changes so
    # near-hit lookups are ONE keyed read (service.cache). Strictly
    # best-effort: the cache is an optimization, so a miss is always a
    # safe answer and no failure here may ever fail (or even slow — see
    # store.resilient's single-attempt guard) the solve it fronts.
    def _cache_warn(self, op: str, exc: Exception) -> None:
        # one structured event per outage, not one line per request: an
        # open breaker fails every lookup instantly, so unthrottled
        # logging would scale 1:1 with traffic for the outage's duration
        if _cache_warned.get(op):
            return
        _cache_warned[op] = True
        try:
            from vrpms_tpu.obs import log_event

            log_event(
                "store.cache_degraded",
                op=op,
                error=f"{type(exc).__name__}: {exc}",
            )
        except Exception:
            print(
                f"[store] solution-cache {op} failed "
                f"({type(exc).__name__}: {exc}); continuing without cache",
                file=sys.stderr,
            )

    def _cache_recovered(self, op: str) -> None:
        # clear only the succeeding op's latch: a partial outage (reads
        # fine, writes denied — e.g. a one-sided RLS policy) must not
        # re-arm the write latch on every successful read, or the
        # one-event-per-outage throttle never engages
        _cache_warned.pop(op, None)

    # One failed cache call disables the cache for the REST of this
    # instance's lifetime — instances are per-request (store.
    # get_database in the handlers), so this caps what an outage can
    # cost a single request at ONE store deadline: without it, a hung
    # backend with the breaker still closed would charge a near-eligible
    # miss up to three sequential deadlines (exact read, family scan,
    # winner hydration) before the solve even starts.
    _cache_down = False

    def get_cache_family(self, family: str) -> list:
        """Rows for a family — at minimum the seed-ranking fields
        (`key` + problem/customers/cost, nested under 'entry' or flat);
        [] on failure. The winning row is re-read by key afterwards
        (service.cache), so backends may return slim rows here."""
        if self._cache_down:
            return []
        try:
            rows = self._fetch_cache_family(family)
        except Exception as exc:
            self._cache_warn("read", exc)
            self._cache_down = True
            return []
        self._cache_recovered("read")
        return list(rows or [])

    def get_cached_solution(self, key: str) -> dict | None:
        """The exact-hit path: ONE keyed read (primary-key lookup on the
        network backends — no family scan on the hot path); None on miss
        or failure."""
        if self._cache_down:
            return None
        try:
            row = self._fetch_cached_solution(key)
        except Exception as exc:
            self._cache_warn("read", exc)
            self._cache_down = True
            return None
        self._cache_recovered("read")
        return row

    def put_cached_solution(self, key: str, family: str, entry: dict) -> bool:
        if self._cache_down:
            # entries are recomputable; the next healthy request
            # re-populates — don't spend another deadline after a solve
            # whose lookup already found the cache store unreachable
            return False
        try:
            self._upsert_cached_solution(key, family, entry)
        except Exception as exc:
            self._cache_warn("write", exc)
            self._cache_down = True
            return False
        self._cache_recovered("write")
        return True

    # -- durable trace export (fleet observability extension) ---------------
    # One row per (trace_id, replica): each replica that recorded spans
    # for a trace exports ITS span set as one bounded document, so a
    # cross-replica job's full waterfall is the union of its rows and
    # replicas never clobber each other's half. Strictly best-effort,
    # with the solution cache's inverted resilience policy (see
    # store.resilient._cache_call): a trace store outage drops spans —
    # it must never block, slow, or fail a solve, and the exporter's
    # counters (vrpms_trace_export_total) account for every span either
    # way. Reads distinguish "no rows" ([]) from "store unreachable"
    # (None) so the federated debug surfaces can degrade to local-only
    # with an honest `degraded: true` marker.
    def _put_trace_rows(self, rows: list):
        raise NotImplementedError

    def _fetch_trace_rows(self, trace_id: str) -> list:
        raise NotImplementedError

    def _list_trace_rows(self, limit: int) -> list:
        raise NotImplementedError

    def put_trace_spans(self, rows: list) -> bool:
        """Batch-write exported trace rows ({trace_id, replica, doc,
        summary columns}); one store call for the whole batch. False on
        failure (the exporter counts the spans as failed)."""
        if not rows:
            return True
        try:
            self._put_trace_rows(rows)
        except Exception as exc:
            self._cache_warn("trace_write", exc)
            return False
        self._cache_recovered("trace_write")
        return True

    def get_trace_spans(self, trace_id: str) -> list | None:
        """Every replica's exported row for `trace_id`; [] when none,
        None when the store could not be read (degraded marker)."""
        try:
            rows = self._fetch_trace_rows(trace_id)
        except Exception as exc:
            self._cache_warn("trace_read", exc)
            return None
        self._cache_recovered("trace_read")
        return list(rows or [])

    def list_traces(self, limit: int = 50) -> list | None:
        """Newest-first exported-trace summaries, one per trace with
        its rows merged across replicas; None when the store could not
        be read (the fleet-scope debug list degrades to local-only)."""
        try:
            rows = self._list_trace_rows(max(1, int(limit)) * 4)
        except Exception as exc:
            self._cache_warn("trace_read", exc)
            return None
        self._cache_recovered("trace_read")
        merged: dict = {}
        order: list = []
        for row in rows or []:
            tid = row.get("trace_id")
            if tid is None:
                continue
            cur = merged.get(tid)
            if cur is None:
                merged[tid] = cur = {
                    "traceId": tid,
                    "startedAt": row.get("started_at"),
                    "endAt": None,
                    "status": row.get("status") or "ok",
                    "root": row.get("root"),
                    "spans": 0,
                    "replicas": [],
                }
                order.append(tid)
            started = row.get("started_at")
            if started is not None and (
                cur["startedAt"] is None or started < cur["startedAt"]
            ):
                # the earliest replica's row is the submitting side:
                # its root names the trace
                cur["startedAt"] = started
                if row.get("root"):
                    cur["root"] = row.get("root")
            if started is not None and row.get("duration_ms") is not None:
                end = started + float(row["duration_ms"]) / 1e3
                if cur["endAt"] is None or end > cur["endAt"]:
                    cur["endAt"] = end
            if row.get("status") == "error":
                cur["status"] = "error"
            cur["spans"] += int(row.get("spans") or 0)
            rep = row.get("replica")
            if rep and rep not in cur["replicas"]:
                cur["replicas"].append(rep)
        out = []
        for tid in order[: max(1, int(limit))]:
            cur = merged[tid]
            end = cur.pop("endAt")
            cur["durationMs"] = (
                None
                if end is None or cur["startedAt"] is None
                else round((end - cur["startedAt"]) * 1e3, 3)
            )
            out.append(cur)
        return out

    # -- durable flight records (solve analytics extension) -----------------
    # One row per (job_id, replica): the completed solve's flight record
    # (device/host split, padding + batch occupancy, evals/sec, cache
    # outcome, gap, primal integral) as one bounded document, written by
    # the analytics exporter's background flusher. Same inverted
    # resilience policy as trace export: an outage drops records — it
    # must never block, slow, or fail a solve — and reads distinguish
    # "no rows" ([]) from "store unreachable" (None) so the federated
    # /api/debug/analytics rollup degrades to local-only honestly.
    def _put_flight_rows(self, rows: list):
        raise NotImplementedError

    def _fetch_flight_rows(self, limit: int) -> list:
        raise NotImplementedError

    def put_flight_records(self, rows: list) -> bool:
        """Batch-write exported flight rows ({job_id, replica,
        finished_at, tier, algorithm, doc}); one store call for the
        whole batch. False on failure (the exporter counts the records
        as failed)."""
        if not rows:
            return True
        try:
            self._put_flight_rows(rows)
        except Exception as exc:
            self._cache_warn("flight_write", exc)
            return False
        self._cache_recovered("flight_write")
        return True

    def get_flight_records(self, limit: int = 256) -> list | None:
        """Newest-first flight rows across all replicas; [] when none,
        None when the store could not be read (degraded marker)."""
        try:
            rows = self._fetch_flight_rows(max(1, int(limit)))
        except Exception as exc:
            self._cache_warn("flight_read", exc)
            return None
        self._cache_recovered("flight_read")
        return list(rows or [])

    # -- durable solve checkpoints (crash-resume extension) -----------------
    # One row per (job id, attempt): a running solve's latest durable
    # incumbent — routes in original location ids, penalized cost,
    # evals, elapsed, and (decomposed giants) each completed shard's
    # routes — written by the background checkpointer
    # (service.checkpoint) at a bounded cadence. Reclaimed/requeued
    # attempts read the LATEST row and warm-resume through the existing
    # Prepared.resolve continuation path. Strictly best-effort with the
    # solution cache's fail-open policy (see store.resilient._cache_call
    # and the single-attempt primitives below): a checkpoint store
    # outage drops the write — accounted in
    # vrpms_ckpt_total{outcome="dropped"} — and must never fail, slow,
    # or change the solve it shadows. Terminal ack/dead paths delete a
    # job's rows (stale-checkpoint hygiene); the hosted backend pairs
    # the table with a retention sweep (store/schema.sql).
    def _fetch_checkpoint(self, job_id: str):
        raise NotImplementedError

    def _upsert_checkpoint(self, job_id: str, attempt: int, state: dict):
        raise NotImplementedError

    def _delete_checkpoint(self, job_id: str):
        raise NotImplementedError

    def put_checkpoint(self, job_id: str, attempt: int, state: dict) -> bool:
        """Persist a job's latest checkpoint state for `attempt`; False
        on failure (the checkpointer counts the write as dropped)."""
        try:
            self._upsert_checkpoint(str(job_id), int(attempt), state)
        except Exception as exc:
            self._cache_warn("ckpt_write", exc)
            return False
        self._cache_recovered("ckpt_write")
        return True

    def get_checkpoint(self, job_id: str, errors=None) -> dict | None:
        """The LATEST-attempt checkpoint row for `job_id` as
        {"attempt": int, "state": dict}; None on miss or failure — a
        checkpoint that cannot be read degrades to a from-zero resume,
        never to a failed job. The optional `errors` list (the get_job
        convention) lets federated readers tell a miss from a store
        outage so they can mark the response degraded."""
        try:
            row = self._fetch_checkpoint(str(job_id))
        except Exception as exc:
            self._cache_warn("ckpt_read", exc)
            if errors is not None:
                errors += [
                    {"what": "Database read error", "reason": str(exc)}
                ]
            return None
        self._cache_recovered("ckpt_read")
        return row

    def delete_checkpoint(self, job_id: str) -> bool:
        """Drop every checkpoint row for `job_id` (terminal hygiene:
        ack'd and dead jobs must not leave stale resume state behind);
        False on failure (the retention sweep is the backstop)."""
        try:
            self._delete_checkpoint(str(job_id))
        except Exception as exc:
            self._cache_warn("ckpt_delete", exc)
            return False
        self._cache_recovered("ckpt_delete")
        return True

    # -- standing subscriptions (re-solve-on-change extension) --------------
    # One row per subscription id: a standing re-solve-on-change job's
    # durable control-plane doc — the base request content, cadence,
    # generation counter, lineage tail, and last launched job id —
    # written by the subscription manager (service.subscriptions) at
    # every generation boundary. Any replica can read the full set
    # (list) to adopt due cadences after a drain or crash, so the rows
    # are durable state, not cache: reads/writes go through the
    # fail-open latch wrappers below (an outage degrades a generation
    # launch or a cadence adoption, never the solves themselves).
    def _fetch_subscription(self, sub_id: str):
        raise NotImplementedError

    def _list_subscriptions(self):
        raise NotImplementedError

    def _upsert_subscription(self, sub_id: str, doc: dict):
        raise NotImplementedError

    def _delete_subscription(self, sub_id: str):
        raise NotImplementedError

    def put_subscription(self, sub_id: str, doc: dict) -> bool:
        """Persist a subscription's control-plane doc; False on failure
        (the manager keeps serving from its in-process copy)."""
        try:
            self._upsert_subscription(str(sub_id), doc)
        except Exception as exc:
            self._cache_warn("sub_write", exc)
            return False
        self._cache_recovered("sub_write")
        return True

    def get_subscription(self, sub_id: str, errors=None) -> dict | None:
        """A subscription doc by id; None on miss or failure. The
        optional `errors` list (the get_job convention) lets callers
        tell a miss from a store outage."""
        try:
            row = self._fetch_subscription(str(sub_id))
        except Exception as exc:
            self._cache_warn("sub_read", exc)
            if errors is not None:
                errors += [
                    {"what": "Database read error", "reason": str(exc)}
                ]
            return None
        self._cache_recovered("sub_read")
        return None if row is None else row.get("doc")

    def list_subscriptions(self) -> list | None:
        """Every stored subscription doc, or None when the store cannot
        be read (callers must treat None as unknown, not empty — a
        cadence adopter must not conclude the fleet has no standing
        work because of one read blip)."""
        try:
            rows = self._list_subscriptions()
        except Exception as exc:
            self._cache_warn("sub_read", exc)
            return None
        self._cache_recovered("sub_read")
        return [r.get("doc") for r in rows or []]

    def delete_subscription(self, sub_id: str) -> bool:
        """Drop a subscription row (DELETE endpoint / terminal
        hygiene); False on failure."""
        try:
            self._delete_subscription(str(sub_id))
        except Exception as exc:
            self._cache_warn("sub_delete", exc)
            return False
        self._cache_recovered("sub_delete")
        return True

    # -- async job records (scheduler extension) ----------------------------
    # The jobs API (service.jobs) persists each job's lifecycle record
    # through this seam so `GET /api/jobs/{id}` answers from whichever
    # backend is configured — in-process memory for tests/local, Supabase
    # for the hosted deployment (store/schema.sql `jobs`). Job ids are
    # unguessable uuid4 hex, which is the (reference-parity) access
    # control: like unauthenticated solves, job records are not owner-
    # scoped. Writes are best-effort with a stderr warning (a telemetry/
    # bookkeeping failure must never fail the solve itself); reads
    # surface errors into the caller's envelope list.
    def save_job(self, job_id: str, record: dict) -> bool:
        try:
            self._upsert_job(job_id, record)
            return True
        except Exception as exc:
            print(
                f"[store] job write failed ({type(exc).__name__}: {exc}); "
                "job status may be stale — check store/schema.sql",
                file=sys.stderr,
            )
            return False

    def get_job(self, job_id: str, errors) -> dict | None:
        try:
            row = self._fetch_job(job_id)
            return None if row is None else row.get("record")
        except Exception as exc:
            errors += [{"what": "Database read error", "reason": str(exc)}]
            return None

    def get_job_seed(self, job_id: str) -> dict | None:
        """Best-effort job-record read for dynamic re-solve seeding
        (service.cache's `warmStart: {"jobId": ...}` resolution): like
        get_job but with NO error side channel — a seed that cannot be
        retrieved degrades to an unseeded solve, never to a failed
        request. Reads the jobs table directly, so jobId-seeded
        re-solves stay functional with the solution cache off
        (VRPMS_CACHE does not gate job records)."""
        try:
            row = self._fetch_job(job_id)
            return None if row is None else row.get("record")
        except Exception:
            return None

    # -- warm-start checkpoints (framework extension) -----------------------
    # The reference has no computation checkpointing; its closest analog is
    # the ignored/completed dynamic re-solve inputs (SURVEY.md §5
    # "checkpoint/resume"). This seam persists the best-so-far solution
    # keyed by (owner, solutionName) so a re-solve can seed its population
    # from the previous result. Owner scoping mirrors save_solution's auth
    # rule: without an authenticated owner nothing is stored or returned —
    # otherwise tenants could read or clobber each other's checkpoints
    # through a shared solutionName. Best-effort by design: a miss or store
    # failure must never fail a solve.
    def _warmstart_owner(self) -> str | None:
        # Database instances are per-request; cache the owner so a
        # warm-started solve resolves it once, not once per get + save
        # (on Supabase each resolution is an auth network round-trip).
        if not hasattr(self, "_warmstart_owner_cache"):
            try:
                self._warmstart_owner_cache = self._owner_email()
            except Exception:
                self._warmstart_owner_cache = None
        return self._warmstart_owner_cache

    def _warmstart_warn(self, op: str, exc: Exception) -> None:
        # Best-effort must not mean silent: a store/schema problem (e.g.
        # a warmstarts table missing the owner column — see
        # store/schema.sql) would otherwise disable checkpoints with no
        # trace at all.
        print(
            f"[store] warm-start {op} failed ({type(exc).__name__}: {exc}); "
            "continuing without checkpoint — check store/schema.sql",
            file=sys.stderr,
        )

    def get_warmstart(self, name) -> dict | None:
        owner = self._warmstart_owner()
        if not owner:
            return None
        try:
            row = self._fetch_warmstart(owner, name)
            return None if row is None else row.get("state")
        except Exception as exc:
            self._warmstart_warn("read", exc)
            return None

    def save_warmstart(self, name, state: dict, better_than=None) -> bool:
        """Persist a checkpoint; with `better_than`, only if it improves.

        `better_than(prev_state) -> bool` is evaluated against the
        freshly re-fetched stored state immediately before the upsert
        (the in-memory store runs the whole sequence under its table
        lock; remote stores narrow the race window to one round-trip).
        """
        owner = self._warmstart_owner()
        if not owner:
            return False
        try:
            return self._upsert_warmstart_guarded(owner, name, state, better_than)
        except Exception as exc:
            self._warmstart_warn("write", exc)
            return False

    def _upsert_warmstart_guarded(self, owner, name, state, better_than) -> bool:
        if better_than is not None:
            row = self._fetch_warmstart(owner, name)
            prev = None if row is None else row.get("state")
            if prev is not None and not better_than(prev):
                return False
        self._upsert_warmstart(owner, name, state)
        return True

    # -- reference-shaped API ----------------------------------------------
    def get_locations_by_id(self, id, errors):
        try:
            row = self._fetch_row("locations", id)
            if row is None:
                raise Exception(
                    f"No location set found with given id {id}. "
                    "Make sure you are accessing public data or data owned "
                    "by you. Check if your authentication token has expired."
                )
            return row["locations"]
        except Exception as exception:
            errors += [{"what": "Database read error", "reason": str(exception)}]
            return None

    def get_durations_by_id(self, id, errors):
        try:
            row = self._fetch_row("durations", id)
            if row is None:
                raise Exception(
                    f"No duration matrix found with given id {id}. "
                    "Make sure you are accessing public data or data owned "
                    "by you. Check if your authentication token has expired."
                )
            return row["matrix"]
        except Exception as exception:
            errors += [{"what": "Database read error", "reason": str(exception)}]
            return None

    def _save(self, data: dict, errors):
        try:
            email = self._owner_email()
        except Exception as exception:
            # e.g. supabase get_user() raising on an expired token; must
            # surface as the error envelope, not a dropped connection.
            errors += [{"what": "Database auth error", "reason": str(exception)}]
            return None
        if not email:
            errors += [
                {
                    "what": "Not permitted",
                    "reason": "An authentication token is required to save "
                    "solutions to database. Please provide 'auth' with a "
                    "valid JWT token in the request body. If you have "
                    "already provided a token, it has very likely expired.",
                }
            ]
            return None
        data = dict(data, owner=email)
        try:
            return self._insert_solution(data)
        except Exception as exception:
            errors += [{"what": "Database write error", "reason": str(exception)}]
            return None


class DatabaseVRP(Database):
    def save_solution(
        self, name, description, locations, vehicles, duration_max, duration_sum, errors
    ):
        return self._save(
            {
                "name": name,
                "description": description,
                "durationMax": duration_max,
                "durationSum": duration_sum,
                "locations": locations,
                "vehicles": vehicles,
            },
            errors,
        )


class DatabaseTSP(Database):
    def save_solution(self, name, description, locations, vehicle, duration, errors):
        return self._save(
            {
                "name": name,
                "description": description,
                "duration": duration,
                "locations": locations,
                "vehicle": vehicle,
            },
            errors,
        )
