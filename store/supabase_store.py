"""Supabase adapter: reference-parity persistence, import-gated.

Mirrors the reference's client usage (reference api/database.py): anon
client from SUPABASE_URL/SUPABASE_KEY, JWT login via set_session with
swallowed failure (:18-23), per-table select-by-id, owner email from the
authenticated user. The supabase SDK is imported lazily so environments
without it (this framework's solver core has no network dependency) can
still import the package; constructing the store without the SDK raises
a clear error.
"""

from __future__ import annotations

import os

from store.base import Database, DatabaseTSP, DatabaseVRP
from vrpms_tpu.obs import log_event


class _SupabaseMixin(Database):
    def __init__(self, auth=None):
        super().__init__(auth)
        try:
            from supabase.client import create_client
            from supabase.lib.client_options import ClientOptions
        except ImportError as e:  # pragma: no cover - environment dependent
            raise RuntimeError(
                "supabase SDK not installed; set VRPMS_STORE=memory or "
                "install supabase to use the hosted store"
            ) from e
        url = os.environ.get("SUPABASE_URL") or ""
        key = os.environ.get("SUPABASE_KEY") or ""
        self.client = create_client(
            url, key, options=ClientOptions(persist_session=False)
        )
        if auth:
            try:
                self.client.auth.set_session(access_token=auth, refresh_token=auth)
            except Exception as exc:
                # Reference parity: login failures surface later as
                # missing-owner / row-level-security errors, not here —
                # but not silently: the request is RLS-doomed, so
                # operators get a structured warning and a counter.
                log_event(
                    "store.auth_failed",
                    level="warn",
                    error=f"{type(exc).__name__}: {exc}",
                )
                try:
                    from service import obs

                    obs.AUTH_FAILURES.inc()
                except Exception:
                    pass  # telemetry must not change auth semantics

    def _fetch_row(self, table: str, row_id):
        result = self.client.table(table).select("*").eq("id", row_id).execute()
        if not len(result.data):
            return None
        return result.data[0]

    def _insert_solution(self, data: dict):
        return self.client.table("solutions").insert(data).execute()

    def _owner_email(self):
        user = self.client.auth.get_user()
        if not user:
            return None
        return user.model_dump()["user"]["email"]

    def _fetch_warmstart(self, owner, name):
        result = (
            self.client.table("warmstarts")
            .select("*")
            .eq("owner", owner)
            .eq("name", name)
            .execute()
        )
        if not len(result.data):
            return None
        return result.data[0]

    def _upsert_warmstart(self, owner, name, state: dict):
        return (
            self.client.table("warmstarts")
            .upsert(
                {"owner": owner, "name": name, "state": state},
                on_conflict="owner,name",
            )
            .execute()
        )

    def _fetch_job(self, job_id):
        result = (
            self.client.table("jobs").select("*").eq("id", job_id).execute()
        )
        if not len(result.data):
            return None
        return result.data[0]

    def _upsert_job(self, job_id, record: dict):
        return (
            self.client.table("jobs")
            .upsert({"id": job_id, "record": record}, on_conflict="id")
            .execute()
        )

    def _fetch_cache_family(self, family):
        # bounded: a hot family (one city's dataset) accumulates one row
        # per distinct request shape; 64 most-recent rows are plenty of
        # near-hit candidates and keep the read one indexed round trip.
        # Slim projection: seed RANKING needs only problem/customers/
        # cost per row (service.cache._pick_seed reads flat rows too) —
        # each full entry jsonb embeds the whole served response, and 64
        # of those would be hundreds of KB of pre-solve transfer on the
        # HTTP thread; the single winner is hydrated by a keyed read
        result = (
            self.client.table("solution_cache")
            .select(
                "key,problem:entry->problem,"
                "customers:entry->customers,cost:entry->cost"
            )
            .eq("family", family)
            .order("updated_at", desc=True)
            .limit(64)
            .execute()
        )
        return list(result.data)

    def _fetch_cached_solution(self, key):
        # exact-hit hot path: one primary-key read, no family scan
        result = (
            self.client.table("solution_cache")
            .select("*")
            .eq("key", key)
            .limit(1)
            .execute()
        )
        return result.data[0] if result.data else None

    def _upsert_cached_solution(self, key, family, entry: dict):
        # updated_at must ride the payload: the column default fires on
        # INSERT only, and recency ordering + the documented retention
        # job both read it — a re-solved entry refreshes its slot
        from datetime import datetime, timezone

        return (
            self.client.table("solution_cache")
            .upsert(
                {
                    "key": key,
                    "family": family,
                    "entry": entry,
                    "updated_at": datetime.now(timezone.utc).isoformat(),
                },
                on_conflict="key",
            )
            .execute()
        )


class SupabaseDatabaseVRP(_SupabaseMixin, DatabaseVRP):
    pass


class SupabaseDatabaseTSP(_SupabaseMixin, DatabaseTSP):
    pass
