"""Supabase adapter: reference-parity persistence, import-gated.

Mirrors the reference's client usage (reference api/database.py): anon
client from SUPABASE_URL/SUPABASE_KEY, JWT login via set_session with
swallowed failure (:18-23), per-table select-by-id, owner email from the
authenticated user. The supabase SDK is imported lazily so environments
without it (this framework's solver core has no network dependency) can
still import the package; constructing the store without the SDK raises
a clear error.
"""

from __future__ import annotations

from store.base import (
    Database,
    DatabaseTSP,
    DatabaseVRP,
    JobQueueStore,
    Q_LEASED,
    Q_QUEUED,
    notify_queue_event,
)
from vrpms_tpu import config
from vrpms_tpu.obs import log_event


class _SupabaseMixin(Database):
    def __init__(self, auth=None):
        super().__init__(auth)
        try:
            from supabase.client import create_client
            from supabase.lib.client_options import ClientOptions
        except ImportError as e:  # pragma: no cover - environment dependent
            raise RuntimeError(
                "supabase SDK not installed; set VRPMS_STORE=memory or "
                "install supabase to use the hosted store"
            ) from e
        url = config.get("SUPABASE_URL")
        key = config.get("SUPABASE_KEY")
        self.client = create_client(
            url, key, options=ClientOptions(persist_session=False)
        )
        if auth:
            try:
                self.client.auth.set_session(access_token=auth, refresh_token=auth)
            except Exception as exc:
                # Reference parity: login failures surface later as
                # missing-owner / row-level-security errors, not here —
                # but not silently: the request is RLS-doomed, so
                # operators get a structured warning and a counter.
                log_event(
                    "store.auth_failed",
                    level="warn",
                    error=f"{type(exc).__name__}: {exc}",
                )
                try:
                    from service import obs

                    obs.AUTH_FAILURES.inc()
                except Exception:
                    pass  # telemetry must not change auth semantics

    def _fetch_row(self, table: str, row_id):
        result = self.client.table(table).select("*").eq("id", row_id).execute()
        if not len(result.data):
            return None
        return result.data[0]

    def _insert_solution(self, data: dict):
        return self.client.table("solutions").insert(data).execute()

    def _owner_email(self):
        user = self.client.auth.get_user()
        if not user:
            return None
        return user.model_dump()["user"]["email"]

    def _fetch_warmstart(self, owner, name):
        result = (
            self.client.table("warmstarts")
            .select("*")
            .eq("owner", owner)
            .eq("name", name)
            .execute()
        )
        if not len(result.data):
            return None
        return result.data[0]

    def _upsert_warmstart(self, owner, name, state: dict):
        return (
            self.client.table("warmstarts")
            .upsert(
                {"owner": owner, "name": name, "state": state},
                on_conflict="owner,name",
            )
            .execute()
        )

    def _fetch_job(self, job_id):
        result = (
            self.client.table("jobs").select("*").eq("id", job_id).execute()
        )
        if not len(result.data):
            return None
        return result.data[0]

    def _upsert_job(self, job_id, record: dict):
        return (
            self.client.table("jobs")
            .upsert({"id": job_id, "record": record}, on_conflict="id")
            .execute()
        )

    def _fetch_cache_family(self, family):
        # bounded: a hot family (one city's dataset) accumulates one row
        # per distinct request shape; 64 most-recent rows are plenty of
        # near-hit candidates and keep the read one indexed round trip.
        # Slim projection: seed RANKING needs only problem/customers/
        # cost per row (service.cache._pick_seed reads flat rows too) —
        # each full entry jsonb embeds the whole served response, and 64
        # of those would be hundreds of KB of pre-solve transfer on the
        # HTTP thread; the single winner is hydrated by a keyed read
        result = (
            self.client.table("solution_cache")
            .select(
                "key,problem:entry->problem,"
                "customers:entry->customers,cost:entry->cost"
            )
            .eq("family", family)
            .order("updated_at", desc=True)
            .limit(64)
            .execute()
        )
        return list(result.data)

    def _fetch_cached_solution(self, key):
        # exact-hit hot path: one primary-key read, no family scan
        result = (
            self.client.table("solution_cache")
            .select("*")
            .eq("key", key)
            .limit(1)
            .execute()
        )
        return result.data[0] if result.data else None

    def _put_trace_rows(self, rows: list):
        # one upsert for the whole exporter batch (the point of
        # batching: K traces = ONE network round trip); updated_at
        # rides the payload for the same reason as the cache upsert —
        # the retention job and the newest-first list both read it
        from datetime import datetime, timezone

        now = datetime.now(timezone.utc).isoformat()
        return (
            self.client.table("trace_spans")
            .upsert(
                [dict(row, updated_at=now) for row in rows],
                on_conflict="trace_id,replica",
            )
            .execute()
        )

    def _fetch_trace_rows(self, trace_id):
        result = (
            self.client.table("trace_spans")
            .select("*")
            .eq("trace_id", trace_id)
            .execute()
        )
        return list(result.data)

    def _list_trace_rows(self, limit):
        # slim scan (the cache family-read precedent): summaries never
        # transfer the span documents, only the indexed summary columns
        result = (
            self.client.table("trace_spans")
            .select(
                "trace_id,replica,started_at,duration_ms,status,root,spans"
            )
            .order("updated_at", desc=True)
            .limit(max(1, int(limit)))
            .execute()
        )
        return list(result.data)

    def _put_flight_rows(self, rows: list):
        # one upsert for the whole analytics-exporter batch (K records
        # = ONE network round trip); updated_at rides the payload for
        # the retention job and the newest-first read
        from datetime import datetime, timezone

        now = datetime.now(timezone.utc).isoformat()
        return (
            self.client.table("flight_records")
            .upsert(
                [dict(row, updated_at=now) for row in rows],
                on_conflict="job_id,replica",
            )
            .execute()
        )

    def _fetch_flight_rows(self, limit):
        # newest-first full rows: the rollup reads the doc jsonb (it is
        # compact by construction — serialize_record bounds it)
        result = (
            self.client.table("flight_records")
            .select("*")
            .order("updated_at", desc=True)
            .limit(max(1, int(limit)))
            .execute()
        )
        return list(result.data)

    def _fetch_checkpoint(self, job_id):
        # latest attempt wins: the resume path wants the newest durable
        # incumbent (an attempt-2 run that checkpointed supersedes the
        # attempt-1 rows it resumed from)
        result = (
            self.client.table("solve_checkpoints")
            .select("job_id,attempt,state")
            .eq("job_id", job_id)
            .order("attempt", desc=True)
            .limit(1)
            .execute()
        )
        return result.data[0] if result.data else None

    def _upsert_checkpoint(self, job_id, attempt, state: dict):
        # updated_at rides the payload (the solution-cache rule): the
        # column default fires on INSERT only and the retention sweep
        # reads it — a long solve's refreshed checkpoint must not age
        # out mid-run
        from datetime import datetime, timezone

        return (
            self.client.table("solve_checkpoints")
            .upsert(
                {
                    "job_id": job_id,
                    "attempt": int(attempt),
                    "state": state,
                    "updated_at": datetime.now(timezone.utc).isoformat(),
                },
                on_conflict="job_id,attempt",
            )
            .execute()
        )

    def _delete_checkpoint(self, job_id):
        return (
            self.client.table("solve_checkpoints")
            .delete()
            .eq("job_id", job_id)
            .execute()
        )

    def _fetch_subscription(self, sub_id):
        result = (
            self.client.table("subscriptions")
            .select("id,doc")
            .eq("id", sub_id)
            .limit(1)
            .execute()
        )
        return result.data[0] if result.data else None

    def _list_subscriptions(self):
        result = (
            self.client.table("subscriptions")
            .select("id,doc")
            .execute()
        )
        return list(result.data)

    def _upsert_subscription(self, sub_id, doc: dict):
        # updated_at rides the payload (the solution-cache rule): the
        # column default fires on INSERT only, and a long-lived
        # subscription's doc is rewritten at every generation boundary
        from datetime import datetime, timezone

        return (
            self.client.table("subscriptions")
            .upsert(
                {
                    "id": sub_id,
                    "doc": doc,
                    "updated_at": datetime.now(timezone.utc).isoformat(),
                },
                on_conflict="id",
            )
            .execute()
        )

    def _delete_subscription(self, sub_id):
        return (
            self.client.table("subscriptions")
            .delete()
            .eq("id", sub_id)
            .execute()
        )

    def _upsert_cached_solution(self, key, family, entry: dict):
        # updated_at must ride the payload: the column default fires on
        # INSERT only, and recency ordering + the documented retention
        # job both read it — a re-solved entry refreshes its slot
        from datetime import datetime, timezone

        return (
            self.client.table("solution_cache")
            .upsert(
                {
                    "key": key,
                    "family": family,
                    "entry": entry,
                    "updated_at": datetime.now(timezone.utc).isoformat(),
                },
                on_conflict="key",
            )
            .execute()
        )


class SupabaseDatabaseVRP(_SupabaseMixin, DatabaseVRP):
    pass


class SupabaseDatabaseTSP(_SupabaseMixin, DatabaseTSP):
    pass


class SupabaseJobQueue(JobQueueStore):
    """Shared-queue backend on the `jobs` table's lease columns
    (store/schema.sql: queue_state / lease_owner / lease_expires_at /
    slot / attempt / queue_entry, plus the jobs_queue_claim index).

    Claims are a SELECT of candidate ids followed by one conditional
    UPDATE per candidate (`... where id = X and queue_state = 'queued'`)
    — Postgres updates a row atomically, so when two replicas race, one
    UPDATE matches zero rows and that replica moves to the next
    candidate (surfaced as a claim_conflict event). The same pattern
    guards renew/ack/nack (`... and lease_owner = me`) and the expiry
    reclaim (`... and lease_owner = <observed>` so concurrent scanners
    re-queue each crashed job exactly once). Lease clocks are client
    epoch seconds stored as ISO timestamps — replicas must run NTP-sane
    clocks within a fraction of the lease (15 s default)."""

    CLAIM_CANDIDATES = 8

    #: class-level latch: False once a qos_rank/deadline_at write or
    #: ordered scan failed WITH an undefined-column error (a hosted
    #: table predating the QoS columns in store/schema.sql) — from then
    #: on this process enqueues and scans without them, degrading claim
    #: order to plain FIFO instead of failing every queue op (the
    #: claim_batch base-fallback rule applied to columns). Only a
    #: missing-column error latches: transient failures (timeouts,
    #: 5xx) re-raise to the caller's existing retry/backoff and must
    #: NOT silently disable QoS for the process lifetime. Process-wide
    #: by design: every request builds a fresh store instance, and
    #: rediscovering the missing columns once per request would double
    #: every op's round trips.
    _qos_cols = True

    @staticmethod
    def _missing_qos_columns(exc: Exception) -> bool:
        """Does this error say the QoS columns are absent? PostgREST
        surfaces Postgres's undefined-column as code 42703 with the
        column name in the message."""
        text = str(exc)
        return "42703" in text or (
            "column" in text.lower()
            and ("qos_rank" in text or "deadline_at" in text)
        )

    def __init__(self):
        try:
            from supabase.client import create_client
            from supabase.lib.client_options import ClientOptions
        except ImportError as e:  # pragma: no cover - environment dependent
            raise RuntimeError(
                "supabase SDK not installed; set VRPMS_STORE=memory or "
                "install supabase to use the hosted job queue"
            ) from e
        url = config.get("SUPABASE_URL")
        key = config.get("SUPABASE_KEY")
        self.client = create_client(
            url, key, options=ClientOptions(persist_session=False)
        )

    @staticmethod
    def _iso(epoch_s: float) -> str:
        from datetime import datetime, timezone

        return datetime.fromtimestamp(epoch_s, timezone.utc).isoformat()

    @staticmethod
    def _epoch(iso: str | None) -> float | None:
        if not iso:
            return None
        from datetime import datetime

        return datetime.fromisoformat(iso).timestamp()

    def _entry(self, row: dict) -> dict:
        entry = dict(row.get("queue_entry") or {})
        entry["id"] = row["id"]
        entry["slot"] = row.get("slot") or 0
        entry["state"] = row.get("queue_state")
        entry["attempt"] = row.get("attempt") or 0
        entry["lease_owner"] = row.get("lease_owner")
        entry["lease_expires_at"] = self._epoch(row.get("lease_expires_at"))
        return entry

    def enqueue(self, entry: dict) -> None:
        import time as _time

        doc = {
            k: v
            for k, v in entry.items()
            if k
            not in ("id", "slot", "state", "attempt", "lease_owner",
                    "lease_expires_at")
        }
        row = {
            "id": entry["id"],
            "queue_state": Q_QUEUED,
            "slot": int(entry.get("slot") or 0),
            "attempt": int(entry.get("attempt") or 0),
            "lease_owner": None,
            "lease_expires_at": None,
            "queue_entry": doc,
            "updated_at": self._iso(_time.time()),
        }
        if type(self)._qos_cols and (
            entry.get("qos") is not None
            or entry.get("deadline_at") is not None
        ):
            from vrpms_tpu.sched import qos as qos_mod

            row["qos_rank"] = qos_mod.rank(entry.get("qos"))
            row["deadline_at"] = (
                None
                if entry.get("deadline_at") is None
                else self._iso(float(entry["deadline_at"]))
            )
            try:
                self.client.table("jobs").upsert(
                    row, on_conflict="id"
                ).execute()
                return
            except Exception as exc:
                if not self._missing_qos_columns(exc):
                    raise  # transient failure: the caller's problem
                # table predates the QoS columns: latch off and fall
                # through to the column-free upsert (FIFO ordering) —
                # the entry's own qos/deadline_at stay readable in the
                # queue_entry doc for when the schema catches up
                type(self)._qos_cols = False
                log_event(
                    "store.qos_columns_missing",
                    level="warn",
                    hint="apply the qos_rank/deadline_at migration in "
                    "store/schema.sql; claim order degrades to FIFO",
                )
                row.pop("qos_rank", None)
                row.pop("deadline_at", None)
        self.client.table("jobs").upsert(row, on_conflict="id").execute()

    def _candidates(self, slots, states, expired_before=None,
                    limit=None) -> list:
        # slim scan (the PR-6 family-scan precedent): candidate rows
        # carry only the lease/ordering columns plus the ring token
        # (claim-K batch assembly keys on it) — winners' full rows
        # (queue_entry payload included) come back on the conditional
        # UPDATE's returning representation, so polling replicas never
        # transfer payloads they will not run
        ordered = type(self)._qos_cols and expired_before is None
        cols = (
            "id,slot,queue_state,lease_owner,lease_expires_at,"
            "attempt,bucket:queue_entry->>bucket"
        )
        if ordered:
            # claim order rides the index: class rank first, EDF within
            # class (nulls — no deadline — last), then age. Reclaim
            # scans (expired_before) keep the plain age order: expiry
            # is not a scheduling decision.
            cols += ",qos:queue_entry->>qos"
        q = self.client.table("jobs").select(cols).in_(
            "queue_state", list(states)
        )
        if ordered:
            q = q.order("qos_rank", desc=False).order(
                "deadline_at", desc=False, nullsfirst=False
            )
        q = q.order("updated_at", desc=False).limit(
            limit or self.CLAIM_CANDIDATES
        )
        if expired_before is not None:
            q = q.lt("lease_expires_at", self._iso(expired_before))
        if slots:
            q = q.or_(
                ",".join(
                    f"and(slot.gte.{lo},slot.lt.{hi})" for lo, hi in slots
                )
            )
        try:
            return list(q.execute().data)
        except Exception as exc:
            if not ordered or not self._missing_qos_columns(exc):
                raise  # transient failure: the claim loop backs off
            # the ordered scan failed on the missing columns: latch off
            # and retry this one scan FIFO so the claim loop never sees
            # the schema gap
            type(self)._qos_cols = False
            log_event(
                "store.qos_columns_missing",
                level="warn",
                hint="apply the qos_rank/deadline_at migration in "
                "store/schema.sql; claim order degrades to FIFO",
            )
            return self._candidates(
                slots, states, expired_before=expired_before, limit=limit
            )

    def claim(self, owner: str, lease_s: float, slots=None) -> dict | None:
        import time as _time

        if slots is not None and not slots:
            return None
        for row in self._candidates(slots, (Q_QUEUED,)):
            upd = (
                self.client.table("jobs")
                .update(
                    {
                        "queue_state": Q_LEASED,
                        "lease_owner": owner,
                        "lease_expires_at": self._iso(
                            _time.time() + lease_s
                        ),
                    }
                )
                .eq("id", row["id"])
                .eq("queue_state", Q_QUEUED)
                .execute()
            )
            if upd.data:
                return self._entry(dict(row, **upd.data[0]))
            notify_queue_event("claim_conflict")
        return None

    def claim_batch(self, owner: str, lease_s: float, k: int,
                    slots=None) -> list:
        """Claim-K-matching as ONE conditional UPDATE against the
        jobs_queue_claim index: pick the oldest queued candidate, gather
        the younger candidates sharing its ring token (queue_entry->>
        bucket), then

            update jobs set queue_state='leased', lease_owner=$me, ...
             where id in ($leader, $mates...) and queue_state='queued'
             returning *;

        Rows a racing replica leased between the scan and the update
        simply do not match — the two fleets split the token's backlog,
        never share an entry (the per-row atomicity of a Postgres
        UPDATE, exactly the single-claim rule applied to a set). Each
        returned entry carries its own lease and is renewed / acked /
        reclaimed individually."""
        import time as _time

        if k <= 0 or (slots is not None and not slots):
            return []
        rows = self._candidates(
            slots, (Q_QUEUED,), limit=max(self.CLAIM_CANDIDATES, k)
        )
        while rows:
            leader = rows[0]
            bucket = leader.get("bucket")
            batch = [leader]
            if bucket is not None:
                from vrpms_tpu.sched import qos as qos_mod

                # free-rider fill over the scan (which already arrives
                # in claim order, so EDF/FIFO within each preference
                # tier is preserved): same-class mates first, lower
                # classes top off, same-class never displaced
                mates = [
                    r for r in rows[1:] if r.get("bucket") == bucket
                ]
                batch += qos_mod.select_mates(
                    leader, mates, k - 1,
                    key=lambda r: qos_mod.order_key(r.get("qos"), None),
                )
            by_id = {r["id"]: r for r in batch}
            upd = (
                self.client.table("jobs")
                .update(
                    {
                        "queue_state": Q_LEASED,
                        "lease_owner": owner,
                        "lease_expires_at": self._iso(
                            _time.time() + lease_s
                        ),
                    }
                )
                .in_("id", list(by_id))
                .eq("queue_state", Q_QUEUED)
                .execute()
            )
            if upd.data:
                if len(upd.data) < len(by_id):
                    # the race cost us some mates, not the batch
                    notify_queue_event(
                        "claim_conflict", len(by_id) - len(upd.data)
                    )
                won = sorted(
                    (self._entry(dict(by_id[r["id"]], **r)) for r in upd.data),
                    key=lambda e: list(by_id).index(e["id"]),
                )
                return won
            notify_queue_event("claim_conflict", len(by_id))
            # the whole batch was raced away: drop it and retry on the
            # remaining candidates (the single-claim retry rule)
            rows = [r for r in rows if r["id"] not in by_id]
        return []

    def _owned_update(self, owner: str, job_id: str, patch: dict) -> bool:
        upd = (
            self.client.table("jobs")
            .update(patch)
            .eq("id", job_id)
            .eq("queue_state", Q_LEASED)
            .eq("lease_owner", owner)
            .execute()
        )
        return bool(upd.data)

    def renew(self, owner: str, job_id: str, lease_s: float) -> bool:
        import time as _time

        return self._owned_update(
            owner, job_id,
            {"lease_expires_at": self._iso(_time.time() + lease_s)},
        )

    def ack(self, owner: str, job_id: str) -> bool:
        # "remove from the queue", not "delete the job": the row stays
        # (it carries the persisted record) with the queue columns
        # cleared so no scan ever matches it again
        return self._owned_update(
            owner, job_id,
            {
                "queue_state": None,
                "lease_owner": None,
                "lease_expires_at": None,
                "queue_entry": None,
            },
        )

    def nack(self, owner: str, job_id: str, note: dict | None = None) -> bool:
        patch = {
            "queue_state": Q_QUEUED,
            "lease_owner": None,
            "lease_expires_at": None,
        }
        if note:
            # merge the drain marker into the entry payload. The
            # read-modify-write is safe: we still HOLD the lease, so no
            # peer can touch the row between the select and the
            # owner-conditional update (which arbitrates if the lease
            # expired underneath us anyway).
            sel = (
                self.client.table("jobs")
                .select("queue_entry")
                .eq("id", job_id)
                .limit(1)
                .execute()
            )
            if sel.data:
                doc = dict(sel.data[0].get("queue_entry") or {})
                payload = dict(doc.get("payload") or {})
                payload.update(note)
                doc["payload"] = payload
                patch["queue_entry"] = doc
        return self._owned_update(owner, job_id, patch)

    def reclaim_expired(self, max_attempts: int | None = None):
        import time as _time

        if max_attempts is None:
            max_attempts = self.MAX_ATTEMPTS
        requeued, dead = [], []
        now = _time.time()
        for row in self._candidates(
            None, (Q_LEASED,), expired_before=now
        ):
            attempt = int(row.get("attempt") or 0) + 1
            terminal = attempt >= max_attempts
            upd = (
                self.client.table("jobs")
                .update(
                    {
                        "queue_state": None if terminal else Q_QUEUED,
                        "lease_owner": None,
                        "lease_expires_at": None,
                        "attempt": attempt,
                    }
                )
                .eq("id", row["id"])
                .eq("queue_state", Q_LEASED)
                .eq("lease_owner", row.get("lease_owner") or "")
                # re-check expiry IN the update: the owner's heartbeat
                # may have renewed between our SELECT and now — a live,
                # renewed lease must never be stolen (the memory
                # backend does this check-and-reset under one lock)
                .lt("lease_expires_at", self._iso(now))
                .execute()
            )
            if not upd.data:
                notify_queue_event("claim_conflict")
                continue  # a peer's scan won this expiry
            # the returned representation carries the full row (the
            # candidate scan is slim) — queue_entry included, which
            # the dead-entry failure record needs
            entry = self._entry(dict(upd.data[0], attempt=attempt))
            (dead if terminal else requeued).append(entry)
        return requeued, dead

    def depth(self) -> int:
        result = (
            self.client.table("jobs")
            .select("id", count="exact")
            .eq("queue_state", Q_QUEUED)
            .limit(1)
            .execute()
        )
        return int(result.count or 0)

    def get_entry(self, job_id: str) -> dict | None:
        # slim owner lookup for the federated read path: the lease
        # columns identify the owning replica; the queue_entry doc
        # rides along only because _entry reconstructs the contract
        # shape from it (no conditional UPDATE — no lease is taken)
        rows = (
            self.client.table("jobs")
            .select(
                "id,slot,queue_state,lease_owner,lease_expires_at,"
                "attempt,queue_entry"
            )
            .eq("id", str(job_id))
            .limit(1)
            .execute()
            .data
        )
        return self._entry(rows[0]) if rows else None

    def depth_by_class(self) -> dict | None:
        if not type(self)._qos_cols:
            return None  # schema predates the columns: omit the view
        from vrpms_tpu.sched import qos as qos_mod

        out = {}
        for name in qos_mod.CLASSES:
            q = (
                self.client.table("jobs")
                .select("id", count="exact")
                .eq("queue_state", Q_QUEUED)
                .limit(1)
            )
            if name == qos_mod.DEFAULT_CLASS:
                # rows enqueued without a class (pre-QoS builds,
                # VRPMS_QOS=off peers) count as standard
                q = q.or_(
                    f"qos_rank.eq.{qos_mod.rank(name)},qos_rank.is.null"
                )
            else:
                q = q.eq("qos_rank", qos_mod.rank(name))
            try:
                out[name] = int(q.execute().count or 0)
            except Exception as exc:
                if self._missing_qos_columns(exc):
                    type(self)._qos_cols = False
                return None  # omit the view; never fail readiness
        return out

    #: bounded tenant scan: the fairness map is a heuristic, and an
    #: unbounded select of every active row would grow with backlog
    TENANT_SCAN_LIMIT = 512

    def tenant_depths(self) -> dict | None:
        try:
            result = (
                self.client.table("jobs")
                .select("tenant:queue_entry->>tenant")
                .in_("queue_state", (Q_QUEUED, Q_LEASED))
                # server-side tenant filter: the bounded sample must
                # contain only quota-relevant rows, or a deep mostly-
                # anonymous backlog could fill the limit with null
                # tenants and report an over-quota tenant as 0 —
                # quotas failing open exactly under the overload they
                # exist for
                .filter("queue_entry->>tenant", "not.is", "null")
                .limit(self.TENANT_SCAN_LIMIT)
                .execute()
            )
        except Exception:
            return None  # unknown — admission fails open
        depths: dict = {}
        for row in result.data:
            tenant = row.get("tenant")
            if tenant:
                depths[tenant] = depths.get(tenant, 0) + 1
        return depths

    #: class-level latch, the _qos_cols pattern: False once an info
    #: write failed with an undefined-column error (a replicas table
    #: predating the fleet-rollup migration) — heartbeats then write
    #: without the doc instead of failing every beat.
    _info_col = True

    def register_replica(self, replica_id: str, ttl_s: float,
                         info: dict | None = None) -> None:
        import time as _time

        row = {
            "id": replica_id,
            "expires_at": self._iso(_time.time() + ttl_s),
        }
        if info is not None and type(self)._info_col:
            try:
                self.client.table("replicas").upsert(
                    dict(row, info=info), on_conflict="id"
                ).execute()
                return
            except Exception as exc:
                # precise undefined-column match only (the _qos_cols
                # rule): a transient error whose text merely CONTAINS
                # "info" must re-raise, not silently disable the doc
                # for the process lifetime
                text = str(exc)
                if "42703" not in text and 'column "info"' not in text:
                    raise  # transient failure: the caller's problem
                type(self)._info_col = False
                log_event(
                    "store.replica_info_column_missing",
                    level="warn",
                    hint="apply the replicas.info migration in "
                    "store/schema.sql; /api/debug/fleet degrades to "
                    "membership ids only",
                )
        self.client.table("replicas").upsert(row, on_conflict="id").execute()

    def replicas(self) -> list[str]:
        import time as _time

        result = (
            self.client.table("replicas")
            .select("id")
            .gt("expires_at", self._iso(_time.time()))
            .execute()
        )
        return sorted(row["id"] for row in result.data)

    def deregister_replica(self, replica_id: str) -> None:
        # graceful drain: drop the heartbeat row now so peers' next
        # ring refresh moves this replica's arcs without waiting out
        # the TTL
        self.client.table("replicas").delete().eq("id", replica_id).execute()

    def replica_infos(self) -> dict | None:
        import time as _time

        if not type(self)._info_col:
            return None  # schema predates the docs: ids-only rollup
        try:
            result = (
                self.client.table("replicas")
                .select("id,info")
                .gt("expires_at", self._iso(_time.time()))
                .execute()
            )
        except Exception:
            return None  # the rollup fails open to membership ids
        return {row["id"]: row.get("info") or {} for row in result.data}
