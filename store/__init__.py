"""Pluggable persistence keeping the reference's table/row shapes.

The reference binds its handlers directly to a Supabase client
(reference api/database.py). Here the same interface — get_locations_by_id,
get_durations_by_id, save_solution with identical row shapes — is a seam
(store.base.Database) with two implementations:

  * store.memory  — in-process fake for tests/local runs (the clean seam
    SURVEY.md §4 item 4 calls for; no network, seedable);
  * store.supabase_store — the real adapter, import-gated so the
    framework runs without the supabase SDK installed.

Selection: VRPMS_STORE env var ("memory" | "supabase"); default is
"supabase" when SUPABASE_URL is configured (reference parity), else
"memory".
"""

from __future__ import annotations

import os

from vrpms_tpu.utils import load_dotenv

# The reference loads `.env` at package import (src/__init__.py:1-2) so
# SUPABASE_URL/SUPABASE_KEY are present by the time a client is built;
# the store is that consumer here, so it bootstraps too (idempotent, and
# real environment variables always win).
load_dotenv()


def get_database(problem: str, auth=None):
    """Factory: problem is 'vrp' or 'tsp'; returns the configured store."""
    kind = os.environ.get("VRPMS_STORE")
    if kind is None:
        kind = "supabase" if os.environ.get("SUPABASE_URL") else "memory"
    if kind == "memory":
        from store.memory import InMemoryDatabaseTSP, InMemoryDatabaseVRP

        cls = InMemoryDatabaseVRP if problem == "vrp" else InMemoryDatabaseTSP
        return cls(auth)
    if kind == "supabase":
        from store.supabase_store import SupabaseDatabaseTSP, SupabaseDatabaseVRP

        cls = SupabaseDatabaseVRP if problem == "vrp" else SupabaseDatabaseTSP
        return cls(auth)
    raise ValueError(f"unknown VRPMS_STORE {kind!r}")
