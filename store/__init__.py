"""Pluggable persistence keeping the reference's table/row shapes.

The reference binds its handlers directly to a Supabase client
(reference api/database.py). Here the same interface — get_locations_by_id,
get_durations_by_id, save_solution with identical row shapes — is a seam
(store.base.Database) with two implementations:

  * store.memory  — in-process fake for tests/local runs (the clean seam
    SURVEY.md §4 item 4 calls for; no network, seedable);
  * store.supabase_store — the real adapter, import-gated so the
    framework runs without the supabase SDK installed.

Selection: VRPMS_STORE env var ("memory" | "supabase" |
"faulty[:<plan>]"); default is "supabase" when SUPABASE_URL is
configured (reference parity), else "memory". "faulty" is the chaos
backend: the in-memory store behind a declarative fault plan
(store.faulty / vrpms_tpu.testing.faults).

Resilience: network-ish backends (supabase, faulty) are wrapped in
store.resilient.ResilientDatabase — per-call deadlines, read retries,
circuit breaker, degraded-mode cache/journal fallbacks — unless
VRPMS_RESILIENCE=off; VRPMS_RESILIENCE=on additionally wraps the
in-process memory store (only useful for experiments — it adds a
thread hop per call).
"""

from __future__ import annotations

from vrpms_tpu import config
from vrpms_tpu.utils import load_dotenv

# The reference loads `.env` at package import (src/__init__.py:1-2) so
# SUPABASE_URL/SUPABASE_KEY are present by the time a client is built;
# the store is that consumer here, so it bootstraps too (idempotent, and
# real environment variables always win).
load_dotenv()


def _resilience_wraps(kind: str) -> bool:
    mode = config.get("VRPMS_RESILIENCE").lower()
    if mode in ("off", "0", "false", "no"):
        return False
    if mode in ("on", "1", "true", "yes"):
        return True
    return kind in ("supabase", "faulty")


def get_database(problem: str, auth=None):
    """Factory: problem is 'vrp' or 'tsp'; returns the configured store."""
    kind = config.raw("VRPMS_STORE")
    if kind is None:
        kind = "supabase" if config.get("SUPABASE_URL") else "memory"
    plan = ""
    if kind.startswith("faulty"):
        kind, _, plan = kind.partition(":")
        if kind != "faulty":
            raise ValueError(f"unknown VRPMS_STORE {kind!r}")
    if kind == "memory":
        from store.memory import InMemoryDatabaseTSP, InMemoryDatabaseVRP

        cls = InMemoryDatabaseVRP if problem == "vrp" else InMemoryDatabaseTSP
        db = cls(auth)
    elif kind == "supabase":
        from store.supabase_store import SupabaseDatabaseTSP, SupabaseDatabaseVRP

        cls = SupabaseDatabaseVRP if problem == "vrp" else SupabaseDatabaseTSP
        db = cls(auth)
    elif kind == "faulty":
        from store.faulty import FaultyDatabaseTSP, FaultyDatabaseVRP

        cls = FaultyDatabaseVRP if problem == "vrp" else FaultyDatabaseTSP
        db = cls(auth, plan=plan)
    else:
        raise ValueError(f"unknown VRPMS_STORE {kind!r}")
    if _resilience_wraps(kind):
        from store.resilient import wrap

        db = wrap(db, kind, problem)
    return db


def get_queue_store():
    """Factory for the distributed job-queue backend (the scale-out
    seam — store.base.JobQueueStore): same VRPMS_STORE selection as
    get_database, so the shared queue and the job records live in the
    same store. NOT wrapped in ResilientDatabase: the replica claim
    loop is already a retry loop by construction (it polls), claims
    must stay conditional single attempts (a blind retry could
    double-claim after a commit-then-timeout), and a queue outage
    degrades to "this replica claims nothing for a while", never to a
    failed request — the resilience policy is the loop itself."""
    kind = config.raw("VRPMS_STORE")
    if kind is None:
        kind = "supabase" if config.get("SUPABASE_URL") else "memory"
    plan = ""
    if kind.startswith("faulty"):
        kind, _, plan = kind.partition(":")
    if kind == "memory":
        from store.memory import InMemoryJobQueue

        return InMemoryJobQueue()
    if kind == "supabase":
        from store.supabase_store import SupabaseJobQueue

        return SupabaseJobQueue()
    if kind == "faulty":
        from store.faulty import FaultyJobQueue

        return FaultyJobQueue(plan)
    raise ValueError(f"unknown VRPMS_STORE {kind!r}")
