-- Supabase/Postgres schema for the hosted store (store/supabase_store.py).
--
-- Tables `locations`, `durations`, and `solutions` mirror the reference's
-- row shapes exactly (reference api/database.py:28,40,80; see
-- store/base.py for the column contracts). `warmstarts` is this
-- framework's extension: best-so-far solve checkpoints keyed by
-- (owner, name) — owner scoping is REQUIRED, it is what prevents
-- tenants from reading or clobbering each other's checkpoints through a
-- shared solutionName. Pair with row-level-security policies matching
-- the reference's ownership model (reference api/database.py:57-59).

create table if not exists locations (
  id text primary key,
  locations jsonb not null
);

create table if not exists durations (
  id text primary key,
  matrix jsonb not null
);

create table if not exists solutions (
  id bigint generated always as identity primary key,
  name text not null,
  description text,
  owner text not null,
  "durationMax" double precision,   -- VRP results
  "durationSum" double precision,   -- VRP results
  duration double precision,        -- TSP results
  locations jsonb,
  vehicles jsonb,                   -- VRP results
  vehicle jsonb,                    -- TSP results
  created_at timestamptz not null default now()
);

create table if not exists warmstarts (
  owner text not null,
  name text not null,
  state jsonb not null,
  updated_at timestamptz not null default now(),
  primary key (owner, name)         -- upsert target: on_conflict="owner,name"
);

-- Async solve jobs (service.jobs): one lifecycle record per jobId, the
-- whole record as one jsonb document (status, timings, result/errors —
-- the shape service.jobs._job_record writes). Ids are unguessable uuid4
-- hex; like unauthenticated solves, records are not owner-scoped.
-- Records accumulate with request volume: pair with a retention job,
-- e.g. pg_cron:  delete from jobs where updated_at < now() - '7 days';
-- (the in-memory backend bounds itself at store.memory MAX_JOBS).
create table if not exists jobs (
  id text primary key,              -- upsert target: on_conflict="id"
  record jsonb not null,
  updated_at timestamptz not null default now()
);
create index if not exists jobs_updated_at on jobs (updated_at);

-- Content-addressed solution cache (service/cache.py): one row per
-- (instance fingerprint + algorithm-relevant request options) under
-- `key`; `family` groups rows by dataset + fleet config + auth scope so
-- near-hit (warm-start-from-similar) lookups are one indexed read. The
-- entry document carries the served result, the giant-tour routes in
-- original location ids, the penalized cost, and the customer-id set
-- (store/base.py get_cache_family / put_cached_solution). Auth scope is
-- hashed INTO both key and family, so tenants can never share entries.
-- Rows accumulate with distinct-request volume: pair with a retention
-- job, e.g. pg_cron:
--   delete from solution_cache where updated_at < now() - '7 days';
-- (the in-memory backend LRU-bounds itself at the VRPMS_CACHE cap).
create table if not exists solution_cache (
  key text primary key,             -- upsert target: on_conflict="key"
  family text not null,
  entry jsonb not null,
  updated_at timestamptz not null default now()
);
create index if not exists solution_cache_family
  on solution_cache (family, updated_at desc);
