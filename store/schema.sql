-- Supabase/Postgres schema for the hosted store (store/supabase_store.py).
--
-- Tables `locations`, `durations`, and `solutions` mirror the reference's
-- row shapes exactly (reference api/database.py:28,40,80; see
-- store/base.py for the column contracts). `warmstarts` is this
-- framework's extension: best-so-far solve checkpoints keyed by
-- (owner, name) — owner scoping is REQUIRED, it is what prevents
-- tenants from reading or clobbering each other's checkpoints through a
-- shared solutionName. Pair with row-level-security policies matching
-- the reference's ownership model (reference api/database.py:57-59).

create table if not exists locations (
  id text primary key,
  locations jsonb not null
);

create table if not exists durations (
  id text primary key,
  matrix jsonb not null
);

create table if not exists solutions (
  id bigint generated always as identity primary key,
  name text not null,
  description text,
  owner text not null,
  "durationMax" double precision,   -- VRP results
  "durationSum" double precision,   -- VRP results
  duration double precision,        -- TSP results
  locations jsonb,
  vehicles jsonb,                   -- VRP results
  vehicle jsonb,                    -- TSP results
  created_at timestamptz not null default now()
);

create table if not exists warmstarts (
  owner text not null,
  name text not null,
  state jsonb not null,
  updated_at timestamptz not null default now(),
  primary key (owner, name)         -- upsert target: on_conflict="owner,name"
);

-- Async solve jobs (service.jobs): one lifecycle record per jobId, the
-- whole record as one jsonb document (status, timings, result/errors —
-- the shape service.jobs._job_record writes). Ids are unguessable uuid4
-- hex; like unauthenticated solves, records are not owner-scoped.
-- Records accumulate with request volume: pair with a retention job,
-- e.g. pg_cron:  delete from jobs where updated_at < now() - '7 days';
-- (the in-memory backend bounds itself at store.memory MAX_JOBS).
create table if not exists jobs (
  id text primary key,              -- upsert target: on_conflict="id"
  record jsonb not null,
  updated_at timestamptz not null default now()
);
create index if not exists jobs_updated_at on jobs (updated_at);

-- Content-addressed solution cache (service/cache.py): one row per
-- (instance fingerprint + algorithm-relevant request options) under
-- `key`; `family` groups rows by dataset + fleet config + auth scope so
-- near-hit (warm-start-from-similar) lookups are one indexed read. The
-- entry document carries the served result, the giant-tour routes in
-- original location ids, the penalized cost, and the customer-id set
-- (store/base.py get_cache_family / put_cached_solution). Auth scope is
-- hashed INTO both key and family, so tenants can never share entries.
-- Rows accumulate with distinct-request volume: pair with a retention
-- job, e.g. pg_cron:
--   delete from solution_cache where updated_at < now() - '7 days';
-- (the in-memory backend LRU-bounds itself at the VRPMS_CACHE cap).
create table if not exists solution_cache (
  key text primary key,             -- upsert target: on_conflict="key"
  family text not null,
  entry jsonb not null,
  updated_at timestamptz not null default now()
);
create index if not exists solution_cache_family
  on solution_cache (family, updated_at desc);

-- Distributed job queue (horizontal scale-out; store/base.py
-- JobQueueStore, store/supabase_store.py SupabaseJobQueue): the jobs
-- table doubles as the shared queue. A submitting replica enqueues by
-- setting queue_state='queued' with the request payload in queue_entry
-- and the job's consistent-hash ring position in slot; peers claim via
-- ONE conditional update
--   update jobs set queue_state='leased', lease_owner=$me,
--          lease_expires_at=now() + $lease
--    where id=$candidate and queue_state='queued';
-- (zero rows updated = another replica won the race), heartbeat-renew
-- while solving, and clear the queue columns on ack. Claim-K-matching
-- (fleet-wide micro-batching) is the same statement over a SET: the
-- claimant scans the oldest queued candidates, keeps those sharing the
-- leader's ring token (queue_entry->>'bucket'), and leases them all in
-- one conditional update against the jobs_queue_claim index
--   update jobs set queue_state='leased', lease_owner=$me,
--          lease_expires_at=now() + $lease
--    where id in ($leader, $mates...) and queue_state='queued'
--    returning *;
-- rows a racing replica already leased simply do not match, so two
-- fleets SPLIT a token's backlog but never share an entry. Leases stay
-- strictly per-row: each claimed entry renews/acks/reclaims on its own,
-- so a crash mid-batch re-queues exactly the unfinished members. A
-- crashed replica's lease expires and any peer's reclaim scan re-queues
-- the entry exactly once (conditional on the observed lease_owner),
-- bumping attempt; attempt >= 2 fails the job clean instead of
-- crash-looping. Replicas must run NTP-sane clocks (skew well under
-- the lease, 15 s default).
alter table jobs add column if not exists queue_state text;
alter table jobs add column if not exists lease_owner text;
alter table jobs add column if not exists lease_expires_at timestamptz;
alter table jobs add column if not exists slot integer;
alter table jobs add column if not exists attempt integer not null default 0;
alter table jobs add column if not exists queue_entry jsonb;
-- claim scans filter on state (+ slot arcs) ordered by age; the partial
-- index keeps settled job rows (queue_state null) out of it entirely
create index if not exists jobs_queue_claim
  on jobs (queue_state, slot, updated_at)
  where queue_state is not null;

-- QoS claim ordering (deadline-aware scheduling): qos_rank is the
-- request's priority class as an integer (0=interactive, 1=standard,
-- 2=batch; NOT NULL DEFAULT 1 so rows enqueued by pre-QoS builds or
-- VRPMS_QOS=off peers — which write no qos columns at all — order as
-- standard, matching the in-memory backend's reference semantics; the
-- ALTER backfills pre-migration rows to 1 as well), deadline_at the
-- absolute EDF deadline
-- (submit time + the request's timeLimit budget; null = no deadline,
-- sorts LAST within its class). Claim candidate scans order by
--   (qos_rank asc, deadline_at asc nulls last, updated_at asc)
-- — higher class first, earliest deadline first within class, FIFO on
-- ties — which is exactly what the in-memory backend's sorted sweep
-- computes under its table lock. The claimant (store/supabase_store.py
-- SupabaseJobQueue) detects a table that predates these columns at the
-- first failed write/scan and degrades claim order to plain FIFO, so
-- the migration can roll out after the code.
alter table jobs add column if not exists qos_rank integer not null default 1;
alter table jobs add column if not exists deadline_at timestamptz;
create index if not exists jobs_queue_claim_qos
  on jobs (queue_state, qos_rank, deadline_at, updated_at)
  where queue_state is not null;

-- Ring membership: one heartbeat row per live replica; consistent-hash
-- arcs are derived client-side from the live id set (sched/ring.py).
-- `info` is the replica's heartbeat status doc (inflight, claim mix,
-- warmed tiers — sched/replica.py publishes it each beat) that
-- GET /api/debug/fleet aggregates into one fleet rollup; replicas
-- predating the column keep heartbeating (the store latches off the
-- doc write on the first undefined-column error and the rollup
-- degrades to membership ids).
create table if not exists replicas (
  id text primary key,              -- upsert target: on_conflict="id"
  expires_at timestamptz not null
);
alter table replicas add column if not exists info jsonb;

-- Durable trace export (fleet observability; store/base.py trace seam,
-- vrpms_tpu/obs/export.py): one row per (trace_id, replica) — each
-- replica that recorded spans for a trace exports ITS span set as one
-- bounded document (the exporter trims events, then attributes, then
-- drops the trace rather than write an oversized row), so a
-- cross-replica job's full waterfall is the union of its trace's rows
-- and replicas never clobber each other's half. The summary columns
-- (started_at epoch seconds, duration_ms, status, root, spans count)
-- exist so list scans never transfer the documents. Strictly
-- best-effort: writes are single-attempt behind the shared circuit
-- breaker (store/resilient.py) and an outage drops spans, never blocks
-- a solve. Rows accumulate with traffic: pair with a retention job,
-- e.g. pg_cron:
--   delete from trace_spans where updated_at < now() - '3 days';
-- (the in-memory backend bounds itself at store.memory MAX_TRACE_ROWS).
create table if not exists trace_spans (
  trace_id text not null,
  replica text not null,
  started_at double precision,      -- trace start, epoch seconds
  duration_ms double precision,
  status text,
  root text,                        -- root span name (summary lists)
  spans integer,                    -- span count in doc
  doc jsonb not null,               -- the replica's full span tree
  updated_at timestamptz not null default now(),
  primary key (trace_id, replica)   -- upsert: on_conflict="trace_id,replica"
);
create index if not exists trace_spans_updated_at
  on trace_spans (updated_at desc);

-- Durable flight records (solve analytics; store/base.py flight seam,
-- vrpms_tpu/obs/analytics.py): one row per (job_id, replica) holding
-- the completed solve's flight record as one bounded jsonb document —
-- device/host split and overlap ratio, padding + batch occupancy,
-- evals/sec, compile seconds, cache outcome, cost/gap/primal integral
-- (serialize_record bounds the doc, trimming the progress profile
-- first). The summary columns (finished_at epoch seconds, tier,
-- algorithm) exist for retention and grouped scans. Strictly
-- best-effort: writes are single-attempt behind the shared circuit
-- breaker (store/resilient.py) and an outage drops records, never
-- blocks a solve. Rows accumulate with traffic: pair with a retention
-- job, e.g. pg_cron:
--   delete from flight_records where updated_at < now() - '7 days';
-- (the in-memory backend bounds itself at store.memory
-- MAX_FLIGHT_ROWS).
create table if not exists flight_records (
  job_id text not null,
  replica text not null,
  finished_at double precision,     -- solve finish, epoch seconds
  tier text,                        -- padded tier label, e.g. vrp:64x8x1
  algorithm text,
  doc jsonb not null,               -- the flight record document
  updated_at timestamptz not null default now(),
  primary key (job_id, replica)     -- upsert: on_conflict="job_id,replica"
);
create index if not exists flight_records_updated_at
  on flight_records (updated_at desc);

-- Durable solve checkpoints (crash-resumable solves; store/base.py
-- checkpoint seam, service/checkpoint.py): one row per (job id,
-- attempt) holding the running solve's latest durable incumbent —
-- routes in original location ids, penalized cost, evals, elapsed,
-- and (decomposed giant solves) each completed shard's routes — so a
-- lease reclaim or watchdog requeue warm-resumes from it instead of
-- re-solving from zero. The background checkpointer refreshes the row
-- at the VRPMS_CKPT_MS cadence; reads take the LATEST attempt.
-- Strictly best-effort: writes are single-attempt behind the shared
-- circuit breaker (store/resilient.py) and a failed write only
-- increments vrpms_ckpt_total{dropped} — it never fails a solve.
-- Terminal ack/dead paths delete a job's rows (stale-checkpoint
-- hygiene), but crashed-and-abandoned jobs can orphan rows: pair with
-- a retention sweep like the trace_spans one, e.g. pg_cron:
--   delete from solve_checkpoints
--    where updated_at < now() - interval '1 day';
-- (the in-memory backend bounds itself at store.memory
-- MAX_CHECKPOINTS).
create table if not exists solve_checkpoints (
  job_id text not null,
  attempt integer not null default 1,
  state jsonb not null,             -- {problem, algorithm, routes,
                                    --  cost, evals, elapsedMs, shards?}
  updated_at timestamptz not null default now(),
  primary key (job_id, attempt)     -- upsert: on_conflict="job_id,attempt"
);
create index if not exists solve_checkpoints_updated_at
  on solve_checkpoints (updated_at);

-- Standing subscriptions (service/subscriptions.py): one row per
-- subscription holding its durable control-plane doc — the base
-- request content, cadence, generation counter, lineage tail, and
-- last launched job id. Rewritten at every generation boundary
-- (updated_at rides the payload, like the solution cache, so it
-- tracks write recency, not insert time). Any replica lists the
-- table to adopt due cadences after a drain or crash; DELETE
-- /api/subscriptions/{id} removes the row. No retention sweep — a
-- subscription lives until deleted (the in-memory backend bounds
-- itself at store.memory MAX_SUBSCRIPTIONS).
create table if not exists subscriptions (
  id text primary key,              -- upsert: on_conflict="id"
  doc jsonb not null,               -- {id, content, problem, algorithm,
                                    --  resolveEvery?, generation,
                                    --  lastJobId, lineage, ...}
  updated_at timestamptz not null default now()
);

-- Belt-and-braces stale-lease sweep: reclaim normally happens in every
-- replica's scan loop, but if ALL replicas die mid-lease the entries
-- sit leased until one comes back. A pg_cron job returns them to the
-- queue (and ages out dead replica heartbeats) on the server side.
-- The attempt ceiling MUST carry over: an entry already reclaimed once
-- (attempt >= 1) gets retired, not a third execution — the same
-- at-most-one-requeue rule the in-process reclaim enforces.
--   select cron.schedule('vrpms-stale-leases', '* * * * *', $$
--     update jobs set queue_state = 'queued', lease_owner = null,
--            lease_expires_at = null, attempt = attempt + 1
--      where queue_state = 'leased' and attempt < 1
--        and lease_expires_at < now() - interval '5 minutes';
--     update jobs set queue_state = null, lease_owner = null,
--            lease_expires_at = null, attempt = attempt + 1
--      where queue_state = 'leased' and attempt >= 1
--        and lease_expires_at < now() - interval '5 minutes';
--     delete from replicas where expires_at < now() - interval '5 minutes';
--   $$);
-- (retired entries keep their last persisted record; operators find
-- them via queue_state is null + attempt >= 2 and can re-submit)
