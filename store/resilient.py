"""ResilientDatabase: deadlines, retries, circuit breaker, degraded mode.

Wraps any `store.base.Database` backend so a slow, flaky, or down store
can never take the service with it (ROADMAP: graceful degradation under
partial failure). Policy per primitive call:

  * **deadline** — every backend call runs on a small shared thread
    pool and is abandoned after `VRPMS_STORE_DEADLINE_S` seconds, so an
    HTTP thread is never parked on a hung socket for longer than the
    configured bound;
  * **retries** — reads retry up to `VRPMS_STORE_RETRIES` times with
    jittered exponential backoff; solution/job/warm-start WRITES are
    attempted at most once inline (a blind client-side write retry
    against a store that may have committed is not idempotent-safe) and
    spool to the journal instead;
  * **circuit breaker** — closed -> open after `VRPMS_CB_FAILURES`
    consecutive-window failures; open sheds calls instantly (no thread
    stacking behind a dead backend); after `VRPMS_CB_RESET_S` one
    half-open probe is admitted and its outcome closes or re-opens.

Degraded mode (circuit open, or retries exhausted):

  * reads fall back to a bounded in-process read-through cache of
    last-known rows (writes also update it, so a job poll sees its own
    spooled record); owner-scoped rows are cached with the request's
    auth token in the key so degraded serving cannot leak across
    tenants;
  * writes spool into a bounded in-memory journal, replayed in order
    on a background thread once a call succeeds after recovery
    (at-least-once: a timed-out write that actually committed may
    replay — upserts are idempotent, solution inserts may duplicate;
    a direct write that lands post-recovery supersedes its key's
    spooled versions so replay never regresses a row);
  * any fallback-served call flips the instance's `degraded` flag, and
    the service marks the response `degraded: true`.

One deliberate exception to best-effort: an AUTHENTICATED save whose
owner cannot be resolved at all (store down, owner never cached this
process) still fails the request with the auth-error envelope —
identity is not best-effort, and spooling a solution row without a
verified owner would let a stale/forged token write under a guessed
identity on replay. Once a token's owner has been seen once, it is
cached and authed saves degrade gracefully like everything else.

Breaker/cache/journal state is process-wide per backend kind (store
instances are per-request); `reset_resilience()` clears it for tests.
Counters/gauges surface via service.obs (imported lazily — this module
stays importable standalone) and `/metrics` scrapes `circuit_states()`.
"""

from __future__ import annotations

import concurrent.futures
import random
import threading
import time

from store.base import Database, DatabaseTSP, DatabaseVRP
from vrpms_tpu import config
from vrpms_tpu.obs import log_event, spans

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
#: Prometheus encoding of breaker state (gauge value on /metrics).
STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

BACKOFF_CAP_S = 2.0


class StoreUnavailable(Exception):
    """The backend is unreachable and no degraded fallback applies."""


class StoreTimeout(Exception):
    """A backend call exceeded the per-call deadline."""


def _obs():
    """service.obs, if importable (lazy: keeps store -> service one-way
    at import time and this module usable without the service layer)."""
    try:
        from service import obs

        return obs
    except Exception:  # pragma: no cover - only without the service pkg
        return None


def backoff_s(attempt: int, base_s: float, rng=random) -> float:
    """Jittered exponential backoff for retry `attempt` (0-based): a
    uniform [0.5, 1.5) multiple of base * 2^attempt, capped so a large
    retry count cannot out-sleep the caller's own deadline budget."""
    return min(base_s * (2.0**attempt), BACKOFF_CAP_S) * (0.5 + rng.random())


class CircuitBreaker:
    """closed -> open -> half-open breaker, thread-safe.

    `allow()` gates calls: closed admits everything; open sheds until
    `reset_s` has elapsed, then flips to half-open and admits exactly
    ONE probe; the probe's success()/failure() closes or re-opens.
    """

    def __init__(self, threshold: int = 5, reset_s: float = 30.0,
                 clock=time.monotonic):
        self.threshold = max(1, threshold)
        self.reset_s = reset_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED  # guarded-by: _lock
        self._failures = 0  # guarded-by: _lock
        self._opened_at = 0.0  # guarded-by: _lock
        self._probing = False  # guarded-by: _lock

    def _tick_locked(self) -> None:
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.reset_s
        ):
            self._state = HALF_OPEN
            self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            self._tick_locked()
            return self._state

    def allow(self) -> bool:
        with self._lock:
            self._tick_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True  # exactly one in-flight probe
                return True
            return False

    def record_success(self) -> bool:
        """Returns True when this success RECOVERED the circuit (it was
        not closed) — the caller's cue to replay the write journal."""
        with self._lock:
            recovered = self._state != CLOSED
            self._state = CLOSED
            self._failures = 0
            self._probing = False
            return recovered

    def record_failure(self) -> bool:
        """Returns True when this failure OPENED the circuit."""
        with self._lock:
            self._tick_locked()
            if self._state == OPEN:
                return False  # straggler from an already-shed window
            self._failures += 1
            if self._state == HALF_OPEN or self._failures >= self.threshold:
                self._state = OPEN
                self._opened_at = self._clock()
                self._probing = False
                return True
            return False


class FallbackStore:
    """Bounded last-known-row map: read-through on successful reads,
    write-back on spooled writes (degraded reads see their own writes).
    Insertion-ordered dict eviction = drop the stalest entry first."""

    def __init__(self, limit: int = 256):
        self.limit = max(1, limit)
        self._lock = threading.Lock()
        self._rows: dict = {}  # guarded-by: _lock

    def get(self, key):
        with self._lock:
            if key in self._rows:
                value = self._rows.pop(key)
                self._rows[key] = value  # refresh recency
                return True, value
            return False, None

    def put(self, key, value) -> None:
        with self._lock:
            self._rows.pop(key, None)
            self._rows[key] = value
            while len(self._rows) > self.limit:
                self._rows.pop(next(iter(self._rows)))

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)


class WriteJournal:
    """Bounded FIFO of spooled writes, replayed in order on recovery.
    Overflow drops the OLDEST entry (keep the newest state; upserts
    make later entries supersede earlier ones anyway) and counts it.

    Entries carry the write's fallback key (None for append-only
    inserts) so a DIRECT write that succeeds after recovery supersedes
    any stale spooled version of the same key: `discard(key)` removes
    queued entries and tombstones the key, and the replayer skips
    drained-but-tombstoned entries — otherwise replay could regress a
    record (e.g. a job back from 'done' to 'running'). A later append
    for the key lifts its tombstone (new outage, new truth).

    Entries also carry the backend INSTANCE that spooled them (`target`
    — it holds the request's auth session, so an authed write never
    replays through some other request's anon client) and a replay
    attempt count (a persistently-rejected entry — e.g. an RLS denial —
    is dropped after MAX_REPLAY_ATTEMPTS instead of head-of-line
    blocking every entry behind it forever)."""

    MAX_TOMBSTONES = 4096  # runaway bound; clearing only widens the
                           # (already tiny) drained-entry race window
    MAX_REPLAY_ATTEMPTS = 3

    def __init__(self, limit: int = 512):
        self.limit = max(1, limit)
        self._lock = threading.Lock()
        self._entries: list = []  # guarded-by: _lock
        self._tombstones: set = set()  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock

    def append(self, method: str, args: tuple, key=None, target=None) -> None:
        with self._lock:
            self._tombstones.discard(key)
            self._entries.append((method, args, key, target, 0))
            while len(self._entries) > self.limit:
                self._entries.pop(0)
                self.dropped += 1

    def discard(self, key) -> None:
        """A direct write for `key` just committed: every spooled
        version of it (queued or already drained) is now stale."""
        if key is None:
            return
        with self._lock:
            self._entries = [e for e in self._entries if e[2] != key]
            self._tombstones.add(key)
            if len(self._tombstones) > self.MAX_TOMBSTONES:
                self._tombstones.clear()  # lose staleness info, not data

    def stale(self, key) -> bool:
        if key is None:
            return False
        with self._lock:
            return key in self._tombstones

    def drain(self) -> list:
        with self._lock:
            entries, self._entries = self._entries, []
            return entries

    def push_front(self, entries: list) -> None:
        with self._lock:
            self._entries[:0] = entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class _Resilience:
    """Process-wide shared state for one backend kind."""

    def __init__(self):
        self.breaker = CircuitBreaker(
            threshold=config.get("VRPMS_CB_FAILURES"),
            reset_s=config.get("VRPMS_CB_RESET_S"),
        )
        self.fallback = FallbackStore(config.get("VRPMS_STORE_CACHE"))
        self.journal = WriteJournal(config.get("VRPMS_STORE_JOURNAL"))
        self.replay_lock = threading.Lock()


_state_lock = threading.Lock()
_states: dict[str, _Resilience] = {}  # guarded-by: _state_lock
_executor: concurrent.futures.ThreadPoolExecutor | None = None  # guarded-by: _state_lock


def _resilience_for(kind: str) -> _Resilience:
    with _state_lock:
        st = _states.get(kind)
        if st is None:
            st = _states[kind] = _Resilience()
        return st


def reset_resilience() -> None:
    """Drop all breaker/cache/journal state (tests, ops escape hatch)."""
    with _state_lock:
        _states.clear()


def circuit_states() -> dict[str, str]:
    """{backend kind: closed|half-open|open} — /metrics + /api/ready."""
    with _state_lock:
        pairs = list(_states.items())
    return {kind: st.breaker.state for kind, st in pairs}


def journal_depths() -> dict[str, int]:
    with _state_lock:
        pairs = list(_states.items())
    return {kind: len(st.journal) for kind, st in pairs}


def _get_executor() -> concurrent.futures.ThreadPoolExecutor:
    global _executor
    with _state_lock:
        if _executor is None:
            _executor = concurrent.futures.ThreadPoolExecutor(
                max_workers=config.get("VRPMS_STORE_POOL"),
                thread_name_prefix="vrpms-store",
            )
        return _executor


class _ResilientMixin(Database):
    def __init__(self, inner: Database, kind: str):
        super().__init__(inner.auth)
        self.inner = inner
        self.kind = kind
        self.degraded = False  # any fallback served this request
        self._res = _resilience_for(kind)
        # per-instance (= per-request) knobs, re-read so tests and live
        # tuning apply without clearing the shared breaker state
        self.deadline_s = config.get("VRPMS_STORE_DEADLINE_S")
        self.retries = config.get("VRPMS_STORE_RETRIES")
        self.backoff_base_s = config.get("VRPMS_STORE_BACKOFF_S")

    # -- call plumbing ------------------------------------------------------
    def _attempt(self, method: str, args: tuple, timeout=None,
                 target: Database | None = None):
        """One backend call under a deadline (default: the configured
        per-call deadline). A timed-out call is abandoned (its pool
        thread stays busy until the backend lets go — the breaker is
        what stops those from stacking up). `target` lets the journal
        replay a write through the INSTANCE that spooled it (its auth
        session), not whichever request witnessed recovery."""
        if timeout is None:
            timeout = self.deadline_s if self.deadline_s > 0 else None
        fut = _get_executor().submit(
            getattr(target or self.inner, method), *args
        )
        try:
            return fut.result(timeout=timeout)
        except concurrent.futures.TimeoutError:
            fut.cancel()
            raise StoreTimeout(
                f"store {method} exceeded its deadline "
                f"({0.0 if timeout is None else timeout:.3f}s)"
            ) from None

    def _note_failure(self, method: str, exc: Exception) -> None:
        obs = _obs()
        if obs is not None:
            reason = "timeout" if isinstance(exc, StoreTimeout) else "error"
            obs.STORE_FAILURES.labels(kind=self.kind, reason=reason).inc()
        if self._res.breaker.record_failure():
            log_event(
                "store.circuit_open",
                kind=self.kind,
                error=f"{type(exc).__name__}: {exc}",
            )

    def _note_success(self) -> None:
        if self._res.breaker.record_success():
            log_event("store.circuit_closed", kind=self.kind)
        self._maybe_replay()

    def _served_fallback(self, source: str, method: str) -> None:
        self.degraded = True
        obs = _obs()
        if obs is not None:
            obs.STORE_FALLBACKS.labels(kind=self.kind, source=source).inc()
        log_event("store.fallback", kind=self.kind, source=source,
                  method=method)

    # -- read path: deadline + retries + cache fallback ---------------------
    def _read(self, method: str, args: tuple, cache_key=None):
        # the resilience story joins the request's trace: each guarded
        # call is one span recording attempts/retries, the breaker
        # state it saw, and whether a degraded fallback served it —
        # the "store retry storm" a p99 spike needs attributed
        with spans.span(
            "store.resilient", op="read", method=method.lstrip("_"),
            kind=self.kind,
        ) as sp:
            return self._read_guarded(method, args, cache_key, sp)

    def _read_guarded(self, method: str, args: tuple, cache_key, sp):
        # the deadline bounds the WHOLE read — attempts and backoff
        # sleeps share it, so retries help against fast flaky errors
        # but a hung backend costs exactly one deadline, never
        # (retries+1) of them (the "no HTTP thread blocks longer than
        # the store deadline" contract)
        res = self._res
        last_exc = None
        t0 = time.monotonic()
        budget = self.deadline_s if self.deadline_s > 0 else None
        for attempt in range(self.retries + 1):
            remaining = None
            if budget is not None:
                remaining = budget - (time.monotonic() - t0)
                if remaining <= 0:
                    break  # the read's whole budget is spent
            if not res.breaker.allow():
                if sp is not None:
                    sp.set(breaker=res.breaker.state)
                break  # shed instantly; fall through to the cache
            try:
                value = self._attempt(method, args, timeout=remaining)
            except Exception as exc:
                last_exc = exc
                self._note_failure(method, exc)
                if sp is not None:
                    sp.event(
                        "store.retry",
                        attempt=attempt,
                        error=f"{type(exc).__name__}: {exc}",
                    )
                if attempt < self.retries:
                    obs = _obs()
                    if obs is not None:
                        obs.STORE_RETRIES.labels(kind=self.kind).inc()
                    delay = backoff_s(attempt, self.backoff_base_s)
                    if budget is not None:
                        delay = min(
                            delay,
                            max(0.0, budget - (time.monotonic() - t0)),
                        )
                    time.sleep(delay)
                continue
            self._note_success()
            if sp is not None and attempt:
                sp.set(attempts=attempt + 1)
            if cache_key is not None:
                res.fallback.put(cache_key, value)
            return value
        if cache_key is not None:
            hit, value = res.fallback.get(cache_key)
            if hit:
                self._served_fallback("cache", method)
                if sp is not None:
                    sp.set(
                        fallback="cache", degraded=True,
                        breaker=res.breaker.state,
                    )
                return value
        if last_exc is not None:
            raise StoreUnavailable(
                f"store {method} failed ({type(last_exc).__name__}: "
                f"{last_exc}) and no cached fallback exists"
            ) from last_exc
        raise StoreUnavailable(
            f"store circuit open and no cached fallback for {method}"
        )

    # -- write path: at-most-once inline, then the journal ------------------
    def _write(self, method: str, args: tuple, fallback_row=None,
               sentinel=None):
        with spans.span(
            "store.resilient", op="write", method=method.lstrip("_"),
            kind=self.kind,
        ) as sp:
            return self._write_guarded(method, args, fallback_row, sentinel, sp)

    def _write_guarded(self, method: str, args: tuple, fallback_row,
                       sentinel, sp):
        res = self._res
        key = fallback_row[0] if fallback_row is not None else None
        if res.breaker.allow():
            try:
                value = self._attempt(method, args)
            except Exception as exc:
                self._note_failure(method, exc)
                if sp is not None:
                    sp.event(
                        "store.write_failed",
                        error=f"{type(exc).__name__}: {exc}",
                    )
            else:
                # supersede any stale spooled version of this key
                # BEFORE _note_success can kick off a replay — a
                # journal replay must never regress the row this call
                # just committed
                res.journal.discard(key)
                self._note_success()
                if fallback_row is not None:
                    res.fallback.put(*fallback_row)
                return value
        # pin the spooling instance as the replay target ONLY for
        # authenticated writes (its auth session is what must not leak
        # through another request's anon client); unauthenticated
        # writes replay through whichever healthy inner observes the
        # recovery — pinning them would freeze a stale client instead
        res.journal.append(
            method, args, key, target=self.inner if self.auth else None
        )
        if fallback_row is not None:
            res.fallback.put(*fallback_row)  # degraded reads see the write
        self._served_fallback("journal", method)
        if sp is not None:
            sp.set(
                fallback="journal", degraded=True,
                breaker=res.breaker.state, journalDepth=len(res.journal),
            )
        log_event("store.journal_spool", kind=self.kind, method=method,
                  depth=len(res.journal))
        return sentinel

    def _maybe_replay(self) -> None:
        """Kick off a journal flush on a background thread: a journal
        can hold hundreds of entries, each worth up to a deadline —
        serially replaying them inline would park the one user request
        that happened to witness the recovery for minutes."""
        res = self._res
        if not len(res.journal):
            return
        threading.Thread(
            target=self._replay, name="vrpms-store-replay", daemon=True
        ).start()

    def _replay(self) -> None:
        """Flush the journal through the (healthy again) backend.

        One replayer at a time. Each entry replays through the instance
        that spooled it (right auth session). A failed entry re-queues
        with its attempt count bumped and BLOCKS later entries for the
        same key (per-key order is the correctness constraint);
        independent keys keep replaying. Entries that keep failing are
        dropped after MAX_REPLAY_ATTEMPTS — a poison entry (say, an RLS
        denial) must not head-of-line block everything behind it at
        every recovery until overflow. If the breaker re-opens mid-
        replay (backend down again) the untouched tail re-queues as-is.
        """
        res = self._res
        if not res.replay_lock.acquire(blocking=False):
            return  # a replay is already running
        try:
            entries = res.journal.drain()
            requeue: list = []
            blocked_keys: set = set()
            replayed = 0
            for i, entry in enumerate(entries):
                method, args, key, target, attempts = entry
                if res.journal.stale(key):
                    continue
                if key is not None and key in blocked_keys:
                    requeue.append(entry)
                    continue
                try:
                    self._attempt(method, args, target=target)
                    replayed += 1
                except Exception as exc:
                    self._note_failure(method, exc)
                    if attempts + 1 >= res.journal.MAX_REPLAY_ATTEMPTS:
                        log_event(
                            "store.journal_entry_dropped",
                            kind=self.kind,
                            method=method,
                            attempts=attempts + 1,
                            error=f"{type(exc).__name__}: {exc}",
                        )
                    else:
                        requeue.append(
                            (method, args, key, target, attempts + 1)
                        )
                        if key is not None:
                            blocked_keys.add(key)
                    if res.breaker.state == OPEN:
                        requeue.extend(entries[i + 1:])
                        log_event(
                            "store.journal_replay_stalled",
                            kind=self.kind,
                            replayed=replayed,
                            remaining=len(requeue),
                            error=f"{type(exc).__name__}: {exc}",
                        )
                        break
            res.journal.push_front(requeue)
            if replayed:
                obs = _obs()
                if obs is not None:
                    obs.STORE_REPLAYS.labels(kind=self.kind).inc(replayed)
                log_event("store.journal_replayed", kind=self.kind,
                          entries=replayed)
        finally:
            res.replay_lock.release()

    # -- guarded primitives -------------------------------------------------
    def _fetch_row(self, table, row_id):
        # auth in the key: a row readable under one token must not be
        # served from cache to another (RLS-scoped backends)
        return self._read(
            "_fetch_row", (table, row_id),
            cache_key=("row", table, str(row_id), self.auth),
        )

    def _owner_email(self):
        key = ("owner", self.auth) if self.auth else None
        return self._read("_owner_email", (), cache_key=key)

    def _fetch_warmstart(self, owner, name):
        return self._read(
            "_fetch_warmstart", (owner, name),
            cache_key=("warmstarts", owner, str(name)),
        )

    def _fetch_job(self, job_id):
        return self._read(
            "_fetch_job", (job_id,), cache_key=("jobs", str(job_id))
        )

    def _insert_solution(self, data):
        # sentinel: a spooled save still answers the contract's 200 (the
        # envelope gains degraded: true instead of a write error)
        return self._write("_insert_solution", (data,), sentinel=data)

    def _upsert_warmstart(self, owner, name, state):
        return self._write(
            "_upsert_warmstart", (owner, name, state),
            fallback_row=(
                ("warmstarts", owner, str(name)),
                {"owner": owner, "name": name, "state": state},
            ),
        )

    def _upsert_warmstart_guarded(self, owner, name, state, better_than):
        # delegate the WHOLE guarded sequence to the inner store while
        # it is healthy: backends with an atomic keep-best (the
        # in-memory store's table-lock version) keep their atomicity —
        # running the base class's fetch/compare/write here would
        # silently reintroduce the concurrent-checkpoint race the
        # override exists to prevent. Degraded, fall back to the base
        # sequence over the guarded primitives (cache + journal).
        res = self._res
        if res.breaker.allow():
            try:
                wrote = self._attempt(
                    "_upsert_warmstart_guarded",
                    (owner, name, state, better_than),
                )
            except Exception as exc:
                self._note_failure("_upsert_warmstart_guarded", exc)
            else:
                self._note_success()
                if wrote:
                    res.fallback.put(
                        ("warmstarts", owner, str(name)),
                        {"owner": owner, "name": name, "state": state},
                    )
                return wrote
        return super()._upsert_warmstart_guarded(
            owner, name, state, better_than
        )

    def _upsert_job(self, job_id, record):
        return self._write(
            "_upsert_job", (job_id, record),
            fallback_row=(
                ("jobs", str(job_id)), {"id": job_id, "record": record}
            ),
        )

    # -- solution-cache primitives: single attempt, fail fast ---------------
    # The content cache (service.cache) is a pure optimization whose
    # safe answer is always "miss", so its resilience policy inverts
    # the read path's: NO retries (a retry storm on the pre-solve hot
    # path defeats the cache's purpose), NO degraded-cache fallback and
    # NO degraded flag (a missed lookup solves normally — nothing about
    # the response is best-effort), and NO journal spooling for writes
    # (cache entries are recomputable; spooling them would burn bounded
    # journal slots that job records and checkpoints need during an
    # outage). Calls still run under the per-call deadline and feed the
    # shared circuit breaker, so a down store costs at most one deadline
    # before the open circuit sheds cache traffic instantly.
    def _cache_call(self, method: str, args: tuple):
        res = self._res
        if not res.breaker.allow():
            raise StoreUnavailable(f"store circuit open for {method}")
        try:
            value = self._attempt(method, args)
        except Exception as exc:
            self._note_failure(method, exc)
            raise
        self._note_success()
        return value

    def _fetch_cache_family(self, family):
        return self._cache_call("_fetch_cache_family", (family,))

    def _fetch_cached_solution(self, key):
        return self._cache_call("_fetch_cached_solution", (key,))

    def _upsert_cached_solution(self, key, family, entry):
        return self._cache_call(
            "_upsert_cached_solution", (key, family, entry)
        )

    # -- trace-export primitives: the cache's inverted policy ---------------
    # Exported traces are debug evidence, recomputable from nothing:
    # a failed write is a dropped trace (counted by the exporter), a
    # failed read degrades the federated debug surface to local-only
    # with an honest marker. So: single attempt, NO retries (the
    # exporter flushes on a background thread, but the federated READS
    # run on debug-request HTTP threads), NO degraded-cache fallback
    # (stale spans presented as the fleet view would lie), NO journal
    # spooling (trace rows must never compete with job records for
    # bounded journal slots during an outage) — while the per-call
    # deadline and the shared circuit breaker still apply, so a down
    # store costs one deadline before the open circuit sheds trace
    # traffic instantly.
    # -- checkpoint primitives: the cache's inverted policy too -------------
    # A checkpoint is recoverable state whose safe answer is always
    # "none" (resume degrades to solving from zero): single attempt, NO
    # retries (writes run on the background checkpointer but READS sit
    # on the claim path of every reclaimed job), NO degraded-cache
    # fallback (a stale checkpoint served as fresh could resume a job
    # backwards), NO journal spooling (checkpoint rows must never
    # compete with job records for bounded journal slots during an
    # outage — they are refreshed at the next cadence tick anyway).
    # The per-call deadline and shared breaker still apply. The
    # federated job-read path (service.jobs: checkpoint-sourced
    # incumbent overlays for non-owning replicas) rides this same
    # primitive, so per-poll checkpoint reads stay bounded-cost under
    # an outage: one deadline, then the open breaker sheds instantly
    # and the poll degrades to a marked store-record response.
    def _fetch_checkpoint(self, job_id):
        return self._cache_call("_fetch_checkpoint", (job_id,))

    def _upsert_checkpoint(self, job_id, attempt, state):
        return self._cache_call(
            "_upsert_checkpoint", (job_id, attempt, state)
        )

    def _delete_checkpoint(self, job_id):
        return self._cache_call("_delete_checkpoint", (job_id,))

    # -- subscription primitives: same inverted policy ----------------------
    # A subscription row is control-plane state whose safe answer is
    # "none"/"unknown": the manager keeps serving from its in-process
    # doc, a missed list delays cadence adoption one tick, and a
    # dropped write is rewritten at the next generation boundary.
    # Single attempt, no degraded-cache fallback, no journal spooling
    # (subscription docs must not compete with job records for bounded
    # journal slots); the per-call deadline and shared breaker apply.
    def _fetch_subscription(self, sub_id):
        return self._cache_call("_fetch_subscription", (sub_id,))

    def _list_subscriptions(self):
        return self._cache_call("_list_subscriptions", ())

    def _upsert_subscription(self, sub_id, doc):
        return self._cache_call("_upsert_subscription", (sub_id, doc))

    def _delete_subscription(self, sub_id):
        return self._cache_call("_delete_subscription", (sub_id,))

    def _put_trace_rows(self, rows):
        return self._cache_call("_put_trace_rows", (rows,))

    def _fetch_trace_rows(self, trace_id):
        return self._cache_call("_fetch_trace_rows", (trace_id,))

    def _list_trace_rows(self, limit):
        return self._cache_call("_list_trace_rows", (limit,))

    # -- flight-record primitives: the trace rows' exact policy -------------
    # A flight record is rollup evidence, recomputable from nothing: a
    # failed write is a dropped record (counted by the analytics
    # exporter), a failed read degrades /api/debug/analytics to
    # local-only with an honest marker. Single attempt, no retries, no
    # degraded-cache fallback, no journal spooling; the per-call
    # deadline and shared breaker still apply.
    def _put_flight_rows(self, rows):
        return self._cache_call("_put_flight_rows", (rows,))

    def _fetch_flight_rows(self, limit):
        return self._cache_call("_fetch_flight_rows", (limit,))


class ResilientDatabaseVRP(_ResilientMixin, DatabaseVRP):
    pass


class ResilientDatabaseTSP(_ResilientMixin, DatabaseTSP):
    pass


def wrap(inner: Database, kind: str, problem: str) -> Database:
    """Wrap a constructed backend in the resilience policy."""
    cls = ResilientDatabaseVRP if problem == "vrp" else ResilientDatabaseTSP
    return cls(inner, kind)
