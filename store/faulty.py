"""Fault-injecting store: the in-memory backend behind a chaos plan.

Selected with `VRPMS_STORE=faulty:<plan>` (plan DSL:
vrpms_tpu.testing.faults). Every primitive store operation first runs
the plan's injector — latency, jittered latency, hang, fail-N-then-
succeed, error-rate, or hard-down — then delegates to the in-memory
tables, so tests and chaos benchmarks exercise the service's real
degradation paths (store.resilient) against real data.

Injectors are process-wide, keyed by plan text: "fail the first 3
calls" counts across the per-request store instances the service
constructs, and a test can flip plans mid-run just by changing the env
var (each request re-reads it). `reset_faults()` restarts the counters.
"""

from __future__ import annotations

import threading

from store.memory import InMemoryJobQueue, _InMemoryMixin
from store.base import DatabaseTSP, DatabaseVRP
from vrpms_tpu.testing.faults import FaultInjector, parse_plan

_lock = threading.Lock()
_injectors: dict[str, FaultInjector] = {}


def injector_for(plan_text: str) -> FaultInjector:
    """The process-wide injector for this plan (parse validates it)."""
    with _lock:
        inj = _injectors.get(plan_text)
        if inj is None:
            inj = _injectors[plan_text] = FaultInjector(parse_plan(plan_text))
        return inj


def reset_faults() -> None:
    """Forget all injectors (fail-N counters restart) — test hygiene."""
    with _lock:
        _injectors.clear()


class _FaultyMixin(_InMemoryMixin):
    def __init__(self, auth=None, plan: str = ""):
        super().__init__(auth)
        self._injector = injector_for(plan)

    # -- reads --------------------------------------------------------------
    def _fetch_row(self, table, row_id):
        self._injector.apply("read")
        return super()._fetch_row(table, row_id)

    def _owner_email(self):
        self._injector.apply("read")
        return super()._owner_email()

    def _fetch_warmstart(self, owner, name):
        self._injector.apply("read")
        return super()._fetch_warmstart(owner, name)

    def _fetch_job(self, job_id):
        self._injector.apply("read")
        return super()._fetch_job(job_id)

    def _fetch_cache_family(self, family):
        self._injector.apply("read")
        return super()._fetch_cache_family(family)

    def _fetch_cached_solution(self, key):
        self._injector.apply("read")
        return super()._fetch_cached_solution(key)

    def _fetch_trace_rows(self, trace_id):
        self._injector.apply("read")
        return super()._fetch_trace_rows(trace_id)

    def _fetch_checkpoint(self, job_id):
        self._injector.apply("read")
        return super()._fetch_checkpoint(job_id)

    def _fetch_flight_rows(self, limit):
        # the federated analytics read; a plan that downs reads must
        # degrade /api/debug/analytics to local-only, never a 500
        self._injector.apply("read")
        return super()._fetch_flight_rows(limit)

    def _list_trace_rows(self, limit):
        self._injector.apply("read")
        return super()._list_trace_rows(limit)

    def _fetch_subscription(self, sub_id):
        self._injector.apply("read")
        return super()._fetch_subscription(sub_id)

    def _list_subscriptions(self):
        self._injector.apply("read")
        return super()._list_subscriptions()

    # -- writes -------------------------------------------------------------
    def _insert_solution(self, data):
        self._injector.apply("write")
        return super()._insert_solution(data)

    def _upsert_warmstart(self, owner, name, state):
        self._injector.apply("write")
        return super()._upsert_warmstart(owner, name, state)

    def _upsert_job(self, job_id, record):
        self._injector.apply("write")
        return super()._upsert_job(job_id, record)

    def _upsert_cached_solution(self, key, family, entry):
        self._injector.apply("write")
        return super()._upsert_cached_solution(key, family, entry)

    def _put_trace_rows(self, rows):
        # one injection per exporter batch (it is ONE upsert on the
        # real backend), so a plan fails the whole batch or none —
        # the exporter's failed counter ticks once per batch's spans
        self._injector.apply("write")
        return super()._put_trace_rows(rows)

    def _put_flight_rows(self, rows):
        # one injection per exporter batch (ONE upsert on the real
        # backend): a plan fails the whole batch or none — the
        # analytics exporter's failed counter ticks once per record
        self._injector.apply("write")
        return super()._put_flight_rows(rows)

    def _upsert_checkpoint(self, job_id, attempt, state):
        # a failed checkpoint write must only ever increment
        # vrpms_ckpt_total{dropped} — never fail (or slow) the solve it
        # shadows; tests/test_chaos.py pins that under live plans
        self._injector.apply("write")
        return super()._upsert_checkpoint(job_id, attempt, state)

    def _delete_checkpoint(self, job_id):
        self._injector.apply("write")
        return super()._delete_checkpoint(job_id)

    def _upsert_subscription(self, sub_id, doc):
        # a failed subscription write degrades the durable copy only —
        # the manager's in-process doc keeps serving, and the next
        # generation boundary rewrites the row
        self._injector.apply("write")
        return super()._upsert_subscription(sub_id, doc)

    def _delete_subscription(self, sub_id):
        self._injector.apply("write")
        return super()._delete_subscription(sub_id)


class FaultyDatabaseVRP(_FaultyMixin, DatabaseVRP):
    pass


class FaultyDatabaseTSP(_FaultyMixin, DatabaseTSP):
    pass


class FaultyJobQueue(InMemoryJobQueue):
    """The in-memory shared queue behind the same chaos plan: claims,
    renews, and reclaims count as reads (polling), mutations of the
    queue's durable truth (enqueue/ack/nack) as writes — so
    `ops=reads`/`ops=writes` plans can fail the lease machinery and the
    admission path independently. The replica loop's exactly-once
    contract must hold under every plan (tests/test_distqueue.py)."""

    def __init__(self, plan: str = ""):
        self._injector = injector_for(plan)

    def enqueue(self, entry):
        self._injector.apply("write")
        return super().enqueue(entry)

    def claim(self, owner, lease_s, slots=None):
        self._injector.apply("read")
        return super().claim(owner, lease_s, slots)

    def claim_batch(self, owner, lease_s, k, slots=None):
        # one injection per batched claim (it is ONE conditional update
        # on the real backends), so a fault plan fails the whole batch
        # or none of it — never a half-leased set
        self._injector.apply("read")
        return super().claim_batch(owner, lease_s, k, slots)

    def renew(self, owner, job_id, lease_s):
        self._injector.apply("read")
        return super().renew(owner, job_id, lease_s)

    def ack(self, owner, job_id):
        self._injector.apply("write")
        return super().ack(owner, job_id)

    def nack(self, owner, job_id, note=None):
        self._injector.apply("write")
        return super().nack(owner, job_id, note)

    def reclaim_expired(self, max_attempts=None):
        self._injector.apply("read")
        return super().reclaim_expired(max_attempts)

    def depth(self):
        self._injector.apply("read")
        return super().depth()

    def depth_by_class(self):
        self._injector.apply("read")
        return super().depth_by_class()

    def tenant_depths(self):
        # quota accounting is a read; a plan that downs reads must make
        # admission fail OPEN (the service treats None/raise as
        # unknown), which this injection exercises
        self._injector.apply("read")
        return super().tenant_depths()

    def get_entry(self, job_id):
        # the federated owner lookup; a plan that downs reads must
        # degrade the read path to checkpoint/marked responses, never
        # a 500
        self._injector.apply("read")
        return super().get_entry(job_id)

    def register_replica(self, replica_id, ttl_s, info=None):
        self._injector.apply("read")
        return super().register_replica(replica_id, ttl_s, info)

    def replicas(self):
        self._injector.apply("read")
        return super().replicas()

    def replica_infos(self):
        # the fleet rollup's cross-replica read; a plan that downs
        # reads must degrade it to membership-ids-only, never a 500
        self._injector.apply("read")
        return super().replica_infos()

    def deregister_replica(self, replica_id):
        # drain's heartbeat removal is best-effort: a plan that downs
        # writes must leave TTL expiry as the fallback, never crash
        # the drain
        self._injector.apply("write")
        return super().deregister_replica(replica_id)
