"""Request/response helpers — byte-for-byte envelope parity.

Same four helpers as the reference (api/helpers.py): missing-parameter
accumulation into a shared mutable errors list, location filtering for
persistence, and the fail/success JSON envelopes.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler


def get_parameter(name: str, content: dict, errors, optional=False):
    if name not in content and not optional:
        errors += [
            {"what": "Missing parameter", "reason": f"'{name}' was not provided"}
        ]
    return content.get(name)


def remove_unused_locations(locations, ignored_customers, completed_customers):
    disregard = ignored_customers + completed_customers
    return [loc for loc in locations if loc["id"] not in disregard]


def fail(handler: BaseHTTPRequestHandler, errors):
    handler.send_response(400)
    handler.send_header("Content-type", "application/json")
    handler.end_headers()
    response = {"success": False, "errors": errors}
    handler.wfile.write(json.dumps(response).encode("utf-8"))


def success(handler: BaseHTTPRequestHandler, result: dict):
    handler.send_response(200)
    handler.send_header("Content-type", "application/json")
    handler.end_headers()
    response = {"success": True, "message": result}
    handler.wfile.write(json.dumps(response).encode("utf-8"))
