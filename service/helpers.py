"""Request/response helpers — byte-for-byte envelope parity.

Same four helpers as the reference (api/helpers.py): missing-parameter
accumulation into a shared mutable errors list, location filtering for
persistence, and the fail/success JSON envelopes. Additive fields on
every envelope (success and error alike, 400/429/503 included):
`requestId` and `traceId` (when the handler generated them) so any
response — including the sheds and outage answers — correlates with
its structured log lines and its trace (GET /api/debug/traces/{id});
responses also carry a W3C `traceparent` header. The reference keys
are untouched.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler

from service import obs


def read_json_body(handler: BaseHTTPRequestHandler) -> dict | None:
    """The shared POST intake ladder: Content-Length hardening, body-size
    observation, JSON parse. Writes the contract's 400 envelope and
    returns None on any failure; an empty body is a valid empty dict.
    One implementation for every submit surface (handler_base, jobs) so
    hardening fixes can never drift between them."""
    raw_length = handler.headers.get("Content-Length")
    try:
        content_length = int(raw_length or 0)
        if content_length < 0:
            raise ValueError(raw_length)
    except (TypeError, ValueError):
        # a malformed/absent Content-Length must produce the contract's
        # 400 envelope, not a ValueError-killed connection
        fail(handler, [{
            "what": "Bad request",
            "reason": f"invalid Content-Length header: {raw_length!r}",
        }])
        return None
    handler._obs_body_bytes = content_length
    obs.BODY_BYTES.observe(content_length)
    content_string = str(handler.rfile.read(content_length).decode("utf-8"))
    try:
        return json.loads(content_string) if content_string else dict()
    except json.JSONDecodeError as e:
        fail(handler, [{"what": "Bad request", "reason": f"invalid JSON: {e}"}])
        return None


def get_parameter(name: str, content: dict, errors, optional=False):
    if name not in content and not optional:
        errors += [
            {"what": "Missing parameter", "reason": f"'{name}' was not provided"}
        ]
    return content.get(name)


def remove_unused_locations(locations, ignored_customers, completed_customers):
    disregard = ignored_customers + completed_customers
    return [loc for loc in locations if loc["id"] not in disregard]


def send_static_headers(handler: BaseHTTPRequestHandler):
    """Route-attached response headers (the reference's edge config pins
    CORS headers to every /api/vrp/ga RESPONSE, not just the OPTIONS
    preflight — reference vercel.json:4-11). Handlers opt in via a
    `static_headers` class attribute; emitted by every response writer,
    together with the request's outgoing `traceparent`."""
    for key, value in getattr(handler, "static_headers", ()):
        handler.send_header(key, value)
    for key, value in obs.trace_response_headers(handler):
        handler.send_header(key, value)


def attach_ids(handler, response: dict) -> dict:
    """Echo the request id and trace id into an envelope (every writer,
    every status code — a 429 shed or a 503 outage answer must be as
    correlatable as a 400)."""
    rid = getattr(handler, "_request_id", None)
    if rid is not None and "requestId" not in response:
        response["requestId"] = rid
    tid = getattr(handler, "_trace_id", None)
    if tid is not None and "traceId" not in response:
        response["traceId"] = tid
    return response


def respond_json(handler: BaseHTTPRequestHandler, code: int,
                 payload: dict) -> None:
    """The one JSON responder for envelope-shaped non-solve routes
    (jobs API, readiness, debug traces): ids attached, static +
    traceparent headers emitted."""
    payload = attach_ids(handler, dict(payload))
    body = json.dumps(payload).encode("utf-8")
    handler.send_response(code)
    handler.send_header("Content-type", "application/json")
    send_static_headers(handler)
    handler.end_headers()
    handler.wfile.write(body)


def fail(handler: BaseHTTPRequestHandler, errors):
    kinds = [e.get("what", "unknown") for e in errors]
    for what in kinds:
        obs.ERROR_KINDS.labels(what=what).inc()
    handler._obs_errors = sorted(set(kinds))  # for the access log line
    handler.send_response(400)
    handler.send_header("Content-type", "application/json")
    send_static_headers(handler)
    handler.end_headers()
    response = attach_ids(handler, {"success": False, "errors": errors})
    handler.wfile.write(json.dumps(response).encode("utf-8"))


def too_busy(handler: BaseHTTPRequestHandler, retry_after_s: float,
             reason: str | None = None):
    """Backpressure response: 429 + Retry-After (admission queue full,
    or — `reason` given — another QoS shed such as a per-tenant quota).

    The scheduler's whole point is that overload sheds IMMEDIATELY with
    a machine-readable retry hint instead of accepting work that would
    start with a spent deadline budget (or holding the connection)."""
    import math

    obs.ERROR_KINDS.labels(what="Too busy").inc()
    handler._obs_errors = ["Too busy"]
    handler.send_response(429)
    handler.send_header("Content-type", "application/json")
    handler.send_header(
        "Retry-After", str(max(1, int(math.ceil(retry_after_s))))
    )
    send_static_headers(handler)
    handler.end_headers()
    response = attach_ids(handler, {
        "success": False,
        "errors": [
            {
                "what": "Too busy",
                "reason": reason
                or "solver admission queue is full; retry after the "
                "Retry-After interval",
            }
        ],
    })
    handler.wfile.write(json.dumps(response).encode("utf-8"))


def success(handler: BaseHTTPRequestHandler, result: dict):
    handler.send_response(200)
    handler.send_header("Content-type", "application/json")
    send_static_headers(handler)
    handler.end_headers()
    response = attach_ids(handler, {"success": True, "message": result})
    handler.wfile.write(json.dumps(response).encode("utf-8"))
