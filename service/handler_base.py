"""Shared endpoint pipeline (the reference repeats this skeleton 8 times;
here it lives once and each endpoint module binds its constants).

Pipeline parity with reference handlers (SURVEY.md §3.1): read body ->
parse params (error accumulation) -> 400 ladder -> fetch locations +
durations from the store -> run algorithm -> save-if-authenticated ->
200 envelope. The VRP save filters ignored/completed locations exactly
like the reference (api/vrp/ga/index.py:57-65); the TSP save does not
(api/tsp/bf/index.py:46-53).
"""

from __future__ import annotations

from http.server import BaseHTTPRequestHandler

import store
from service.helpers import (
    fail,
    read_json_body,
    remove_unused_locations,
    send_static_headers,
    success,
    too_busy,
)
from service.jobs import job_qos_class, note_shed, scheduler_solve
from service.obs import (
    SCHED_REJECTS,
    RequestObsMixin,
    begin_request_obs,
    end_request_obs,
)
from service.parameters import parse_solver_options
from vrpms_tpu.obs import spans
from vrpms_tpu.sched import QueueFull


class SolveHandler(RequestObsMixin, BaseHTTPRequestHandler):
    """Base for all solve endpoints; subclasses set problem/algorithm/
    banner and (for VRP GA) CORS preflight. RequestObsMixin emits one
    structured access line + request-counter bump per response."""

    problem: str = "vrp"       # 'vrp' | 'tsp'
    algorithm: str = "sa"      # 'ga' | 'sa' | 'aco' | 'bf'
    banner: str = "Hi!"
    parse_common = None        # staticmethod set by subclass
    parse_algo = None          # staticmethod or None

    def do_GET(self):
        self.send_response(200)
        self.send_header("Content-type", "text/plain")
        send_static_headers(self)
        self.end_headers()
        self.wfile.write(self.banner.encode("utf-8"))

    def do_POST(self):
        # Request context: id + clock + trace root first, so every
        # later log line and span (including solver-side ones via the
        # contextvars) correlates and the access line carries a
        # duration. The trace adopts an incoming W3C traceparent.
        begin_request_obs(self)
        try:
            self._solve_post()
        finally:
            end_request_obs(self)

    def _solve_post(self):
        # Read + parse via the one shared intake ladder (Content-Length
        # hardening, body-size observation, JSON 400 envelopes).
        with spans.span("parse"):
            content = read_json_body(self)
            if content is None:
                return

            # Parse parameters
            errors: list = []
            params = type(self).parse_common(content, errors)
            algo_params = type(self).parse_algo(content, errors) if type(self).parse_algo else {}
            opts = parse_solver_options(content, errors)

        if len(errors) > 0:
            fail(self, errors)
            return

        # Retrieve data from the store
        try:
            database = store.get_database(self.problem, params["auth"])
        except Exception as e:
            fail(self, [{"what": "Database error", "reason": str(e)}])
            return
        with spans.span("store.read", tables="locations,durations"):
            locations = database.get_locations_by_id(params["locations_key"], errors)
            durations = database.get_durations_by_id(params["durations_key"], errors)

        if len(errors) > 0:
            fail(self, errors)
            return

        # Dynamic re-solve delta: rewrite the dataset view BEFORE the
        # solve AND the save — the active-set params mutate in place and
        # the returned locations carry demand/time-window changes, so
        # the instance build, the cache keys, and the persisted solution
        # all see the post-delta world (vrpms_tpu.core.delta).
        if opts.get("delta") is not None:
            from vrpms_tpu.core.delta import apply_request_delta

            with spans.span("resolve.delta", problem=self.problem):
                locations = apply_request_delta(
                    self.problem, params, locations, opts["delta"], errors
                )
            if locations is None or errors:
                fail(self, errors)
                return

        # Run algorithm (the reference's TODO hole, realised) — via the
        # scheduler: this thread submits and parks on the job event, the
        # device-owning worker solves (merging concurrent same-shape
        # requests into one batched launch). Queue-full sheds with 429 +
        # Retry-After instead of holding the connection behind a queue
        # this request would start deadline-spent in.
        try:
            result = scheduler_solve(
                self.problem, self.algorithm, params, opts, algo_params,
                locations, durations, errors, database,
            )
        except QueueFull as e:
            # QuotaExceeded subclasses QueueFull: a tenant-quota shed
            # rides the same 429 surface, with its own reason text and
            # shed-counter label
            reason = getattr(e, "reason", None)
            SCHED_REJECTS.labels(
                reason="tenant_quota" if reason else "queue_full"
            ).inc()
            note_shed(
                "tenant_quota" if reason else "queue_full",
                job_qos_class(opts),
            )
            too_busy(self, e.retry_after_s, reason=reason)
            return
        if result is None or len(errors) > 0:
            fail(self, errors)
            return

        # Save results
        if params["auth"]:
            with spans.span("store.persist", table="solutions"):
                if self.problem == "vrp":
                    database.save_solution(
                        name=params["name"],
                        description=params["description"],
                        locations=remove_unused_locations(
                            locations,
                            params["ignored_customers"],
                            params["completed_customers"],
                        ),
                        vehicles=result["vehicles"],
                        duration_max=result["durationMax"],
                        duration_sum=result["durationSum"],
                        errors=errors,
                    )
                else:
                    database.save_solution(
                        name=params["name"],
                        description=params["description"],
                        locations=locations,
                        vehicle=result["vehicle"],
                        duration=result["duration"],
                        errors=errors,
                    )

        if len(errors) > 0:
            fail(self, errors)
            return

        # Degraded honesty bit: the solve itself is real, but if any
        # store call on this request (data reads before it or the save
        # just above) was served by a resilience fallback, the client
        # must see that persistence was best-effort.
        if getattr(database, "degraded", False) and "degraded" not in result:
            result = dict(result, degraded=True)

        # includeStats waterfall, rebuilt at respond time so the spans
        # recorded AFTER the solve (the solution save just above) are in
        # it too — the worker-side injection only saw up to the solve
        if (
            self._trace is not None
            and isinstance(result.get("stats"), dict)
        ):
            result = dict(result, stats=dict(
                result["stats"],
                spans=self._trace.waterfall(),
                traceId=self._trace.trace_id,
            ))

        # Respond
        success(self, result)


class CORSPreflightMixin:
    """The reference exposes OPTIONS preflight only on VRP GA
    (api/vrp/ga/index.py:16-22), and its edge config additionally pins
    CORS headers onto every GET/POST response for that route
    (vercel.json:4-11) — reproduced via `static_headers`, which every
    response writer emits (a browser's actual POST would otherwise be
    CORS-blocked even though its preflight succeeded)."""

    static_headers = (
        ("Access-Control-Allow-Credentials", "true"),
        ("Access-Control-Allow-Origin", "*"),
        ("Access-Control-Allow-Methods", "GET,OPTIONS,PATCH,DELETE,POST,PUT"),
        (
            "Access-Control-Allow-Headers",
            "X-CSRF-Token, X-Requested-With, Accept, Accept-Version, "
            "Content-Length, Content-MD5, Content-Type, Date, X-Api-Version",
        ),
    )

    def do_OPTIONS(self):
        self.send_response(200, "ok")
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Access-Control-Allow-Methods", "*")
        self.send_header("Access-Control-Allow-Headers", "*")
        self.end_headers()
