"""Request-body schemas: the reference's keys, plus optional solver knobs.

Required/optional split and camelCase->snake_case mapping preserved
exactly from the reference (api/parameters.py) so existing clients work
unchanged. New *optional* keys extend the reference's per-request flag
system (SURVEY.md §5 "config"): solver hyperparameters and a backend
selector, all defaulted so omitting them reproduces reference behavior.
"""

from __future__ import annotations

from service.helpers import get_parameter
from vrpms_tpu.sched import qos as qos_mod


def parse_common_vrp_parameters(content: dict, errors):
    return {
        "name": get_parameter("solutionName", content, errors),
        "auth": get_parameter("auth", content, errors, optional=True),
        "description": get_parameter("solutionDescription", content, errors),
        "locations_key": get_parameter("locationsKey", content, errors),
        "durations_key": get_parameter("durationsKey", content, errors),
        "capacities": get_parameter("capacities", content, errors),
        "start_times": get_parameter("startTimes", content, errors),
        "ignored_customers": get_parameter("ignoredCustomers", content, errors),
        "completed_customers": get_parameter("completedCustomers", content, errors),
    }


def parse_vrp_ga_parameters(content: dict, errors):
    return {
        "multi_threaded": get_parameter("multiThreaded", content, errors),
        "random_permutationCount": get_parameter(
            "randomPermutationCount", content, errors
        ),
        "iteration_count": get_parameter("iterationCount", content, errors),
    }


def parse_vrp_sa_parameters(content: dict, errors):
    return {}


def parse_vrp_aco_parameters(content: dict, errors):
    return {}


def parse_common_tsp_parameters(content: dict, errors):
    return {
        "name": get_parameter("solutionName", content, errors),
        "auth": get_parameter("auth", content, errors, optional=True),
        "description": get_parameter("solutionDescription", content, errors),
        "locations_key": get_parameter("locationsKey", content, errors),
        "durations_key": get_parameter("durationsKey", content, errors),
        "customers": get_parameter("customers", content, errors),
        "start_node": get_parameter("startNode", content, errors),
        "start_time": get_parameter("startTime", content, errors),
    }


def parse_tsp_ga_parameters(content: dict, errors):
    return {}


def parse_tsp_sa_parameters(content: dict, errors):
    return {}


def parse_tsp_aco_parameters(content: dict, errors):
    return {}


def parse_solver_options(content: dict, errors):
    """Optional extension keys (absent in the reference; all defaulted).

    backend:            "tpu" | "cpu" — device preference for the solve
    seed:               PRNG seed (determinism for a given request)
    iterationCount:     iteration/generation budget (GA endpoints already
                        require it; optional everywhere else)
    populationSize:     SA chains / GA population / ACO ants
    timeSliceDuration:  minutes per time-of-day slice of a 3-D matrix
    warmStart:          seed the search from a prior solution. A truthy
                        scalar keeps the legacy semantics (the solution
                        previously checkpointed under this solutionName,
                        retrieved through the cache family index; SA/GA
                        chain/population seeding, ACO colony incumbent).
                        An OBJECT names an explicit seed source for a
                        dynamic re-solve — one of:
                          {"tour": [[...route ids], ...] | [flat order]}
                            an inline giant tour / visit order,
                          {"jobId": "..."} a prior job's result
                            (live registry or the persisted job record;
                            works with VRPMS_CACHE=off),
                          {"fingerprint": "..."} a cached solution by
                            instance fingerprint (needs the cache on).
                        The seed is repaired onto the CURRENT active
                        customer set over the separator encoding (drop
                        stripped, new greedy-inserted) and SA treats it
                        as a CONTINUATION: the anneal re-enters at a
                        temperature estimated from the repaired tour's
                        cost instead of re-running the hot phase
    delta:              instance delta relative to the stored dataset —
                        {"add": [ids], "drop": [ids],
                         "demands": {id: value},
                         "timeWindows": {id: [ready, due] | null}} —
                        applied before the instance is built (VRP:
                        add/drop move ids out of / into the ignored
                        list; TSP: they edit the customers list).
                        Composes with warmStart for rolling-horizon
                        re-solves; invalid ids and duplicate adds are
                        400 Data errors
    includeStats:       attach solver statistics to the result message
    profile:            capture a jax.profiler trace of the solve
    timeLimit:          wall-clock budget in seconds; every solver
                        (SA, GA, ACO, and BF's chunked enumeration)
                        and the localSearch polish stop at the
                        deadline and return their best-so-far (a
                        deadline-cut BF is then no longer exact; its
                        stats report the orders actually scored)
    makespanWeight:     price the longest route's elapsed time (the
                        durationMax the result reports) into the
                        objective; 0/absent optimizes total distance
    localSearch:        polish the returned solution with the delta-
                        evaluated steepest descent (solvers.delta_ls);
                        true = default sweep budget, an integer caps
                        the number of sweeps
    localSearchPool:    polish this many of the solver's elite solutions
                        at once (SA chain bests / GA final population)
                        and return the winner; default 1 (champion
                        only). A bare localSearchPool > 1 (without
                        localSearch) enables the polish with its
                        default budget; an explicit localSearch: false
                        disables it regardless
    ilsRounds:          SA only: run iterated local search — this many
                        rounds of (anneal -> elite-pool delta polish ->
                        reseed chains from the champion). iterationCount
                        is the TOTAL sweep budget across rounds. The
                        strongest quality setting (solvers.ils).
                        Explicit 0 = ILS off (plain SA)
    ilsReseed:          'ruin' (default; spatial ruin-and-recreate) or
                        'moves' (a few random moves per clone) — how
                        ILS reseeds chains from the champion each round
    islands:            run SA/GA/ACO as an island model over this many
                        devices of the mesh (vrpms_tpu.mesh): per-device
                        populations/colonies with ring elite migration
                        (ACO exchanges incumbent genomes only — each
                        island keeps its own pheromone matrix). Clamped
                        to the devices actually attached; ignored by
                        bf. timeLimit applies (migration blocks run
                        in clock-checked chunks), ilsRounds composes
                        (sharded anneal rounds, pool polish between),
                        and localSearchPool polishes the per-island
                        champions; warmStart applies to ACO only (it
                        seeds every island's colony incumbent)
    migrateEvery:       steps between ring migrations (default 100)
    migrants:           elites sent to the ring neighbor (default 4;
                        SA/GA only — ACO islands always exchange
                        exactly their one incumbent genome)
    qos:                request priority class for the deadline-aware
                        scheduler: "interactive" | "standard" (the
                        default) | "batch". Higher classes pop first,
                        earliest-deadline-first within a class (the
                        deadline is timeLimit's budget measured from
                        submit), and under overload the lowest class
                        sheds (429) first. Ignored (any value) when
                        VRPMS_QOS=off
    """
    qos_value = get_parameter("qos", content, errors, optional=True)
    if qos_mod.enabled() and qos_value is not None:
        # junk classes are 400 Data errors — but only with QoS on:
        # the off switch must treat 'qos' like any other unknown key
        # (ignored), keeping pre-QoS responses byte-identical
        try:
            qos_value = qos_mod.parse_class(qos_value)
        except ValueError as e:
            errors += [{"what": "Data error", "reason": str(e)}]
    return {
        "qos": qos_value,
        "backend": get_parameter("backend", content, errors, optional=True),
        "seed": get_parameter("seed", content, errors, optional=True),
        "iteration_count": get_parameter("iterationCount", content, errors, optional=True),
        "population_size": get_parameter("populationSize", content, errors, optional=True),
        "time_slice_duration": get_parameter(
            "timeSliceDuration", content, errors, optional=True
        ),
        "warm_start": get_parameter("warmStart", content, errors, optional=True),
        "delta": get_parameter("delta", content, errors, optional=True),
        "include_stats": get_parameter("includeStats", content, errors, optional=True),
        "profile": get_parameter("profile", content, errors, optional=True),
        "time_limit": get_parameter("timeLimit", content, errors, optional=True),
        "makespan_weight": get_parameter(
            "makespanWeight", content, errors, optional=True
        ),
        "local_search": get_parameter("localSearch", content, errors, optional=True),
        "local_search_pool": get_parameter(
            "localSearchPool", content, errors, optional=True
        ),
        "ils_rounds": get_parameter("ilsRounds", content, errors, optional=True),
        "ils_reseed": get_parameter("ilsReseed", content, errors, optional=True),
        "islands": get_parameter("islands", content, errors, optional=True),
        "migrate_every": get_parameter("migrateEvery", content, errors, optional=True),
        "migrants": get_parameter("migrants", content, errors, optional=True),
    }
