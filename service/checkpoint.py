"""Durable solve checkpoints: capture, background flush, resume.

A lease reclaim (sched.replica), a watchdog requeue (sched.worker), or
a graceful drain used to re-run a job FROM ZERO at attempt=2 — a
replica dying at 95% of a long anneal threw away every improving
incumbent it had already published, and a decomposed giant lost all its
solved shards. This module closes that gap with three pieces:

  * **capture** — `register()` hangs a `_Handle` off the job's
    ProgressSink; the solver seam (solvers.common.run_blocked) asks it
    `due()` at every block boundary and, at most once per
    `VRPMS_CKPT_MS`, `offer()`s the champion tour. The decomposed path
    (service.solve._solve_decomposed) instead calls `note_shard()` as
    each shard chunk completes. Capture only snapshots host/device
    arrays the drivers already synced — it never changes the block
    decomposition or any device computation, so fixed-seed responses
    are byte-identical with checkpointing on or off.
  * **flush** — one background daemon thread decodes pending giants to
    routes in ORIGINAL location ids and writes
    `{problem, algorithm, routes, cost, evals, elapsedMs, shards?}`
    through the store.base checkpoint seam (put/get/delete keyed by
    job id + attempt). Strictly best-effort with the solution cache's
    fail-open store policy: a failed write increments
    `vrpms_ckpt_total{outcome="dropped"}` and nothing else.
  * **resume** — `load_resume()` reads the latest checkpoint for a
    reclaimed / requeued / drain-nacked job id; the service injects the
    routes as a `warmStart: {"tour": ...}` spec (distributed claims) or
    seeds the surviving Prepared directly (local watchdog requeues), so
    attempt=2 enters through the EXISTING Prepared.resolve continuation
    path — SA re-enters at the seed-estimated temperature, GA ramps the
    seeded population, ACO pre-deposits the seed tour's pheromone — and
    `seed_incumbent` opens the new sink at the checkpoint cost so the
    first published incumbent can never be worse than the checkpoint.

Terminal paths call `finished()` / `delete_for()` so acked and dead
jobs leave no stale rows behind (the hosted backend's retention sweep
in store/schema.sql is the backstop). `VRPMS_CKPT=off` disables
everything: no handle is attached, no store op runs, and the request
path is byte-identical to the pre-checkpoint service.
"""

from __future__ import annotations

import threading
import time

import store
from service import obs
from vrpms_tpu import config
from vrpms_tpu.obs import log_event


def enabled() -> bool:
    """The VRPMS_CKPT master switch (default on). Read per call so
    tests and embedders toggle at runtime. Capture additionally needs a
    progress sink, so VRPMS_PROGRESS=off implies no checkpoints."""
    return config.enabled("VRPMS_CKPT")


def interval_s() -> float:
    return max(0.0, config.get("VRPMS_CKPT_MS")) / 1e3


def _dropped(n: int = 1) -> None:
    obs.CKPT_TOTAL.labels(outcome="dropped").inc(n)


class _Entry:
    """One live job's checkpoint state (capture side + flusher side)."""

    def __init__(self, job, prep, attempt: int):
        self.job_id = job.id
        self.attempt = max(1, int(attempt))
        self.problem = prep.problem
        self.algorithm = prep.algorithm
        # decode context: giant tours are in padded active indexing;
        # routes persist in ORIGINAL location ids (robust to active-set
        # drift at resume, like every other warm-seed source)
        self.orig_ids = list(prep.orig_ids or [])
        inst = prep.inst
        self.n_real = (
            None
            if inst is None or inst.n_real is None
            else int(inst.n_real)
        )
        # span parentage for ckpt.write (the _persist pattern: flusher
        # threads have no active trace context)
        self.trace = job.trace
        self.span = job.span
        self.lock = threading.Lock()
        self.pending = None  # guarded-by: lock (host copy of the giant)
        self.snap = None  # guarded-by: lock (sink snapshot at capture)
        self.shards = {}  # guarded-by: lock ({shard: {routes, cost}})
        self.dirty = False  # guarded-by: lock
        self.closed = False  # guarded-by: lock
        self.wrote = False  # guarded-by: lock (any row persisted)
        self.resumed = False  # guarded-by: lock (seeded from a row)
        # first capture waits ONE full interval from registration: a
        # solve shorter than VRPMS_CKPT_MS never pays a checkpoint
        self.last_capture = time.monotonic()  # guarded-by: lock
        self.last_seq = 0  # guarded-by: lock (sink.seq at last capture)

    # -- capture side (solver / worker threads) -----------------------------
    def due(self, sink) -> bool:
        # Called from the solver seam at block boundaries. Under the
        # pipelined driver (VRPMS_PIPELINE) the check may run at a
        # LAUNCH gate, one in-flight block before the capture's offer
        # lands — the cadence stays bounded (interval_s plus at most
        # one block), it never double-fires for one publish (last_seq
        # only advances in offer), and a capture is never lost: the
        # final in-flight block is always drained and processed.
        now = time.monotonic()
        with self.lock:
            if self.closed:
                return False
            if now - self.last_capture < interval_s():
                return False
            # only improved incumbents are worth a write: the sink's
            # seq advances exactly when it publishes one
            return sink.seq != self.last_seq

    def offer(self, sink, giant) -> None:
        import numpy as np

        try:
            arr = np.asarray(giant)
        except Exception:
            _dropped()
            return
        snap = sink.snapshot()
        with self.lock:
            if self.closed:
                return
            self.pending = arr
            self.snap = snap
            self.dirty = True
            self.last_capture = time.monotonic()
            self.last_seq = sink.seq
        _checkpointer().kick()

    def note_shard(self, shard: int, routes: list, cost: float) -> None:
        """A decomposed solve finished shard `shard` (routes in
        shard-LOCAL node positions): persist it so a resumed attempt
        solves only the remaining shards before stitching."""
        with self.lock:
            if self.closed:
                return
            self.shards[int(shard)] = {
                "routes": [list(map(int, r)) for r in routes],
                "cost": float(cost),
            }
            self.dirty = True
            self.last_capture = time.monotonic()
        _checkpointer().kick()

    def mark_resumed(self) -> None:
        with self.lock:
            self.resumed = True

    # -- flusher side --------------------------------------------------------
    def take(self):
        """(giant, snap, shards, attempt) snapshot for one flush, or
        None when there is nothing new; clears the dirty flag."""
        with self.lock:
            if not self.dirty or self.closed:
                return None
            self.dirty = False
            return (
                self.pending,
                dict(self.snap) if self.snap else None,
                {k: dict(v) for k, v in self.shards.items()},
                self.attempt,
            )

    def close(self) -> tuple[bool, bool]:
        """Stop further captures/flushes; returns (may_have_rows,
        resumed). A capture whose write is still in flight counts — the
        terminal delete must not skip a row that lands a moment
        later."""
        with self.lock:
            self.closed = True
            captured = self.pending is not None or bool(self.shards)
            return self.wrote or captured, self.resumed

    def note_wrote(self) -> None:
        with self.lock:
            self.wrote = True

    def decode_routes(self, giant) -> list | None:
        """Champion giant (padded active indexing) -> routes of
        ORIGINAL location ids, the shape every warm-seed source uses."""
        if giant is None:
            return None
        from vrpms_tpu.core.encoding import routes_from_giant

        routes = []
        for route in routes_from_giant(giant, self.n_real):
            if route:
                routes.append([int(self.orig_ids[c]) for c in route])
        return routes or None


class Checkpointer:
    """The process checkpointer: a registry of live jobs' entries and
    ONE background flusher thread that owns every checkpoint store op
    (writes strictly ordered with deletes — no device-loop thread ever
    pays a checkpoint store round trip)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, _Entry] = {}  # guarded-by: _lock
        self._deletes: list[str] = []  # guarded-by: _lock
        self._thread = None  # guarded-by: _lock
        self._wake = threading.Event()
        self._last_write = None  # guarded-by: _lock (wall time)

    # -- registry ------------------------------------------------------------
    def register(self, job, prep, attempt: int = 1):
        """Attach a capture handle to `job`'s sink (no-op without a
        sink, a prep, or VRPMS_CKPT). Returns the entry or None."""
        if not enabled() or job.sink is None or prep is None:
            return None
        if prep.inst is None and prep.decomp is None:
            return None
        entry = _Entry(job, prep, attempt)
        with self._lock:
            self._entries[job.id] = entry
        job.sink.ckpt = entry
        self._ensure_thread()
        return entry

    def entry_for(self, job_id: str) -> _Entry | None:
        with self._lock:
            return self._entries.get(str(job_id))

    def finished(self, job_id: str, delete: bool = True) -> None:
        """Terminal hygiene: stop captures and (when any row may exist
        — this process wrote one, or the attempt was itself resumed
        from one) queue the job's rows for deletion. Jobs that never
        checkpointed cost no store op here."""
        with self._lock:
            entry = self._entries.pop(str(job_id), None)
        if entry is None:
            return
        wrote, resumed = entry.close()
        if delete and (wrote or resumed) and enabled():
            self.delete_for(job_id)

    def delete_for(self, job_id: str) -> None:
        """Queue an unconditional checkpoint-row delete (the dead-entry
        path: the rows may have been written by ANOTHER replica)."""
        if not enabled():
            return
        with self._lock:
            self._deletes.append(str(job_id))
        self._ensure_thread()
        self._wake.set()

    def flush_job(self, job_id: str) -> bool:
        """Synchronously flush one job's pending state (the drain
        path: the entry must be durable BEFORE the nack hands the job
        to a peer). Returns True when a row was written."""
        entry = self.entry_for(job_id)
        if entry is None:
            return False
        return self._flush_entry(entry, self._db())

    def kick(self) -> None:
        self._wake.set()

    # -- the flusher thread --------------------------------------------------
    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._thread = threading.Thread(
                target=self._run, name="vrpms-ckpt-flusher", daemon=True
            )
            self._thread.start()

    def _run(self) -> None:  # pragma: no cover - exercised via the API
        while True:
            self._wake.wait(timeout=min(1.0, max(0.02, interval_s())))
            self._wake.clear()
            try:
                self.flush_round()
            except Exception as exc:
                # the flusher must never die; a broken round drops its
                # captures (accounted) and the next cadence retries
                _dropped()
                log_event(
                    "ckpt.flush_error",
                    error=f"{type(exc).__name__}: {exc}",
                )

    def _db(self):
        return store.get_database("vrp", None)

    def flush_round(self) -> int:
        """One flush pass: write every dirty entry, then run queued
        deletes (same thread, so a delete can never be overtaken by a
        late write for the same job). Returns rows written."""
        with self._lock:
            entries = list(self._entries.values())
            deletes, self._deletes = self._deletes, []
        wrote = 0
        db = None
        for entry in entries:
            if db is None:
                db = self._db()
            if self._flush_entry(entry, db):
                wrote += 1
        for job_id in deletes:
            if db is None:
                db = self._db()
            db.delete_checkpoint(job_id)
        return wrote

    def _flush_entry(self, entry: _Entry, db) -> bool:
        taken = entry.take()
        if taken is None:
            return False
        giant, snap, shards, attempt = taken
        try:
            routes = entry.decode_routes(giant)
        except Exception as exc:
            _dropped()
            log_event(
                "ckpt.decode_error",
                jobId=entry.job_id,
                error=f"{type(exc).__name__}: {exc}",
            )
            return False
        state = {
            "problem": entry.problem,
            "algorithm": entry.algorithm,
            "routes": routes,
            "cost": None if snap is None else snap.get("bestCost"),
            "evals": None if snap is None else snap.get("evals"),
            "elapsedMs": None if snap is None else snap.get("wallMs"),
            # federated-read provenance: the block id keys SSE event
            # ids on non-owning replicas, writtenAt anchors staleMs
            "block": None if snap is None else snap.get("block"),
            "writtenAt": time.time(),
        }
        if shards:
            state["shards"] = {str(k): v for k, v in shards.items()}
        # explicit span on the job's own trace (the _persist pattern:
        # no trace context is active on the flusher thread)
        sp = None
        if entry.trace is not None:
            sp = entry.trace.span(
                "ckpt.write",
                parent_id=(
                    entry.span.span_id if entry.span is not None else None
                ),
            )
            sp.set(
                jobId=entry.job_id,
                attempt=attempt,
                cost=state["cost"],
                shards=len(shards) or None,
            )
        try:
            ok = db.put_checkpoint(entry.job_id, attempt, state)
        finally:
            if sp is not None:
                sp.end(status=None)
        if ok:
            entry.note_wrote()
            with self._lock:
                self._last_write = time.time()
            obs.CKPT_TOTAL.labels(outcome="written").inc()
        else:
            _dropped()
        return ok

    def health(self) -> dict:
        """Checkpointer liveness for the fleet status doc: live entry
        count and the age of the last successful flush (None = this
        process has not written a row yet). A wedged flusher shows up
        as a growing age with entries > 0 — visible in
        GET /api/debug/fleet BEFORE a crash makes it expensive."""
        with self._lock:
            entries = len(self._entries)
            last = self._last_write
        return {
            "entries": entries,
            "lastFlushAgeMs": (
                None if last is None
                else max(0, round((time.time() - last) * 1e3))
            ),
        }


_ckpt_lock = threading.Lock()
_ckpt: Checkpointer | None = None  # guarded-by: _ckpt_lock


def _checkpointer() -> Checkpointer:
    global _ckpt
    with _ckpt_lock:
        if _ckpt is None:
            _ckpt = Checkpointer()
        return _ckpt


def checkpointer() -> Checkpointer:
    """The process singleton (tests reach flush_round/entries here)."""
    return _checkpointer()


def reset() -> None:
    """Forget the registry (test hygiene between in-process services;
    the daemon thread, if any, keeps idling harmlessly)."""
    global _ckpt
    with _ckpt_lock:
        _ckpt = None


# ---------------------------------------------------------------------------
# Resume: reclaimed / requeued / drain-nacked attempts seed from the rows
# ---------------------------------------------------------------------------


def load_resume(job_id: str) -> dict | None:
    """The latest durable checkpoint STATE for `job_id`, or None
    (disabled, missing, unreadable — every miss degrades to a
    from-zero attempt, never to a failed job)."""
    if not enabled():
        return None
    try:
        row = store.get_database("vrp", None).get_checkpoint(job_id)
    except Exception:
        return None
    if not isinstance(row, dict):
        return None
    state = row.get("state")
    return state if isinstance(state, dict) else None


def note_resumed(job, state: dict, source: str) -> None:
    """Account a successful resume: the counter, a zero-width
    ckpt.resume span on the job's trace, and — monolithic resumes —
    the sink opens at the checkpoint cost so the first published
    incumbent can never be worse than the checkpoint."""
    obs.CKPT_TOTAL.labels(outcome="resumed").inc()
    if job.trace is not None:
        sp = job.trace.span(
            "ckpt.resume",
            parent_id=job.span.span_id if job.span is not None else None,
        )
        sp.set(
            jobId=job.id,
            source=source,
            cost=state.get("cost"),
            shards=len(state.get("shards") or {}) or None,
        )
        sp.end()
    if (
        job.sink is not None
        and state.get("cost") is not None
        and not state.get("shards")
    ):
        try:
            job.sink.seed_incumbent(
                float(state["cost"]), int(state.get("evals") or 0)
            )
        except (TypeError, ValueError):
            pass
    entry = _checkpointer().entry_for(job.id)
    if entry is not None:
        entry.mark_resumed()
    log_event(
        "ckpt.resume",
        jobId=job.id,
        source=source,
        cost=state.get("cost"),
        shards=len(state.get("shards") or {}) or None,
    )


def apply_local_resume(job) -> None:
    """The watchdog-requeue half of resume: the Job object (and its
    Prepared) survived the worker crash in-process, so the checkpoint
    seeds the EXISTING prep — warm perm + continuation marker for
    monolithic solves, the completed-shard map for decomposed ones —
    and the remaining budget replaces the fresh one the requeue reset
    granted. Best-effort: any mismatch solves from zero."""
    if not enabled() or not job.requeued:
        return
    prep = (job.payload or {}).get("prep")
    if prep is None:
        return
    state = load_resume(job.id)
    if state is None:
        return
    if (
        state.get("problem") != prep.problem
        or state.get("algorithm") != prep.algorithm
    ):
        return
    seeded = False
    if prep.decomp is not None:
        if state.get("shards"):
            prep.ckpt = state
            seeded = True
    elif state.get("routes"):
        from service import cache as solution_cache

        try:
            warm = solution_cache._repair_perm(prep, state["routes"])
        except Exception:
            warm = None
        if warm is not None:
            prep.warm = warm
            prep.resolve = {"seedSource": "checkpoint", "seeded": True}
            seeded = True
    if not seeded:
        return
    # remaining budget: the requeue forgave the crashed run's elapsed
    # time (sched.queue.reopen_for_requeue) — a RESUMED attempt must
    # not also get a fresh budget, or crash-resume would grant more
    # wall clock than the request paid for
    elapsed_ms = state.get("elapsedMs")
    if job.time_limit and job.time_limit > 0 and elapsed_ms:
        job.payload["ckpt_elapsed_s"] = float(elapsed_ms) / 1e3
    note_resumed(job, state, source="watchdog")
