"""The api->solver bridge: request data in, contract-shaped results out.

This module stands exactly where the reference's `# TODO: Run algorithm`
holes sit (reference api/vrp/ga/index.py:48-53, api/tsp/bf/index.py:39-43)
and where its README prescribes the api->src call boundary (reference
README.md:31-33). It:

  1. compacts the request's locations + durations matrix into a
     device-ready Instance (excluding ignored/completed customers — the
     reference's dynamic re-solve inputs, api/parameters.py:13-14);
  2. dispatches to the requested solver (bf/sa/ga/aco) with hyper-
     parameters from the request (GA's reference-required params map to
     population/generations; everything else has TPU-sized defaults);
  3. decodes the winning giant tour back to original location ids and
     shapes the result to the endpoint contract: VRP
     {durationMax, durationSum, vehicles}, TSP {duration, vehicle}.

Location schema (the reference stores opaque location dicts with an 'id',
api/helpers.py:11-13; solver-relevant optional keys defined here):
  {'id': int, 'demand': num (default 1), 'serviceTime': num (default 0),
   'timeWindow': [ready, due] (optional)}
The durations matrix is indexed by position in the locations list; a
3-D nesting matrix[i][j] == [per-slice durations] is time-of-day data.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import math
import time
import traceback

import numpy as np
import jax
import jax.numpy as jnp

from service import obs
from service import cache as solution_cache
from vrpms_tpu.obs import collect_blocks, convergence_summary, log_event, spans

from vrpms_tpu import config
from vrpms_tpu.core import decompose
from vrpms_tpu.core import make_instance
from vrpms_tpu.core import tiers
from vrpms_tpu.obs import analytics
from vrpms_tpu.obs import progress
from vrpms_tpu.core.encoding import routes_from_giant
from vrpms_tpu.core.split import greedy_split_giant
from vrpms_tpu.solvers import (
    ACOParams,
    GAParams,
    SAParams,
    solve_aco,
    solve_ga,
    solve_sa,
    solve_tsp_bf,
    solve_tsp_exact,
    solve_vrp_bf,
)

DEFAULT_SLICE_MINUTES = 60.0


def _device_ctx(backend):
    """Best-effort device preference; default platform otherwise."""
    if backend in ("cpu", "tpu"):
        try:
            dev = jax.devices(backend)[0]
            return jax.default_device(dev)
        except RuntimeError:
            pass
    return contextlib.nullcontext()


def _as_float(x):
    return float(np.asarray(x))


def _enveloped(fn):
    """Any unexpected failure becomes a Data error in the envelope — a
    request must never take down the connection without the contract's
    400 JSON body (reference api/helpers.py:16-21)."""

    @functools.wraps(fn)
    def wrapper(algorithm, params, opts, ga_params, locations, matrix, errors, **kw):
        try:
            return fn(algorithm, params, opts, ga_params, locations, matrix, errors, **kw)
        except Exception as e:
            # structured line (request-correlated via the contextvar)
            # instead of a bare stderr traceback; the envelope entry the
            # caller returns stays byte-identical
            log_event(
                "solve.exception",
                algorithm=algorithm,
                error=f"{type(e).__name__}: {e}",
                traceback=traceback.format_exc(),
            )
            errors += [
                {"what": "Data error", "reason": f"{type(e).__name__}: {e}"}
            ]
            return None

    return wrapper


def _build_arrays(locations, matrix, active_pos, errors, slice_minutes):
    """Sub-select the duration matrix and per-location fields for the
    active positions (depot first)."""
    arr = np.asarray(matrix, dtype=np.float64)
    n_all = len(locations)
    if arr.ndim not in (2, 3) or arr.shape[0] != n_all or arr.shape[1] != n_all:
        errors += [
            {
                "what": "Data error",
                "reason": f"durations matrix shape {arr.shape} does not match "
                f"{n_all} locations",
            }
        ]
        return None
    sub = arr[np.ix_(active_pos, active_pos)]
    if not np.isfinite(sub).all() or (sub < 0).any():
        # NaN/inf would propagate through every cost into the response
        # (and NaN is not even valid JSON); negative durations break the
        # solvers' shortest-leg assumptions — both are data errors.
        # Checked on the ACTIVE submatrix only: bad entries confined to
        # ignored/completed/unselected locations never reach a solver
        # (inf rows are a legitimate "unreachable node" convention).
        errors += [
            {
                "what": "Data error",
                "reason": "durations matrix entries must be finite and non-negative",
            }
        ]
        return None
    locs = [locations[i] for i in active_pos]
    demands = [0.0] + [float(loc.get("demand", 1)) for loc in locs[1:]]
    service = [float(loc.get("serviceTime", 0)) for loc in locs]
    tws = [loc.get("timeWindow") for loc in locs]
    has_tw = any(tw is not None for tw in tws)
    ready = due = None
    if has_tw:
        big = 1e9
        ready = [float(tw[0]) if tw else 0.0 for tw in tws]
        due = [float(tw[1]) if tw else big for tw in tws]
    return {
        "durations": sub,
        "demands": demands,
        "service": service,
        "ready": ready,
        "due": due,
        "slice_axis": "last" if sub.ndim == 3 else "auto",
        "slice_minutes": slice_minutes,
    }


def _warm_perm(state, active_ids: list, problem: str):
    """Previous checkpoint -> customer permutation in active indexing.

    The checkpoint stores routes of ORIGINAL location ids; re-solves may
    exclude some (the reference's ignored/completed dynamic inputs) or
    introduce new customers. Order is preserved for surviving ids and new
    customers are appended, so the seed stays a valid permutation of the
    CURRENT active set — the coarse resume-from-world-state semantics of
    SURVEY.md §5 made warm.
    """
    if not state or state.get("problem") != problem:
        return None
    order, seen = solution_cache.strip_order(state.get("routes", []), active_ids)
    order += [i for i in range(1, len(active_ids)) if i not in seen]
    if not order:
        return None
    return jnp.asarray(order, dtype=jnp.int32)


def _better_checkpoint(prev, problem, routes, cost) -> bool:
    """Should this result replace the stored warm-start checkpoint?

    Passed to store.base.Database.save_warmstart as its keep-best guard
    (re-evaluated against the freshly fetched state at write time). Keep
    the stored checkpoint only when it solves the SAME customer set at
    an equal-or-lower cost; a dynamic re-solve (ignored/completed changed
    the active set) always refreshes, because costs across different
    customer sets are not comparable. `cost` is the PENALIZED solver
    objective (distance + capacity/TW penalties), so an infeasible
    short-distance result never displaces a feasible checkpoint.
    """
    if not prev or prev.get("problem") != problem:
        return True
    prev_ids = {c for r in prev.get("routes", []) for c in r}
    new_ids = {c for r in routes for c in r}
    if prev_ids != new_ids:
        return True
    try:
        return float(cost) < float(prev.get("cost"))
    except (TypeError, ValueError):
        return True


def _request_weights(opts):
    """The ONE place request options become CostWeights — the solver
    dispatch and the polish acceptance guard must price the same
    objective, or 'never returns worse' silently breaks."""
    from vrpms_tpu.core.cost import CostWeights

    return CostWeights.make(makespan=float(opts.get("makespan_weight") or 0.0))


def _deadline(opts):
    """The request's timeLimit as a float deadline (None = unbounded) —
    the ONE place the option becomes solver deadline_s, for every
    algorithm. Explicit 0 means "stop as soon as possible", not "no
    limit"."""
    val = opts.get("time_limit")
    return float(val) if val is not None else None


def _ils_reseed(opts):
    """Validated ilsReseed option ('ruin' default — see ILSParams)."""
    val = opts.get("ils_reseed")
    if val is None:
        return "ruin"
    if val not in ("ruin", "moves"):
        raise ValueError(f"'ilsReseed' must be 'ruin' or 'moves', got {val!r}")
    return val


def _positive_int(opts, key, default, name, zero_ok=False):
    """Validated positive-integer option: absent -> default, anything
    not a positive integer -> ValueError (the Solver-error envelope).
    The sharded solvers silently degenerate on nonsense (a negative
    migrateEvery makes every scan empty, 'solving' with zero
    iterations), so rejection must happen at the service boundary.
    `zero_ok` admits an explicit 0 for features where it plainly means
    "off" (ilsRounds, islands) — consistent with timeLimit's explicit-0
    handling — while negatives/non-integers still reject."""
    val = opts.get(key)
    if val is None:
        return default
    iv = int(val)
    if iv < (0 if zero_ok else 1):
        kind = "non-negative" if zero_ok else "positive"
        raise ValueError(f"'{name}' must be a {kind} integer, got {val!r}")
    return iv


def _island_devices(opts):
    """(island_count, devices) for an `islands` request: the backend
    option picks the device pool (like _device_ctx does for non-island
    solves) and the count clamps to what is actually attached (a
    single-chip deployment quietly runs one island, which is exactly
    the non-island solver semantics). The ONE clamp — stats must report
    the same count the mesh was built from."""
    backend = opts.get("backend")
    try:
        devices = jax.devices(backend) if backend in ("cpu", "tpu") else jax.devices()
    except RuntimeError:
        devices = jax.devices()
    n = _positive_int(opts, "islands", 1, "islands")
    return min(n, len(devices)), devices


def _island_setup(opts):
    """(mesh, IslandParams) for an `islands` request."""
    from vrpms_tpu.mesh import IslandParams, make_mesh

    n, devices = _island_devices(opts)
    mesh = make_mesh(devices=devices[:n])
    ip = IslandParams(
        migrate_every=_positive_int(opts, "migrate_every", 100, "migrateEvery"),
        n_migrants=_positive_int(opts, "migrants", 4, "migrants"),
    )
    return mesh, ip


def _enum_certificate(res, inst, split_exact: bool) -> dict:
    """Proof certificate for the chunked-enumeration paths: optimality
    is proven iff every order was scored AND the per-order pricing was
    itself exact (the greedy split under TW/TD/makespan is not) AND the
    returned solution is capacity-feasible — an over-demand instance
    makes the greedy split return the best PENALIZED packing, which is
    a fallback answer, never a proven optimum (ADVICE round 5)."""
    complete = int(res.evals) >= math.factorial(inst.n_customers)
    feasible = float(res.breakdown.cap_excess) <= 0.0
    cert = {
        "proven": bool(complete and split_exact and feasible),
        "method": "enumeration",
    }
    if not feasible:
        # match the B&B InfeasibleError fallback's honesty flag: the
        # answer is a penalized best-effort packing, and the reason it
        # is unproven is infeasibility, not a truncated search
        cert["infeasible"] = True
    return cert


def _solve_instance(inst, algorithm, opts, ga_params, errors, problem, warm=None, w=None,
                    extras=None, continuation=False):
    """Dispatch to the solver; returns a SolveResult or None (errors filled).

    `continuation` marks a warm seed that came from an explicit re-solve
    source (a prior job's incumbent, an inline tour, a fingerprint) —
    SA then CONTINUES annealing from the repaired incumbent instead of
    re-running the high-temperature phase: the schedule's t0 is
    estimated from the seed tour's cost (sa.continuation_params), which
    is what lets a warm delta re-solve match a cold solve's cost in a
    fraction of the evals (benchmarks/resolve_delta.py)."""
    seed = int(opts.get("seed") or 0)
    iters = opts.get("iteration_count")
    pop = opts.get("population_size")
    islands = opts.get("islands")
    w = w if w is not None else _request_weights(opts)
    if warm is not None and inst.n_real is not None:
        # checkpoint perms are over the REAL customers; a tier-padded
        # solver's genome carries the phantom ids at its tail
        warm = tiers.pad_perm(warm, inst)
    try:
        # validated whenever provided; elite pools only feed the
        # multi-start polish, so they are materialised only with it.
        # ILS polishes internally every round: an EXPLICIT
        # localSearchPool is honored exactly, otherwise ILSParams'
        # default pool applies.
        _ils_reseed(opts)  # validated whenever provided (like pool)
        pool = _positive_int(opts, "local_search_pool", 1, "localSearchPool")
        ils_pool = pool if opts.get("local_search_pool") is not None else 32
        if not _polish_enabled(opts):
            pool = 0
        if algorithm == "bf":
            # The exact ladder behind the BF endpoints (the reference's
            # gurobipy pin signals exact intent beyond enumeration):
            # enumeration to 10 customers, then Held-Karp DP (TSP, to
            # 16) / q-route branch-and-bound (CVRP, to ~32) — all exact
            # when they finish; B&B honors timeLimit (default 60 s) and
            # returns its best-effort incumbent when cut short.
            deadline = _deadline(opts)
            from vrpms_tpu.solvers.bf import MAX_BF_CUSTOMERS

            untimed = not inst.has_tw and not inst.time_dependent
            if problem == "tsp":
                from vrpms_tpu.solvers.exact import MAX_EXACT_CUSTOMERS

                if (
                    MAX_BF_CUSTOMERS < inst.n_customers <= MAX_EXACT_CUSTOMERS
                    and untimed
                    and not w.use_makespan
                ):
                    res = solve_tsp_exact(inst, weights=w)
                    if extras is not None:
                        extras["exact"] = {"proven": True, "method": "held-karp"}
                    return res
                res = solve_tsp_bf(inst, weights=w, deadline_s=deadline)
                if extras is not None:
                    # a single-vehicle tour fully determines its
                    # schedule, so complete enumeration is exact even
                    # with time windows
                    extras["exact"] = _enum_certificate(res, inst, split_exact=True)
                return res
            from vrpms_tpu.solvers.exact import (
                MAX_BNB_CUSTOMERS,
                InfeasibleError,
                solve_cvrp_bnb,
            )

            if (
                MAX_BF_CUSTOMERS < inst.n_customers <= MAX_BNB_CUSTOMERS
                and untimed
                and not inst.het_fleet
                and not w.use_makespan
            ):
                # explicit timeLimit 0 means "stop ASAP" (same semantics
                # as _deadline everywhere else), not "no limit"
                try:
                    res, proven, bnb_stats = solve_cvrp_bnb(
                        inst, weights=w,
                        time_limit_s=60.0 if deadline is None else deadline,
                    )
                    # the whole point of an exact endpoint is the
                    # certificate: report whether the tree was exhausted
                    # (optimality PROVEN) or the deadline cut the search
                    # at an incumbent (VERDICT r4 weak-5)
                    if extras is not None:
                        extras["exact"] = {
                            "proven": bool(proven),
                            "method": "branch-and-bound",
                            "nodes": int(bnb_stats.get("nodes", 0)),
                        }
                    return res
                except InfeasibleError:
                    # No capacity-feasible solution exists: the B&B has
                    # nothing to return, and enumeration is out of range
                    # at these sizes — answer with the penalized
                    # best-effort NN + local-search packing instead of a
                    # Solver error, matching the deadline contract every
                    # other solver honors (ADVICE round 3).
                    from vrpms_tpu.solvers.local_search import solve_nn_2opt

                    if extras is not None:
                        extras["exact"] = {
                            "proven": False,
                            "method": "nn-2opt-fallback",
                            "infeasible": True,
                        }
                    return solve_nn_2opt(inst, weights=w)
            res = solve_vrp_bf(inst, weights=w, deadline_s=deadline)
            if extras is not None:
                # timed/makespan instances are enumerated over orders
                # but priced by the GREEDY split (solvers.bf), which is
                # not exact over the full split space — never certify
                # those (code review r5)
                split_exact = not (
                    inst.has_tw or inst.time_dependent or w.use_makespan
                )
                extras["exact"] = _enum_certificate(
                    res, inst, split_exact=split_exact
                )
            return res
        if algorithm == "sa":
            p = SAParams(
                n_chains=int(pop or 128),
                n_iters=int(iters or 5000),
            )
            if continuation and warm is not None:
                # continuation budget: re-enter the anneal at a
                # temperature estimated from the repaired seed's cost
                # (never hotter than a plain warm start) so the whole
                # iteration budget refines instead of re-melting
                from vrpms_tpu.solvers.sa import continuation_params

                p = continuation_params(
                    inst, p, greedy_split_giant(warm, inst), w
                )
            # explicit 0 means "ILS off" (plain SA), like timeLimit's 0
            ils_rounds = _positive_int(opts, "ils_rounds", 0, "ilsRounds", zero_ok=True)
            if islands:
                from vrpms_tpu.mesh import solve_ils_islands, solve_sa_islands

                mesh, ip = _island_setup(opts)
                deadline = _deadline(opts)
                init = None
                if warm is not None:
                    # perturbed checkpoint clones, sized to shard evenly
                    # across islands (clone 0 is the exact seed, so the
                    # best-so-far tracking never regresses below it)
                    from vrpms_tpu.core.cost import resolve_eval_mode
                    from vrpms_tpu.solvers.sa import perturbed_clones

                    n_isl = mesh.shape["islands"]
                    b = max(
                        -(-p.n_chains // n_isl), ip.n_migrants + 1
                    ) * n_isl
                    init = perturbed_clones(
                        jax.random.key(seed + 1),
                        b,
                        greedy_split_giant(warm, inst),
                        resolve_eval_mode("auto"),
                        length_real=inst.move_limit,
                    )
                if ils_rounds:
                    from vrpms_tpu.solvers import ILSParams

                    return solve_ils_islands(
                        inst,
                        key=seed,
                        mesh=mesh,
                        params=ILSParams.from_budget(
                            ils_rounds, p, p.n_iters, pool=ils_pool,
                            reseed=_ils_reseed(opts),
                        ),
                        island_params=ip,
                        weights=w,
                        deadline_s=deadline,
                        init_giants=init,
                    )
                return solve_sa_islands(
                    inst,
                    key=seed,
                    mesh=mesh,
                    params=p,
                    island_params=ip,
                    weights=w,
                    deadline_s=deadline,
                    pool=pool,
                    init_giants=init,
                )
            init = None
            if warm is not None:
                # Every chain starts from the checkpointed solution,
                # decorrelated by a few moves — paired with solve_sa's
                # cool seeded schedule it refines the warm basin instead
                # of drowning one good chain among random ones.
                from vrpms_tpu.core.cost import resolve_eval_mode
                from vrpms_tpu.solvers.sa import perturbed_clones

                init = perturbed_clones(
                    jax.random.key(seed + 1),
                    p.n_chains,
                    greedy_split_giant(warm, inst),
                    resolve_eval_mode("auto"),
                    length_real=inst.move_limit,
                )
            deadline = _deadline(opts)
            if ils_rounds:
                from vrpms_tpu.solvers import ILSParams, solve_ils

                return solve_ils(
                    inst,
                    key=seed,
                    params=ILSParams.from_budget(
                        ils_rounds, p, p.n_iters, pool=ils_pool,
                        reseed=_ils_reseed(opts),
                    ),
                    weights=w,
                    init_giants=init,
                    deadline_s=deadline,
                )
            return solve_sa(
                inst,
                key=seed,
                params=p,
                weights=w,
                init_giants=init,
                deadline_s=deadline,
                pool=pool,
            )
        if algorithm == "aco":
            p = ACOParams(n_ants=int(pop or 64), n_iters=int(iters or 200))
            if islands:
                from vrpms_tpu.mesh import solve_aco_islands

                mesh, ip = _island_setup(opts)
                return solve_aco_islands(
                    inst,
                    key=seed,
                    mesh=mesh,
                    params=p,
                    island_params=ip,
                    weights=w,
                    deadline_s=_deadline(opts),
                    init_perm=warm,
                    pool=pool,
                )
            return solve_aco(
                inst,
                key=seed,
                params=p,
                weights=w,
                deadline_s=_deadline(opts),
                init_perm=warm,
                pool=pool,
                # explicit re-solve seeds pre-deposit the seed tour's
                # pheromone hard (aco.CONTINUATION_DEPOSIT) so the
                # colony refines instead of re-exploring
                continuation=continuation,
            )
        if algorithm == "ga":
            population = int(pop or (ga_params or {}).get("random_permutationCount") or 128)
            generations = int(iters or (ga_params or {}).get("iteration_count") or 300)
            p = GAParams(
                population=max(population, 8),
                generations=max(generations, 1),
                elites=max(2, min(16, population // 8)),
            )
            if islands:
                from vrpms_tpu.mesh import solve_ga_islands

                mesh, ip = _island_setup(opts)
                init = None
                if warm is not None:
                    from vrpms_tpu.core.cost import resolve_eval_mode
                    from vrpms_tpu.solvers.ga import perturbed_perm_clones

                    n_isl = mesh.shape["islands"]
                    per_isl = max(
                        -(-p.population // n_isl),
                        max(p.elites, ip.n_migrants) + 1,
                    )
                    init = perturbed_perm_clones(
                        jax.random.key(seed + 1),
                        per_isl * n_isl,
                        warm,
                        resolve_eval_mode("auto"),
                        n_real_perm=inst.perm_limit,
                    )
                return solve_ga_islands(
                    inst,
                    key=seed,
                    mesh=mesh,
                    params=p,
                    island_params=ip,
                    weights=w,
                    deadline_s=_deadline(opts),
                    pool=pool,
                    init_perms=init,
                )
            init = None
            if warm is not None:
                # Whole population seeded from the checkpointed order
                # (see the SA warm branch above for the rationale). A
                # CONTINUATION seed (explicit re-solve source) uses the
                # graded ramp instead: most of the population stays in
                # the seed's basin, a heavy tail keeps diversity.
                from vrpms_tpu.core.cost import resolve_eval_mode
                from vrpms_tpu.solvers.ga import (
                    continuation_perm_ramp,
                    perturbed_perm_clones,
                )

                seed_pop = (
                    continuation_perm_ramp
                    if continuation
                    else perturbed_perm_clones
                )
                init = seed_pop(
                    jax.random.key(seed + 1),
                    p.population,
                    warm,
                    resolve_eval_mode("auto"),
                    n_real_perm=inst.perm_limit,
                )
            return solve_ga(
                inst,
                key=seed,
                params=p,
                weights=w,
                init_perms=init,
                deadline_s=_deadline(opts),
                pool=pool,
            )
        raise ValueError(f"unknown algorithm {algorithm!r}")
    except ValueError as e:
        errors += [{"what": "Solver error", "reason": str(e)}]
        return None


PROFILE_DIR = "/tmp/vrpms_profile"


@contextlib.contextmanager
def _profiled(opts):
    """jax.profiler trace context when the request asks for one.

    The trace always lands under the fixed PROFILE_DIR — the request
    flag is treated as a boolean, never as a path (a request-supplied
    path would let callers write anywhere the server can). Best-effort:
    a failure to start tracing (e.g. a trace already active from a
    concurrent request) must not fail the solve.
    """
    if not opts.get("profile"):
        yield None
        return
    try:
        ctx = jax.profiler.trace(PROFILE_DIR)
        ctx.__enter__()
    except Exception:
        yield None
        return
    try:
        yield PROFILE_DIR
    finally:
        try:
            ctx.__exit__(None, None, None)
        except Exception:
            pass


POLISH_BLOCK_SWEEPS = 16
POLISH_TOP_K = 8  # delta_ls candidates per sweep; fixed so the eval
                  # count identifies mid-block convergence exactly


def _polish_enabled(opts):
    """Whether the delta-descent polish runs: `localSearch` truthy, or —
    when `localSearch` is simply absent — an explicit `localSearchPool`
    > 1 (asking to polish a pool clearly intends the polish; an explicit
    `localSearch: false` still wins and disables it)."""
    spec = opts.get("local_search")
    if spec is not None:
        return bool(spec)
    try:
        return int(opts.get("local_search_pool") or 0) > 1
    except (TypeError, ValueError):
        return False


def _polish_spec(opts):
    """The sweep budget the polish runs with (see _polish_enabled)."""
    spec = opts.get("local_search")
    return spec if spec is not None else _polish_enabled(opts)


def _polish(res, inst, opts, w, t_start):
    """Optional localSearch pass over the champion — or, when the solver
    returned an elite pool (localSearchPool > 1), over the whole pool at
    once, keeping the winner (distinct chains sit in distinct basins;
    measured ~1% better than champion-only polish on synth X-n200).

    `localSearch: true` uses the full default sweep budget; an integer
    caps the sweeps. Runs in fixed-size sweep blocks with a host clock
    check between them so a request's `timeLimit` bounds the polish too
    (same granularity contract as solve_sa's deadline blocks). Never
    returns a worse result: the final acceptance compares EXACT
    objectives (pool costs are mode-precision), and polish evals are
    accounted even when no sweep improved.
    """
    spec = _polish_spec(opts)
    if not spec or res is None:
        return res, False
    from vrpms_tpu.core.cost import exact_cost
    from vrpms_tpu.solvers import SolveResult, delta_polish_batch

    budget = 128 if spec is True else max(1, int(spec))
    deadline = _deadline(opts)
    giants = res.pool if res.pool is not None else res.giant[None]
    best_seen = None
    extra_evals = 0
    ran = False
    # at least ONE block always runs for an EXPLICIT localSearch request
    # (the ils_loop rule): the user asked for the polish, so the solver
    # consuming the whole timeLimit must not silently skip it (overshoot
    # bounded by one block). Implicit pool polish keeps strict deadlines.
    force_first = bool(opts.get("local_search"))
    while budget > 0:
        if (
            (ran or not force_first)
            and deadline is not None
            and time.perf_counter() - t_start >= deadline
        ):
            break
        block = min(POLISH_BLOCK_SWEEPS, budget)
        giants, costs, evals = delta_polish_batch(
            giants, inst, w, max_sweeps=block, top_k=POLISH_TOP_K
        )
        ran = True
        extra_evals += int(evals)
        budget -= block
        # evals == sweeps * B * top_k, so fewer than a full block's worth
        # means the descent converged mid-block — skip the no-op next call
        converged = int(evals) < block * giants.shape[0] * POLISH_TOP_K
        new_best = float(jnp.min(costs))
        if converged or (
            best_seen is not None and new_best >= best_seen - 1e-6
        ):
            break
        best_seen = new_best
    # saturate like ils_loop does: extreme pool*sweep budgets must not
    # wrap the int32 stats counter
    evals = jnp.int32(min(int(res.evals) + extra_evals, 2**31 - 1))
    if not ran:
        return res._replace(evals=evals), ran
    champ = giants[int(jnp.argmin(costs))]
    bd, cost = exact_cost(champ, inst, w)
    if float(cost) >= float(res.cost):
        return res._replace(evals=evals), ran
    return SolveResult(champ, cost, bd, evals), ran


def flight_partial(timer, wall_s: float, evals: int,
                   compile_s: float = 0.0) -> dict:
    """The solver-side half of a flight record (ISSUE 20): wall clock,
    throughput, the driver's device/host split, and the vmapped
    launch's batch fill when the timer saw one. The finish seams merge
    this with the request-side half (_offer_flight)."""
    ratio = timer.overlap_ratio()
    doc = {
        "wallMs": round(wall_s * 1e3, 1),
        "evals": int(evals),
        "evalsPerSec": (
            round(evals / wall_s, 1) if wall_s > 0 else None
        ),
        # 6 decimals: tiny tiers block for microseconds per launch and
        # must still register a nonzero device share
        "deviceS": round(timer.wait_s, 6),
        "hostS": round(timer.overlap_s + timer.host_s, 6),
        "overlapRatio": None if ratio is None else round(ratio, 4),
        "blocks": timer.blocks,
    }
    if compile_s:
        doc["compileS"] = round(compile_s, 3)
    if timer.batch_members is not None and timer.batch_padded:
        doc["batch"] = {
            "members": int(timer.batch_members),
            "padded": int(timer.batch_padded),
            "maxBatch": max(1, int(config.get("VRPMS_SCHED_MAX_BATCH"))),
            "fill": round(timer.batch_members / timer.batch_padded, 4),
        }
    return doc


def _offer_flight(prep: Prepared, res, extras) -> None:
    """Assemble the completed solve's flight record from the solver
    partial (extras['flight']) plus everything only the finish seam
    knows — tier shape + padding occupancy, final cost and gap vs the
    sink's quick lower bound, the primal integral over the progress
    profile, cache/warm outcome — and offer it to the analytics
    exporter. Gated on VRPMS_ANALYTICS (one env read off); must never
    fail or slow the solve it describes."""
    if not analytics.enabled():
        return
    try:
        sink = progress.active_sink()
        job_id = getattr(sink, "job_id", None) or spans.current_trace_id()
        if not job_id:
            return  # nothing durable to key the record by
        doc = dict((extras or {}).get("flight") or {})
        doc["jobId"] = str(job_id)
        doc["problem"] = prep.problem
        doc["algorithm"] = prep.algorithm
        if prep.inst is not None:
            doc["tier"] = tiers.tier_label(prep.inst, prep.problem)
            doc["occupancy"] = tiers.occupancy(prep.inst)
        doc["cost"] = _as_float(res.cost)
        lb = getattr(sink, "lower_bound", None)
        if lb:
            doc["lowerBound"] = round(float(lb), 6)
            if lb > 0:
                doc["gap"] = round((doc["cost"] - lb) / lb, 6)
        if sink is not None:
            pi = analytics.primal_integral(sink.profile())
            if pi is not None:
                doc["primalIntegral"] = pi
        doc["cache"] = (
            prep.cache.get("outcome") if prep.cache else None
        )
        doc["warmStart"] = prep.warm is not None
        doc["qos"] = str(prep.opts.get("qos") or "standard")
        doc["replica"] = analytics.replica_identity()
        doc["traceId"] = spans.current_trace_id()
        doc["finishedAt"] = time.time()
        analytics.offer(doc)
    except Exception as e:
        log_event(
            "analytics.assemble_error",
            level="warn",
            error=f"{type(e).__name__}: {e}",
        )


def _run_solver(inst, algorithm, opts, ga_params, errors, problem, warm,
                extras=None, continuation=False):
    """Timed + optionally profiled dispatch; returns (res, stats|None).

    `extras`, when given, is filled with solver-path metadata that
    belongs in the response regardless of includeStats — currently the
    exact path's proof certificate (extras["exact"]).
    """
    t0 = time.perf_counter()
    w = _request_weights(opts)
    include_stats = bool(opts.get("include_stats"))
    from vrpms_tpu.obs import compile as compile_obs

    # THREAD-local snapshot: the solve runs (and compiles) on this
    # thread, so a concurrent request or the background tier warmup
    # can't leak into this solve's compile attribution
    compiles0, compile_s0 = compile_obs.snapshot_local()
    # the block-trace collector is installed ONLY under includeStats:
    # without it the solver loops pay one ContextVar read per block and
    # the result stays byte-identical to the pre-telemetry contract.
    # The flight timer (ISSUE 20) follows the same rule: installed only
    # under VRPMS_ANALYTICS, one ContextVar read per solve otherwise.
    ftimer = analytics.FlightTimer() if analytics.enabled() else None
    with _profiled(opts) as trace_dir, collect_blocks(include_stats) as btrace, \
            analytics.flight(ftimer):
        with spans.span(
            "solver.solve", algorithm=algorithm, problem=problem
        ) as solve_span:
            res = _solve_instance(
                inst, algorithm, opts, ga_params, errors, problem, warm, w,
                extras, continuation,
            )
        t_polish = time.perf_counter()
        if _polish_spec(opts) and res is not None:
            with spans.span("solver.polish"):
                res, polished = _polish(res, inst, opts, w, t0)
        else:
            res, polished = _polish(res, inst, opts, w, t0)
        polish_s = time.perf_counter() - t_polish
        if res is not None:
            jax.block_until_ready(res.cost)
    wall_s = time.perf_counter() - t0
    # compile attribution joins the span tree too: a slow trace whose
    # solve span carries compile* attrs is a cold-start, not a solver
    # regression (the exact question an operator asks about a p99 spike)
    compiles1, compile_s1 = compile_obs.snapshot_local()
    if solve_span is not None and compiles1 > compiles0:
        solve_span.set(
            compileCount=compiles1 - compiles0,
            compileSeconds=round(compile_s1 - compile_s0, 3),
        )
    if res is not None:
        trace_id = spans.current_trace_id()
        obs.SOLVE_SECONDS.labels(problem=problem, algorithm=algorithm).observe(
            wall_s, trace_id=trace_id
        )
        obs.SOLVE_EVALS.observe(float(res.evals))
        if polished:
            obs.POLISH_SECONDS.observe(polish_s, trace_id=trace_id)
    if res is not None and ftimer is not None and extras is not None:
        extras["flight"] = flight_partial(
            ftimer, wall_s, int(res.evals),
            compile_s1 - compile_s0 if compiles1 > compiles0 else 0.0,
        )
    if res is None or not include_stats:
        return res, None
    stats = {
        "algorithm": algorithm,
        "evals": int(res.evals),
        "wallMs": round(wall_s * 1e3, 1),
        "backend": jax.default_backend(),
        "warmStart": warm is not None,
        "localSearch": polished,
    }
    if compiles1 > compiles0:
        # the solve paid XLA compiles (first sighting of its shape tier
        # in this process): surface what cold-start actually cost
        stats["compile"] = {
            "count": compiles1 - compiles0,
            "seconds": round(compile_s1 - compile_s0, 3),
        }
    if btrace is not None and btrace.blocks:
        stats["trace"] = btrace.blocks
        conv = convergence_summary(btrace.blocks)
        if conv is not None:
            stats["convergence"] = conv
    # SA/GA/ACO island-shard (bf ignores the option)
    if opts.get("islands") and algorithm in ("sa", "ga", "aco"):
        stats["islands"] = _island_devices(opts)[0]
    if opts.get("ils_rounds") and algorithm == "sa":
        stats["ilsRounds"] = int(opts["ils_rounds"])
    if trace_dir:
        stats["profileDir"] = trace_dir
    return res, stats


@dataclasses.dataclass
class Prepared:
    """A validated, device-ready request — the unit the scheduler moves.

    Produced on the HTTP thread (validation + store reads + instance
    build are cheap and must fail fast as 400s); consumed on the
    scheduler's device-owning worker thread (solve_prepared), possibly
    merged with same-shape requests into one batched launch
    (vrpms_tpu.sched.batch + service.jobs). `trivial` short-circuits
    the zero-customer case without touching the device.
    """

    problem: str
    algorithm: str
    params: dict
    opts: dict
    ga_params: dict
    inst: object = None
    orig_ids: list = None
    anchor_id: int = 0       # VRP: depot's original id; TSP: startNode
    capacities: list = None  # VRP only
    warm: object = None
    database: object = None
    trivial: dict | None = None
    # content-addressed cache context (service.cache.attach): keys +
    # lookup outcome, an optional deferred near-hit seed, and — on an
    # exact hit — the servable cached response (submit paths return it
    # without enqueueing; solve_prepared serves it inline)
    cache: dict | None = None
    cached: dict | None = None
    # dynamic re-solve context (service.cache._attach_resolve): how an
    # explicit warmStart spec resolved — {seedSource, seeded, jobId?}.
    # A seeded resolve drives the solver continuation schedules and is
    # disclosed under stats.resolve
    resolve: dict | None = None
    # giant-instance decomposition (core.decompose): the cluster plan a
    # request above the tier ladder top solves through instead of a
    # monolithic Instance (prep.inst stays None — the whole point is
    # never materializing the giant padded tensors)
    decomp: object = None
    # crash-resume context (service.checkpoint): the predecessor
    # attempt's durable checkpoint state — today only its completed
    # SHARD map is consumed here (a resumed decomposition solves only
    # the remaining shards); monolithic resumes ride warm/resolve above
    ckpt: dict | None = None


def prepare_vrp(algorithm, params, opts, ga_params, locations, matrix,
                errors, database=None) -> Prepared | None:
    """Validate a VRP request and build its device Instance (no solving).

    Fills `errors` and returns None on any contract violation — the
    same 400-envelope entries run_vrp produced when this logic was
    inline. May raise on malformed option types; callers wrap
    (_enveloped / service.jobs submit path)."""
    capacities = params["capacities"]
    start_times = params["start_times"]
    if not isinstance(capacities, list) or not capacities:
        errors += [
            {"what": "Data error", "reason": "'capacities' must be a non-empty list"}
        ]
        return None
    if not isinstance(start_times, list) or len(start_times) != len(capacities):
        errors += [
            {
                "what": "Data error",
                "reason": "'startTimes' must be a list with one entry per vehicle",
            }
        ]
        return None

    ids = [loc.get("id") for loc in locations]
    depot_pos = ids.index(0) if 0 in ids else 0
    excluded = set((params["ignored_customers"] or []) + (params["completed_customers"] or []))
    active_pos = [depot_pos] + [
        i
        for i, loc in enumerate(locations)
        if i != depot_pos and loc.get("id") not in excluded
    ]
    slice_minutes = float(opts.get("time_slice_duration") or DEFAULT_SLICE_MINUTES)
    arrays = _build_arrays(locations, matrix, active_pos, errors, slice_minutes)
    if arrays is None:
        return None

    prep = Prepared(
        problem="vrp", algorithm=algorithm, params=params, opts=opts,
        ga_params=ga_params, database=database,
        anchor_id=locations[depot_pos]["id"],
        capacities=[float(c) for c in capacities],
    )
    n_customers = len(active_pos) - 1
    if n_customers == 0:
        prep.trivial = {"durationMax": 0, "durationSum": 0, "vehicles": []}
        return prep

    # Giant-instance decomposition (core.decompose): above the tier
    # ladder top there is no canonical shape to pad to, so the request
    # clusters into same-tier shards instead of building a monolithic
    # Instance. Strictly a superset gate: any instance that fits one
    # tier falls through to the exact path below, byte-identically.
    if (
        decompose.engaged(
            "vrp", algorithm, len(active_pos), opts
        )
        and arrays["ready"] is None
        and np.asarray(arrays["durations"]).ndim == 2
    ):
        try:
            with spans.span("decompose", phase="plan"):
                prep.decomp = decompose.build_plan(
                    arrays["durations"],
                    arrays["demands"],
                    arrays["service"],
                    prep.capacities,
                    [float(t) for t in start_times],
                    slice_minutes=slice_minutes,
                    seed=int(opts.get("seed") or 0),
                )
        except ValueError as e:
            # an unplannable instance (e.g. fewer vehicles than tier
            # shards) falls THROUGH to the monolithic path below — it
            # solved there before decomposition existed, and a
            # default-on optimization must never turn a solvable
            # request into an error
            log_event("decompose.fallback", reason=str(e))
            prep.decomp = None
        if prep.decomp is not None:
            prep.orig_ids = [locations[i]["id"] for i in active_pos]
            # no cache attach: fingerprinting would materialize exactly
            # the giant padded tensors this path exists to avoid
            return prep

    prep.inst = make_instance(
        arrays["durations"],
        demands=arrays["demands"],
        capacities=prep.capacities,
        ready=arrays["ready"],
        due=arrays["due"],
        service=arrays["service"],
        start_times=[float(t) for t in start_times],
        slice_minutes=slice_minutes,
        slice_axis=arrays["slice_axis"],
    )
    # shape-tier canonicalization (core.tiers): every size in a tier
    # shares one compiled program and one micro-batch bucket. The exact
    # solvers (bf ladder) keep the real shape — enumeration cost scales
    # factorially with the padded size.
    if algorithm != "bf":
        prep.inst = tiers.maybe_pad(prep.inst)
    prep.orig_ids = [locations[i]["id"] for i in active_pos]
    # The content-addressed cache is the ONE warm-start code path now:
    # it serves exact hits, seeds near hits, and routes the legacy
    # warmStart lookup through the fingerprint/family index (falling
    # back to the keep-best checkpoint row when the index is cold).
    # SA/GA/ACO all consume a warm seed, islands included (round 3: the
    # island paths take perturbed checkpoint clones as their first-round
    # chains/population — VERDICT round-2 item 8; BF is the only solver
    # without a warm hook, being exact).
    solution_cache.attach(prep, locations, matrix, database)
    return prep


def finish_vrp(prep: Prepared, res, stats, extras, errors) -> dict:
    """Decode a VRP SolveResult to the contract shape + checkpoint it."""
    with spans.span("finish", problem="vrp"):
        return _finish_vrp(prep, res, stats, extras, errors)


def _finish_vrp(prep: Prepared, res, stats, extras, errors) -> dict:
    bd = res.breakdown
    route_durs = np.asarray(bd.route_durations)
    demands = np.asarray(prep.inst.demands)
    depot_id = prep.anchor_id
    n_real = None if prep.inst.n_real is None else int(prep.inst.n_real)
    vehicles = []
    for r, route in enumerate(routes_from_giant(res.giant, n_real)):
        if not route:
            continue
        vehicles.append(
            {
                "id": r,
                "capacity": float(prep.capacities[r]),
                "tour": [depot_id] + [prep.orig_ids[c] for c in route] + [depot_id],
                "duration": float(route_durs[r]),
                "load": float(sum(demands[c] for c in route)),
            }
        )
    result = {
        "durationMax": _as_float(bd.duration_max),
        "durationSum": _as_float(bd.duration_sum),
        "vehicles": vehicles,
    }
    if extras.get("exact") is not None:
        result["exact"] = extras["exact"]
    if stats is not None:
        result["stats"] = stats
    routes = [v["tour"][1:-1] for v in vehicles]
    chk_cost = _as_float(res.cost)  # penalized objective, not raw duration
    if prep.database is not None:
        with spans.span("store.persist", table="warmstarts"):
            prep.database.save_warmstart(
                prep.params["name"],
                {"problem": "vrp", "routes": routes, "cost": chk_cost},
                better_than=lambda prev: _better_checkpoint(prev, "vrp", routes, chk_cost),
            )
    result = solution_cache.store_result(prep, result, routes, chk_cost)
    _offer_flight(prep, res, extras)
    return _mark_degraded(prep, result)


def _mark_degraded(prep: Prepared, result: dict) -> dict:
    """Flag results whose request was served by store fallbacks.

    The resilient store wrapper (store.resilient) flips `degraded` on
    the per-request database instance whenever a read came from the
    last-known-rows cache or a write spooled to the replay journal —
    the contract's honesty bit: the solve is real, the persistence
    around it was best-effort.
    """
    if result is not None and getattr(prep.database, "degraded", False):
        result["degraded"] = True
    return result


def _solve_decomposed(prep: Prepared, errors) -> dict | None:
    """The giant-instance path: cluster plan -> batched same-tier shard
    solves -> stitch + boundary repair -> contract-shaped result.

    Shards dispatch through sched.batch.solve_sa_batch in chunks of
    VRPMS_SCHED_MAX_BATCH (ceil(K / max_batch) vmapped launches), with
    per-shard incumbents rolled up into the job's single progress sink;
    the request deadline splits 80/20 between the shard solves and the
    boundary re-opt. The response gains a `decomposition` block —
    additive only above the ladder ceiling, where no pre-decomposition
    response existed to stay byte-identical to.
    """
    from vrpms_tpu.solvers import SAParams

    plan = prep.decomp
    opts = prep.opts
    t0 = time.perf_counter()
    w = _request_weights(opts)
    seed = int(opts.get("seed") or 0)
    params = SAParams(
        n_chains=int(opts.get("population_size") or 128),
        n_iters=int(opts.get("iteration_count") or 5000),
    )
    deadline = _deadline(opts)
    max_batch = max(1, int(config.get("VRPMS_SCHED_MAX_BATCH")))
    sink = progress.active_sink()
    rollup = decompose.ShardRollup(sink, plan.n_shards)
    # crash-resume: restore checkpoint-completed shards (validated
    # against THIS plan) and checkpoint each newly completed shard's
    # routes, so a killed decomposition resumes with only the remaining
    # shards to solve. The capture handle rides the job's sink
    # (service.checkpoint.register); solves with none attached — sync
    # paths, VRPMS_CKPT=off — pay a getattr and nothing else.
    ckpt_handle = getattr(sink, "ckpt", None)
    completed = {}
    if prep.ckpt is not None:
        completed = decompose.completed_from_state(
            plan, prep.ckpt.get("shards")
        )
        if ckpt_handle is not None:
            # the resumed attempt's OWN checkpoint must carry the
            # restored shards too: its upsert supersedes the
            # predecessor's row, and a second failover would otherwise
            # read back only the shards THIS attempt solved
            for si, cs in completed.items():
                ckpt_handle.note_shard(si, cs.routes, cs.cost)

    def _note_shard(si: int, res) -> None:
        if ckpt_handle is None:
            return
        local = decompose._local_routes(res, int(plan.members[si].size) + 1)
        ckpt_handle.note_shard(si, local, float(res.cost))
    ftimer = analytics.FlightTimer() if analytics.enabled() else None
    with _device_ctx(opts.get("backend")), analytics.flight(ftimer):
        with spans.span(
            "decompose", shards=plan.n_shards, tier=plan.tier_n
        ) as dspan:
            insts = decompose.shard_instances(plan)
            if dspan is not None:
                # per-shard events: the n=5000 waterfall names every
                # shard (index, size, launch chunk) instead of one
                # opaque span. Capped BELOW the span event limit so the
                # launch-timing events emitted during the solve always
                # have room — a 100-shard plan must not spend the whole
                # cap on shard rows and silently drop the launch story
                launch_room = math.ceil(plan.n_shards / max_batch) + 1
                shard_cap = max(
                    0, spans.MAX_EVENTS_PER_SPAN - launch_room
                )
                for si, members in enumerate(plan.members):
                    if si >= shard_cap:
                        dspan.event(
                            "shard.truncated",
                            shown=shard_cap,
                            shards=plan.n_shards,
                        )
                        break
                    dspan.event(
                        "shard",
                        shard=si,
                        tier=plan.tier_n,
                        n=int(members.size),
                        chunk=si // max_batch,
                    )
        seeds = [seed + i for i in range(len(insts))]
        with spans.span(
            "solver.solve", algorithm=prep.algorithm, problem=prep.problem
        ):
            results, launches = decompose.solve_shards(
                insts,
                seeds,
                params,
                weights=w,
                deadline_s=None if deadline is None else 0.8 * deadline,
                max_batch=max_batch,
                rollup=rollup,
                completed=completed,
                on_shard=_note_shard,
                # launch timing lands on the SAME decompose span (spans
                # may be annotated after end), so shards and the
                # vmapped launches that ran them read as one story
                on_launch=(
                    None
                    if dspan is None
                    else lambda ci, lo, size, wall_s: dspan.event(
                        "launch",
                        chunk=ci,
                        shardLo=lo,
                        size=size,
                        wallMs=round(wall_s * 1e3, 2),
                    )
                ),
            )
        with spans.span("stitch", boundary=int(plan.boundary.size)):
            routes = decompose.stitch(plan, results)
            # keep-best guard: the rolled-up shard solution the progress
            # stream already published IS a feasible full solution; the
            # boundary repair must never ship anything worse (and the
            # final publish_total then always respects the stream's
            # monotone non-increasing contract)
            baseline = [list(r) for r in routes]
            ev0 = decompose.evaluate_routes(plan, baseline)
            remaining = (
                None
                if deadline is None
                else max(0.0, deadline - (time.perf_counter() - t0))
            )
            report = decompose.repair_boundary(
                plan, routes, seed=seed, weights=w, deadline_s=remaining,
                n_chains=params.n_chains,
            )
            report["rebalanced"] = decompose.rebalance_capacity(plan, routes)
    ev = decompose.evaluate_routes(plan, routes)
    # the untimed penalized objective, exactly total_cost's terms (the
    # engagement gate excludes TW/TD/makespan so the other terms are 0)
    cap_w = float(np.asarray(w.cap))
    chk_cost = ev["distance"] + cap_w * ev["cap_excess"]
    cost0 = ev0["distance"] + cap_w * ev0["cap_excess"]
    if chk_cost > cost0 + 1e-6:
        routes, ev, chk_cost = baseline, ev0, cost0
        report["reverted"] = True
    rollup.publish_total(chk_cost)
    wall_s = time.perf_counter() - t0
    evals = sum(int(r.evals) for r in results) + report.get("reoptEvals", 0)
    trace_id = spans.current_trace_id()
    obs.SOLVE_SECONDS.labels(
        problem=prep.problem, algorithm=prep.algorithm
    ).observe(wall_s, trace_id=trace_id)
    obs.SOLVE_EVALS.observe(float(evals))
    obs.DECOMP_SHARDS.observe(float(plan.n_shards))
    obs.DECOMP_LAUNCHES.observe(float(launches))
    obs.DECOMP_BOUNDARY.observe(float(report.get("boundary", 0)))

    depot_id = prep.anchor_id
    vehicles = []
    for v, route in enumerate(routes):
        if not route:
            continue
        vehicles.append(
            {
                "id": v,
                "capacity": float(prep.capacities[v]),
                "tour": [depot_id]
                + [prep.orig_ids[c] for c in route]
                + [depot_id],
                "duration": float(ev["route_durations"][v]),
                "load": float(ev["route_loads"][v]),
            }
        )
    result = {
        "durationMax": ev["duration_max"],
        "durationSum": ev["duration_sum"],
        "vehicles": vehicles,
        "decomposition": {
            "shards": plan.n_shards,
            "launches": launches,
            "maxBatch": max_batch,
            "tier": plan.tier_n,
            "boundary": report.get("boundary", 0),
            "reoptimized": bool(report.get("reoptimized")),
            "rebalanced": report.get("rebalanced", 0),
            "lowerBound": plan.lower_bound,
        },
    }
    if completed:
        # disclose the resume: how many shards this attempt restored
        # from the predecessor's checkpoint instead of re-solving
        result["decomposition"]["resumedShards"] = len(completed)
    if opts.get("include_stats"):
        result["stats"] = {
            "algorithm": prep.algorithm,
            "evals": evals,
            "wallMs": round(wall_s * 1e3, 1),
            "backend": jax.default_backend(),
            "warmStart": False,
            "localSearch": False,
        }
    routes_ids = [v["tour"][1:-1] for v in vehicles]
    if prep.database is not None:
        with spans.span("store.persist", table="warmstarts"):
            prep.database.save_warmstart(
                prep.params["name"],
                {"problem": "vrp", "routes": routes_ids, "cost": chk_cost},
                better_than=lambda prev: _better_checkpoint(
                    prev, "vrp", routes_ids, chk_cost
                ),
            )
    if ftimer is not None:
        # the decomposed path's flight record: no monolithic Instance
        # exists, so the tier names the shard ladder rung and occupancy
        # is omitted; the gap references the plan's shard-sum bound
        try:
            doc = flight_partial(ftimer, wall_s, int(evals))
            job_id = getattr(sink, "job_id", None) or trace_id
            if job_id:
                doc.update(
                    jobId=str(job_id),
                    problem=prep.problem,
                    algorithm=prep.algorithm,
                    tier=f"{prep.problem}:decomposed:{plan.tier_n}",
                    cost=float(chk_cost),
                    cache=None,
                    warmStart=False,
                    qos=str(opts.get("qos") or "standard"),
                    replica=analytics.replica_identity(),
                    traceId=trace_id,
                    finishedAt=time.time(),
                )
                lb = plan.lower_bound
                if lb:
                    doc["lowerBound"] = round(float(lb), 6)
                    if lb > 0:
                        doc["gap"] = round((doc["cost"] - lb) / lb, 6)
                if sink is not None:
                    pi = analytics.primal_integral(sink.profile())
                    if pi is not None:
                        doc["primalIntegral"] = pi
                analytics.offer(doc)
        except Exception as e:
            log_event(
                "analytics.assemble_error",
                level="warn",
                error=f"{type(e).__name__}: {e}",
            )
    return _mark_degraded(prep, result)


def solve_prepared(prep: Prepared, errors) -> dict | None:
    """Run a Prepared request end to end on the calling thread: device
    dispatch + decode + checkpoint save. The scheduler worker's solo
    path, and (composed under _enveloped) run_vrp/run_tsp's tail."""
    if prep.trivial is not None:
        return _mark_degraded(prep, solution_cache.mark_trivial(prep))
    if prep.decomp is not None:
        # giant-instance path: cluster -> batched shard solves -> stitch
        return _solve_decomposed(prep, errors)
    if prep.cached is not None:
        # exact cache hit that reached the inline path (VRPMS_SCHED=off
        # or a direct run_vrp/run_tsp call): serve without solving
        return solution_cache.serve_hit(prep)
    # implicit near-hit seeds apply only here — a job the micro-batcher
    # merged never reaches solve_prepared, so batching is preserved
    solution_cache.apply_deferred_seed(prep)
    extras: dict = {}
    continuation = bool(prep.resolve and prep.resolve.get("seeded"))
    with _device_ctx(prep.opts.get("backend")):
        res, stats = _run_solver(
            prep.inst, prep.algorithm, prep.opts, prep.ga_params, errors,
            prep.problem, prep.warm, extras, continuation,
        )
    if res is None:
        return None
    if stats is not None and prep.resolve is not None:
        # every metaheuristic now has a real continuation schedule: SA
        # re-enters at the seed-estimated temperature, GA ramps the
        # seeded population, ACO pre-deposits the seed tour's pheromone.
        # The GA/ACO ISLAND paths still consume seeds through the plain
        # warm handling, so the flag stays honest there (SA applies
        # continuation_params before its islands split)
        stats["resolve"] = dict(
            prep.resolve,
            continuation=continuation
            and (
                prep.algorithm == "sa"
                or (
                    prep.algorithm in ("ga", "aco")
                    and not prep.opts.get("islands")
                )
            ),
        )
    if prep.problem == "vrp":
        return finish_vrp(prep, res, stats, extras, errors)
    return finish_tsp(prep, res, stats, extras, errors)


@_enveloped
def run_vrp(algorithm, params, opts, ga_params, locations, matrix, errors, database=None):
    """Solve a VRP request; returns the contract result dict or None."""
    prep = prepare_vrp(
        algorithm, params, opts, ga_params, locations, matrix, errors, database
    )
    if prep is None or errors:
        return None
    return solve_prepared(prep, errors)


def prepare_tsp(algorithm, params, opts, ga_params, locations, matrix,
                errors, database=None) -> Prepared | None:
    """Validate a TSP request and build its device Instance (no solving);
    the TSP sibling of prepare_vrp."""
    customers = params["customers"]
    start_node = params["start_node"]
    if not isinstance(customers, list):
        errors += [{"what": "Data error", "reason": "'customers' must be a list"}]
        return None
    customers = list(dict.fromkeys(customers))  # dedupe, preserving order
    ids = [loc.get("id") for loc in locations]
    if start_node not in ids:
        errors += [
            {"what": "Data error", "reason": f"startNode {start_node} not in locations"}
        ]
        return None
    missing = [c for c in customers if c not in ids]
    if missing:
        errors += [
            {"what": "Data error", "reason": f"customers {missing} not in locations"}
        ]
        return None

    depot_pos = ids.index(start_node)
    active_pos = [depot_pos] + [
        ids.index(c) for c in customers if c != start_node
    ]
    slice_minutes = float(opts.get("time_slice_duration") or DEFAULT_SLICE_MINUTES)
    arrays = _build_arrays(locations, matrix, active_pos, errors, slice_minutes)
    if arrays is None:
        return None

    prep = Prepared(
        problem="tsp", algorithm=algorithm, params=params, opts=opts,
        ga_params=ga_params, database=database, anchor_id=start_node,
    )
    if len(active_pos) == 1:
        prep.trivial = {"duration": 0, "vehicle": []}
        return prep

    start_time = float(params["start_time"] or 0)
    prep.inst = make_instance(
        arrays["durations"],
        demands=None,
        n_vehicles=1,
        ready=arrays["ready"],
        due=arrays["due"],
        service=arrays["service"],
        start_times=[start_time],
        slice_minutes=slice_minutes,
        slice_axis=arrays["slice_axis"],
    )
    if algorithm != "bf":
        prep.inst = tiers.maybe_pad(prep.inst)  # see prepare_vrp
    prep.orig_ids = [locations[i]["id"] for i in active_pos]
    # The one cache/warm-start choke point (see prepare_vrp). SA/GA
    # consume a warm seed only without islands; ACO warms its colony
    # incumbent either way (solve_aco/solve_aco_islands init_perm) —
    # service.cache._warm_supported encodes exactly those rules.
    solution_cache.attach(prep, locations, matrix, database)
    return prep


def finish_tsp(prep: Prepared, res, stats, extras, errors) -> dict:
    """Decode a TSP SolveResult to the contract shape + checkpoint it."""
    with spans.span("finish", problem="tsp"):
        return _finish_tsp(prep, res, stats, extras, errors)


def _finish_tsp(prep: Prepared, res, stats, extras, errors) -> dict:
    start_node = prep.anchor_id
    n_real = None if prep.inst.n_real is None else int(prep.inst.n_real)
    routes = routes_from_giant(res.giant, n_real)
    # the single vehicle's customers; padded tours may trail phantom
    # separators, so concatenate every (real-customer) route segment
    customers = [c for route in routes for c in route]
    tour = [start_node] + [prep.orig_ids[c] for c in customers] + [start_node]
    result = {
        "duration": _as_float(res.breakdown.duration_sum),
        "vehicle": tour,
    }
    if extras.get("exact") is not None:
        result["exact"] = extras["exact"]
    if stats is not None:
        result["stats"] = stats
    routes = [tour[1:-1]]
    chk_cost = _as_float(res.cost)  # penalized objective, not raw duration
    if prep.database is not None:
        with spans.span("store.persist", table="warmstarts"):
            prep.database.save_warmstart(
                prep.params["name"],
                {"problem": "tsp", "routes": routes, "cost": chk_cost},
                better_than=lambda prev: _better_checkpoint(prev, "tsp", routes, chk_cost),
            )
    result = solution_cache.store_result(prep, result, routes, chk_cost)
    _offer_flight(prep, res, extras)
    return _mark_degraded(prep, result)


@_enveloped
def run_tsp(algorithm, params, opts, ga_params, locations, matrix, errors, database=None):
    """Solve a TSP request; returns the contract result dict or None."""
    prep = prepare_tsp(
        algorithm, params, opts, ga_params, locations, matrix, errors, database
    )
    if prep is None or errors:
        return None
    return solve_prepared(prep, errors)


def prepare_request(problem, algorithm, params, opts, ga_params, locations,
                    matrix, errors, database=None) -> Prepared | None:
    """Problem-dispatching prepare with the _enveloped exception contract
    inlined — the async submit path (service.jobs) has no run_vrp/run_tsp
    wrapper around it, but a malformed body must still come back as a
    Data-error envelope entry, never a raised exception."""
    fn = prepare_vrp if problem == "vrp" else prepare_tsp
    try:
        with spans.span("prepare", problem=problem, algorithm=algorithm):
            return fn(algorithm, params, opts, ga_params, locations, matrix,
                      errors, database)
    except Exception as e:
        log_event(
            "prepare.exception",
            algorithm=algorithm,
            error=f"{type(e).__name__}: {e}",
            traceback=traceback.format_exc(),
        )
        errors += [{"what": "Data error", "reason": f"{type(e).__name__}: {e}"}]
        return None
