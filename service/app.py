"""Local HTTP server: one router standing in for Vercel's path mapping.

The reference deploys each api/**/index.py as a serverless function
routed by file path (reference README.md:69-72, vercel.json). For
self-hosted/local serving this router reproduces that mapping in one
threading HTTP server:

    python -m service.app --port 8080 [--fixtures fixtures.json] [--store memory]

Routes: /api, /api/{vrp,tsp}/{ga,sa,aco,bf}, /api/jobs[/{id}],
/api/subscriptions[/{id}[/deltas|/stream]] (standing re-solve-on-change
jobs — service.subscriptions, VRPMS_SUBS-gated),
/api/ready (ok|degraded|down readiness — service.jobs.readiness),
/api/debug/traces[/{traceId}] (recent request traces — service.debug),
/metrics (Prometheus text exposition — service.obs). Unknown paths
-> 404.
"""

from __future__ import annotations

import argparse
import os
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from service import obs
from service.api.index import handler as health_handler
from vrpms_tpu import config
from service.debug import (
    AnalyticsHandler,
    FleetHandler,
    JobTimelineHandler,
    TraceDetailHandler,
    TracesHandler,
)
from service.jobs import (
    DrainHandler,
    JobResolveHandler,
    JobsHandler,
    JobStatusHandler,
    JobStreamHandler,
    ReadyHandler,
    shutdown_scheduler,
)
from service.autoscale import ScaleInHandler
from service.autoscale import enabled as autoscale_enabled
from service.subscriptions import (
    SubscriptionDeltasHandler,
    SubscriptionDetailHandler,
    SubscriptionsHandler,
    SubscriptionStreamHandler,
)
from service.subscriptions import enabled as subs_enabled
from service.api.vrp.ga.index import handler as vrp_ga
from service.api.vrp.sa.index import handler as vrp_sa
from service.api.vrp.aco.index import handler as vrp_aco
from service.api.vrp.bf.index import handler as vrp_bf
from service.api.tsp.ga.index import handler as tsp_ga
from service.api.tsp.sa.index import handler as tsp_sa
from service.api.tsp.aco.index import handler as tsp_aco
from service.api.tsp.bf.index import handler as tsp_bf
from vrpms_tpu.obs import log_event

ROUTES = {
    "/api": health_handler,
    "/api/vrp/ga": vrp_ga,
    "/api/vrp/sa": vrp_sa,
    "/api/vrp/aco": vrp_aco,
    "/api/vrp/bf": vrp_bf,
    "/api/tsp/ga": tsp_ga,
    "/api/tsp/sa": tsp_sa,
    "/api/tsp/aco": tsp_aco,
    "/api/tsp/bf": tsp_bf,
    "/api/jobs": JobsHandler,
    "/api/ready": ReadyHandler,
    "/api/admin/drain": DrainHandler,
    "/api/debug/traces": TracesHandler,
    "/api/debug/fleet": FleetHandler,
    "/metrics": obs.MetricsHandler,
}

# the standing-subscription surface registers for route-label purposes
# unconditionally, but dispatch consults VRPMS_SUBS per request (below):
# with the switch off every subscription path 404s and NO pre-existing
# route's behavior shifts by a byte
_SUB_ROUTES = {"/api/subscriptions": SubscriptionsHandler}

# same contract for the elastic-fleet scale-in surface: registered for
# route labels, VRPMS_AUTOSCALE consulted per request (off -> 404)
_AUTOSCALE_ROUTES = {"/api/admin/scalein": ScaleInHandler}

# and for the solve-analytics rollup: route label registered always,
# VRPMS_ANALYTICS consulted per request (off -> 404)
_ANALYTICS_ROUTES = {"/api/debug/analytics": AnalyticsHandler}

# the request counter's route label values come from the route table —
# an arbitrary 404 path can never mint a new series (service.obs)
obs.KNOWN_ROUTES.update(ROUTES)
obs.KNOWN_ROUTES.update(_SUB_ROUTES)
obs.KNOWN_ROUTES.update(_AUTOSCALE_ROUTES)
obs.KNOWN_ROUTES.update(_ANALYTICS_ROUTES)


class Router(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """Delegates each request to the per-route handler class by rebinding
    the handler instance's class — the per-route classes keep the exact
    shape Vercel expects (a BaseHTTPRequestHandler subclass per file), and
    the router stays a thin dispatch layer. Unmatched paths (404/501) are
    logged and counted here; matched ones by the route class's own mixin."""

    def _dispatch(self, method: str):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        cls = ROUTES.get(path)
        if cls is None and path.startswith("/api/jobs/"):
            # parameterized routes: /api/jobs/{id} status polls and
            # cancels, /api/jobs/{id}/stream live SSE progress,
            # /api/jobs/{id}/resolve cancel-and-resolve,
            # /api/jobs/{id}/timeline the stitched per-job event list
            if path.endswith("/stream"):
                cls = JobStreamHandler
            elif path.endswith("/resolve"):
                cls = JobResolveHandler
            elif path.endswith("/timeline"):
                cls = JobTimelineHandler
            else:
                cls = JobStatusHandler
        if cls is None and path.startswith("/api/debug/traces/"):
            # parameterized route: /api/debug/traces/{traceId}
            cls = TraceDetailHandler
        if path == "/api/debug/analytics":
            # solve-analytics rollup (VRPMS_ANALYTICS-gated per request
            # so a flip needs no restart; off -> plain 404, byte-
            # identical to the pre-analytics service)
            from vrpms_tpu.obs import analytics

            cls = AnalyticsHandler if analytics.enabled() else None
        if path == "/api/admin/scalein":
            # elastic-fleet scale-in (VRPMS_AUTOSCALE-gated per request
            # so a flip needs no restart; off -> plain 404, byte-
            # identical to the pre-autoscale service)
            cls = ScaleInHandler if autoscale_enabled() else None
        if path == "/api/subscriptions" or path.startswith(
            "/api/subscriptions/"
        ):
            # standing subscriptions (VRPMS_SUBS-gated per REQUEST so a
            # flip needs no restart; off -> plain 404, byte-identical to
            # the pre-subscription service): /api/subscriptions create/
            # list, /{id} poll+delete, /{id}/deltas the change feed,
            # /{id}/stream per-generation SSE
            if not subs_enabled():
                cls = None
            elif path == "/api/subscriptions":
                cls = SubscriptionsHandler
            elif path.endswith("/deltas"):
                cls = SubscriptionDeltasHandler
            elif path.endswith("/stream"):
                cls = SubscriptionStreamHandler
            else:
                cls = SubscriptionDetailHandler
        if cls is None:
            self.send_response(404)
            self.send_header("Content-type", "text/plain")
            self.end_headers()
            self.wfile.write(b"Not found")
            return
        if not hasattr(cls, f"do_{method}"):
            # e.g. POST to a GET-only route: answer 501 instead of
            # letting getattr AttributeError kill the connection with
            # no HTTP response at all
            self.send_response(501)
            self.end_headers()
            return
        self.__class__ = cls
        getattr(self, f"do_{method}")()

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        # /api/jobs/{id} (cooperative job cancellation) and
        # /api/subscriptions/{id} (retire a standing subscription,
        # cancelling its in-flight generation) accept DELETE; everything
        # else answers 501 via the method check in _dispatch
        self._dispatch("DELETE")

    def do_OPTIONS(self):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        cls = ROUTES.get(path)
        if cls is None or not hasattr(cls, "do_OPTIONS"):
            self.send_response(501)
            self.end_headers()
            return
        self.__class__ = cls
        self.do_OPTIONS()


def serve(port: int = 8080):
    server = ThreadingHTTPServer(("0.0.0.0", port), Router)
    # advertise the BOUND address (port=0 resolves here) so peers' SSE
    # relays can reach this replica's live registry via the heartbeat
    # registry (service.jobs federated reads)
    from service import jobs as jobs_mod

    host, bound_port = server.server_address[:2]
    jobs_mod.set_advertised_addr(str(host), int(bound_port))
    return server


def main():
    parser = argparse.ArgumentParser(description="vrpms_tpu service")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--fixtures", help="JSON fixture file for the memory store")
    parser.add_argument("--store", choices=["memory", "supabase"])
    parser.add_argument(
        "--warmup",
        default=config.get("VRPMS_WARMUP"),
        help="pre-trace solver programs before serving: 'tiers' (or "
        "'auto') warms the shape-tier ladder in the BACKGROUND while "
        "the port serves (core.tiers), or give explicit shapes "
        "'200x36,100x12x1024' (locations x vehicles [x population]; "
        "locations = durations-matrix size incl. depot) to warm "
        "synchronously; also via $VRPMS_WARMUP. See service.warmup.",
    )
    args = parser.parse_args()
    if args.store:
        os.environ["VRPMS_STORE"] = args.store
    if args.fixtures:
        os.environ["VRPMS_FIXTURES"] = args.fixtures
        os.environ.setdefault("VRPMS_STORE", "memory")
    # resolve the tier ladder ONCE at startup: a malformed VRPMS_TIERS
    # must be a clear boot error, not a per-request envelope (the same
    # fail-fast contract VRPMS_STORE resolution follows)
    from vrpms_tpu.core import tiers

    try:
        lad = tiers.ladder()
    except ValueError as e:
        raise SystemExit(f"invalid VRPMS_TIERS: {e}") from e
    # persistent XLA compile cache, ON by default: restarted services
    # skip the ~30s/shape TPU compiles (the north-star 10s budget
    # assumes this is on). A cache dir that cannot be created logs a
    # compile_cache.degraded event (vrpms_tpu.utils) and the service
    # runs on without it.
    from vrpms_tpu.utils import enable_compile_cache

    cache_dir = enable_compile_cache()
    obs.set_compile_cache(cache_dir)
    from service import jobs as jobs_mod

    if jobs_mod.dist_queue_enabled():
        # start the claim loop NOW, not at the first local submit: a
        # replica added purely for capacity may never receive direct
        # traffic, and it must still lease (and reclaim) the fleet's
        # shared-queue work from the moment it boots
        jobs_mod.get_replica()
    if args.warmup in ("tiers", "auto"):
        # tier-ladder warmup in the BACKGROUND: the port binds now and
        # the default-schedule tier programs precompile behind it, so
        # traffic landing after the warmup finishes never pays a
        # compile for any size inside a warmed tier (core.tiers)
        from service.warmup import start_background_warmup, warmup_tiers

        start_background_warmup(warmup_tiers)
    elif args.warmup:
        # explicit shape specs stay synchronous (the operator asked for
        # exactly these shapes to be hot before the port binds);
        # best-effort like the compile cache: a bad shape spec or a
        # transient backend error must not crash-loop the service before
        # the port ever binds
        try:
            from service.warmup import warmup

            warmup(args.warmup)
        except Exception as e:
            log_event(
                "warmup.skipped",
                error=f"{type(e).__name__}: {e}",
                spec=args.warmup,
            )
    server = serve(args.port)
    log_event(
        "service.start",
        port=args.port,
        store=config.raw("VRPMS_STORE") or "auto",
        compileCache=cache_dir or "off",
        tiers="off" if lad is None else f"n<= {lad.n[-1] if lad.n else 0}",
    )
    print(
        f"vrpms_tpu service on :{args.port} "
        f"(store={config.raw('VRPMS_STORE') or 'auto'}, "
        f"compile_cache={cache_dir or 'off'})"
    )
    # SIGTERM (the orchestrator's stop signal) must reach the drain
    # path — the default handler would kill the process with jobs still
    # queued and waiters parked. On the store-backed queue the shutdown
    # is a graceful drain: in-flight leases get VRPMS_DRAIN_GRACE_S to
    # finish, the rest checkpoint-and-nack to peers (service.jobs.
    # shutdown_scheduler)
    import signal

    def _sigterm(*_):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        # drain-on-shutdown: queued jobs fail cleanly (persisted records
        # + woken waiters) instead of being silently abandoned
        shutdown_scheduler()


if __name__ == "__main__":
    main()
