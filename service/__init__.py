"""HTTP service layer: the reference's 9-endpoint contract, solver-backed.

Routes (reference vercel.json deployment model, SURVEY.md §1 L1):
  GET/POST /api                 health banner
  GET/POST /api/vrp/{ga,sa,aco,bf}
  GET/POST /api/tsp/{ga,sa,aco,bf}

Envelope parity (reference api/helpers.py:16-29):
  400 {"success": false, "errors": [{"what", "reason"}, ...]}
  200 {"success": true, "message": {...result...}}

Where the reference's handlers end in `# TODO: Run algorithm`
(e.g. reference api/vrp/ga/index.py:48), these dispatch across the
api->solver boundary into vrpms_tpu's compiled search.

Importing the package loads `.env` (the reference's src/__init__.py:1-2
runs load_dotenv at import time so SUPABASE_URL/SUPABASE_KEY reach the
store, reference README.md:53-66); same bootstrap here, dependency-free.
"""

from vrpms_tpu.utils import load_dotenv

load_dotenv()
