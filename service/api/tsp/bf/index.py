"""TSP Brute Force endpoint (reference api/tsp/bf/index.py)."""

from service.handler_base import SolveHandler
from service.parameters import parse_common_tsp_parameters


class handler(SolveHandler):
    problem = "tsp"
    algorithm = "bf"
    banner = "Hi, this is the TSP Brute Force endpoint"
    parse_common = staticmethod(parse_common_tsp_parameters)
    parse_algo = None
