"""TSP Ant Colony Optimization endpoint (reference api/tsp/aco/index.py)."""

from service.handler_base import SolveHandler
from service.parameters import parse_common_tsp_parameters, parse_tsp_aco_parameters


class handler(SolveHandler):
    problem = "tsp"
    algorithm = "aco"
    banner = "Hi, this is the TSP Ant Colony Optimization endpoint"
    parse_common = staticmethod(parse_common_tsp_parameters)
    parse_algo = staticmethod(parse_tsp_aco_parameters)
