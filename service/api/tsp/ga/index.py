"""TSP Genetic Algorithm endpoint (reference api/tsp/ga/index.py)."""

from service.handler_base import SolveHandler
from service.parameters import parse_common_tsp_parameters, parse_tsp_ga_parameters


class handler(SolveHandler):
    problem = "tsp"
    algorithm = "ga"
    banner = "Hi, this is the TSP Genetic Algorithm endpoint"
    parse_common = staticmethod(parse_common_tsp_parameters)
    parse_algo = staticmethod(parse_tsp_ga_parameters)
