"""TSP Simulated Annealing endpoint (reference api/tsp/sa/index.py)."""

from service.handler_base import SolveHandler
from service.parameters import parse_common_tsp_parameters, parse_tsp_sa_parameters


class handler(SolveHandler):
    problem = "tsp"
    algorithm = "sa"
    banner = "Hi, this is the TSP Simulated Annealing endpoint"
    parse_common = staticmethod(parse_common_tsp_parameters)
    parse_algo = staticmethod(parse_tsp_sa_parameters)
