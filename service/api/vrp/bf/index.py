"""VRP Brute Force endpoint (reference api/vrp/bf/index.py)."""

from service.handler_base import SolveHandler
from service.parameters import parse_common_vrp_parameters


class handler(SolveHandler):
    problem = "vrp"
    algorithm = "bf"
    banner = "Hi, this is the VRP Brute Force endpoint"
    parse_common = staticmethod(parse_common_vrp_parameters)
    parse_algo = None
