"""VRP Genetic Algorithm endpoint (reference api/vrp/ga/index.py)."""

from service.handler_base import SolveHandler, CORSPreflightMixin
from service.parameters import parse_common_vrp_parameters, parse_vrp_ga_parameters


class handler(CORSPreflightMixin, SolveHandler):
    problem = "vrp"
    algorithm = "ga"
    banner = "Hi, this is the VRP Genetic Algorithm endpoint"
    parse_common = staticmethod(parse_common_vrp_parameters)
    parse_algo = staticmethod(parse_vrp_ga_parameters)
