"""VRP Ant Colony Optimization endpoint (reference api/vrp/aco/index.py)."""

from service.handler_base import SolveHandler
from service.parameters import parse_common_vrp_parameters, parse_vrp_aco_parameters


class handler(SolveHandler):
    problem = "vrp"
    algorithm = "aco"
    banner = "Hi, this is the VRP Ant Colony Optimization endpoint"
    parse_common = staticmethod(parse_common_vrp_parameters)
    parse_algo = staticmethod(parse_vrp_aco_parameters)
