"""VRP Simulated Annealing endpoint (reference api/vrp/sa/index.py)."""

from service.handler_base import SolveHandler
from service.parameters import parse_common_vrp_parameters, parse_vrp_sa_parameters


class handler(SolveHandler):
    problem = "vrp"
    algorithm = "sa"
    banner = "Hi, this is the VRP Simulated Annealing endpoint"
    parse_common = staticmethod(parse_common_vrp_parameters)
    parse_algo = staticmethod(parse_vrp_sa_parameters)
