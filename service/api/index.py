"""Health endpoint — liveness banner (reference api/index.py:7-12)."""

from http.server import BaseHTTPRequestHandler

from service.obs import RequestObsMixin


class handler(RequestObsMixin, BaseHTTPRequestHandler):

    def do_GET(self):
        self.send_response(200)
        self.send_header("Content-type", "text/plain")
        self.end_headers()
        self.wfile.write("Hello!".encode("utf-8"))
