"""Async jobs API + the service side of the solve scheduler.

This module wires the generic scheduler (vrpms_tpu.sched: bounded
queue, shape-bucketed micro-batcher, device-owning workers) into the
service:

  * the RUNNER — executes batches on the worker thread: solo jobs run
    the exact run_vrp/run_tsp pipeline tail (service.solve.
    solve_prepared); same-bucket SA jobs merge into ONE vmapped launch
    (vrpms_tpu.sched.batch.solve_sa_batch) and split back per request;
  * the HTTP surface — POST /api/jobs returns a jobId immediately
    (202), GET /api/jobs/{id} polls queued|running|done|failed with the
    standard envelope; queue-full answers 429 + Retry-After;
  * submit-and-wait — the existing synchronous endpoints keep their
    contract by parking on the job event instead of solving inline
    (service.handler_base), so the accelerator is only ever driven by
    the scheduler's workers;
  * persistence + observability — async job records go through the
    store.Database seam (memory and Supabase both work), and every
    transition feeds the sched instruments (service.obs) and a
    request-correlated structured log line.

Config (env): VRPMS_SCHED=off disables the scheduler (solves run inline
on HTTP threads — the PR-1 behavior, kept for benchmarks baselines),
VRPMS_SCHED_QUEUE (admission bound, default 64), VRPMS_SCHED_WINDOW_MS
(micro-batch gather window, default 10), VRPMS_SCHED_MAX_BATCH (default
16).
"""

from __future__ import annotations

import io
import json
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler

import store
from service import obs
from vrpms_tpu import config
from service import cache as solution_cache
from service import checkpoint as ckpt_mod
from service.helpers import (
    fail,
    read_json_body,
    respond_json,
    too_busy,
)
from service.parameters import (
    parse_common_tsp_parameters,
    parse_common_vrp_parameters,
    parse_solver_options,
    parse_tsp_aco_parameters,
    parse_tsp_ga_parameters,
    parse_tsp_sa_parameters,
    parse_vrp_aco_parameters,
    parse_vrp_ga_parameters,
    parse_vrp_sa_parameters,
)
from service.solve import (
    Prepared,
    _mark_degraded,
    finish_tsp,
    finish_vrp,
    flight_partial,
    prepare_request,
    run_tsp,
    run_vrp,
    solve_prepared,
)
from vrpms_tpu.obs import (
    current_request_id,
    log_event,
    progress,
    reset_request_id,
    set_request_id,
    spans,
)
from vrpms_tpu.obs import analytics
from vrpms_tpu.obs import export as trace_export
from vrpms_tpu.obs import slo
from vrpms_tpu.sched import (
    DONE,
    FAILED,
    QUEUED,
    RUNNING,
    Job,
    QueueFull,
    Scheduler,
    qos as qos_mod,
)

_PARSERS = {
    ("vrp", "ga"): (parse_common_vrp_parameters, parse_vrp_ga_parameters),
    ("vrp", "sa"): (parse_common_vrp_parameters, parse_vrp_sa_parameters),
    ("vrp", "aco"): (parse_common_vrp_parameters, parse_vrp_aco_parameters),
    ("vrp", "bf"): (parse_common_vrp_parameters, parse_vrp_sa_parameters),
    ("tsp", "ga"): (parse_common_tsp_parameters, parse_tsp_ga_parameters),
    ("tsp", "sa"): (parse_common_tsp_parameters, parse_tsp_sa_parameters),
    ("tsp", "aco"): (parse_common_tsp_parameters, parse_tsp_aco_parameters),
    ("tsp", "bf"): (parse_common_tsp_parameters, parse_tsp_sa_parameters),
}


def scheduler_enabled() -> bool:
    return config.enabled("VRPMS_SCHED")


# ---------------------------------------------------------------------------
# QoS: priority classes, EDF deadlines, selective shed, tenant quotas
# ---------------------------------------------------------------------------
# The policy mechanics live in vrpms_tpu.sched.qos; this block is the
# service-side wiring — stamping parsed requests onto Jobs/queue
# entries, the shared policy singleton (per-class drain EWMAs price
# every 429's Retry-After), the shed telemetry, and the in-process half
# of tenant accounting (the store-backed half lives with the
# distributed submit below). VRPMS_QOS=off short-circuits all of it:
# no policy object is built, no request field is read, and every queue
# stays the pre-QoS FIFO.


def qos_enabled() -> bool:
    return qos_mod.enabled()  # the ONE switch spelling (sched.qos)


class QuotaExceeded(QueueFull):
    """Per-tenant fairness shed: the tenant already holds its quota of
    active jobs across the fleet (429; subclassing QueueFull keeps the
    sync endpoints' existing backpressure catch working unchanged)."""

    reason = (
        "per-tenant concurrency quota reached; retry after the "
        "Retry-After interval"
    )


def job_qos_class(opts) -> str:
    """The request's (already-validated) priority class; standard when
    QoS is off or the value is junk (junk was 400'd at parse — this is
    only the belt for internal callers)."""
    if not qos_enabled():
        return qos_mod.DEFAULT_CLASS
    try:
        return qos_mod.parse_class(opts.get("qos"))
    except ValueError:
        return qos_mod.DEFAULT_CLASS


def _apply_qos(job: Job, opts: dict, params: dict) -> None:
    """Stamp a Job with its QoS fields from the parsed request: class,
    absolute EDF deadline (submit + timeLimit budget), auth-scoped
    tenant. No-op with QoS off — the Job defaults are the FIFO-neutral
    values, so nothing downstream can tell QoS exists."""
    if not qos_enabled():
        return
    job.qos = job_qos_class(opts)
    job.deadline_at = qos_mod.deadline_at(job.submitted_at, job.time_limit)
    job.tenant = qos_mod.tenant_id(params.get("auth"))


_qos_policy_lock = threading.Lock()
_qos_policy = None  # guarded-by: _qos_policy_lock


def get_qos_policy():
    """The process QoS policy singleton: attached to every local
    JobQueue (priority pop / selective shed / free-rider gather) and
    consulted by the admission paths for per-class Retry-After."""
    global _qos_policy
    with _qos_policy_lock:
        if _qos_policy is None:
            _qos_policy = qos_mod.QosPolicy()
        return _qos_policy


def note_shed(reason: str, qos_class: str) -> None:
    """One shed, counted and traced: the vrpms_jobs_shed_total counter
    plus a zero-width qos.shed span on the request's trace (when one is
    active) so a 429 is visible in the waterfall, not just the
    counter."""
    obs.SHED_TOTAL.labels(reason=reason, qos=qos_class).inc()
    if spans.current_trace() is not None:
        with spans.span("qos.shed", reason=reason, qos=qos_class):
            pass


def _quota_retry_after(qos_class: str) -> float:
    """Retry hint for a quota shed: roughly one of this class's own
    jobs draining (the soonest the tenant could free a slot)."""
    return min(max(1.0, get_qos_policy().class_seconds(qos_class)), 60.0)


# in-process tenant accounting (the local-queue half of fairness; the
# store-backed queue counts active entries fleet-wide instead)
_tenant_lock = threading.Lock()
_tenant_active: dict[str, int] = {}  # guarded-by: _tenant_lock


def _tenant_admit(job: Job) -> bool:
    """Atomically claim a quota slot for the job's tenant; False means
    the quota is spent and the submit must shed. Anonymous jobs (and
    QoS off / quota 0) always admit."""
    quota = qos_mod.tenant_quota() if qos_enabled() else 0
    if quota <= 0 or job.tenant is None:
        return True
    with _tenant_lock:
        if _tenant_active.get(job.tenant, 0) >= quota:
            return False
        _tenant_active[job.tenant] = _tenant_active.get(job.tenant, 0) + 1
        job._tenant_counted = True
    return True


def _tenant_release(job: Job) -> None:
    """Return the job's quota slot (idempotent: terminal-event and
    submit-failure paths may both call it)."""
    with _tenant_lock:
        if not getattr(job, "_tenant_counted", False):
            return
        job._tenant_counted = False
        tenant = job.tenant
        n = _tenant_active.get(tenant, 0) - 1
        if n > 0:
            _tenant_active[tenant] = n
        else:
            _tenant_active.pop(tenant, None)


def _tenant_map() -> dict:
    with _tenant_lock:
        return dict(_tenant_active)


# ---------------------------------------------------------------------------
# Bucketing: which jobs may merge into one batched launch
# ---------------------------------------------------------------------------

# options that change the solver program/flow beyond what the stacked
# launch models — any of them truthy forces the solo path
_UNBATCHABLE_OPTS = (
    "islands", "ils_rounds", "warm_start", "profile", "include_stats",
    "local_search", "local_search_pool", "makespan_weight",
)


def _bucket_key(prep: Prepared):
    """Shape-bucket key: equal keys guarantee everything one stacked
    vmapped SA launch requires — identical padded array shapes,
    identical Instance metadata, identical schedule (chains/iters) and
    identical nominal deadline. None = never merge (solo path)."""
    if prep is None or prep.trivial is not None or prep.inst is None:
        return None
    if prep.algorithm != "sa":
        return None
    o = prep.opts
    if any(o.get(k) for k in _UNBATCHABLE_OPTS):
        return None
    try:
        chains = int(o.get("population_size") or 128)
        iters = int(o.get("iteration_count") or 5000)
        time_limit = (
            None if o.get("time_limit") is None else float(o["time_limit"])
        )
    except (TypeError, ValueError):
        return None  # junk values: the solo path owns the error envelope
    inst = prep.inst
    # With shape tiering (core.tiers) the instance arrives PADDED, so
    # `durations.shape`/`n_vehicles` here are already the TIER's — jobs
    # for N=13 and N=15 customers land in one tier-16 bucket and merge
    # into one vmapped launch, while feature flags (TW, het fleet, TD
    # rank, slice width) still split buckets. The padded marker keeps a
    # padded and an unpadded instance of coincidentally equal shape from
    # stacking (their pytree structures differ).
    return (
        prep.problem,
        "sa",
        tuple(inst.durations.shape),
        int(inst.n_vehicles),
        inst.n_real is not None,
        bool(inst.has_tw),
        bool(inst.het_fleet),
        int(inst.td_rank),
        float(inst.slice_minutes),
        chains,
        iters,
        time_limit,
    )


def _backend_label(opts) -> str:
    b = opts.get("backend")
    if b not in ("cpu", "tpu"):
        return "default"
    try:
        import jax

        if b == jax.default_backend():
            # an explicit backend equal to the process default must not
            # mint a SECOND device-owning worker for the same physical
            # device (that would reintroduce contention and split
            # batchable same-shape traffic across two queues)
            return "default"
    except Exception:
        pass
    return b


def _job_time_limit(opts):
    try:
        val = opts.get("time_limit")
        return None if val is None else float(val)
    except (TypeError, ValueError):
        return None  # junk -> solver-side validation owns the envelope


# ---------------------------------------------------------------------------
# Live-job registry + progress sinks
# ---------------------------------------------------------------------------
# GET /api/jobs/{id} during a solve must read the LIVE incumbent (the
# store record only updates at lifecycle transitions — persisting every
# block would put a store write on the device loop), and DELETE /
# /stream need the in-flight Job object. This registry is the
# in-process index: jobs enter at async submit and leave at their
# terminal transition. It is per-replica by design — the persisted
# record (with the final incumbent + convergence profile) is the
# cross-replica view.

_live_lock = threading.Lock()
_live_jobs: dict[str, Job] = {}


def _register_live(job: Job) -> None:
    with _live_lock:
        _live_jobs[job.id] = job


def _drop_live(job_id: str) -> None:
    with _live_lock:
        _live_jobs.pop(job_id, None)


def get_live_job(job_id: str) -> Job | None:
    """The in-flight Job for this id, if this process owns it."""
    with _live_lock:
        return _live_jobs.get(job_id)


def _running_count() -> int:
    with _live_lock:
        return sum(1 for j in _live_jobs.values() if j.status == RUNNING)


def _attach_sink(job: Job, prep: Prepared) -> None:
    """Give an async job its live-progress mailbox (VRPMS_PROGRESS=on,
    the default). The quick lower bound is computed HERE, on the submit
    thread — milliseconds of host numpy, never on the device loop — so
    every snapshot can carry a gap. With progress off the job carries
    no sink and the solve path is byte-identical to the pre-progress
    contract."""
    if not progress.enabled() or prep is None:
        return
    if prep.inst is None:
        # decomposed giant requests carry no monolithic Instance; the
        # plan's shard-sum bound (per-shard MST, summed at plan build —
        # ms-scale where the monolithic quick bound is quadratic in n)
        # is the gap reference the rollup stream reports against
        if prep.decomp is None:
            return
        job.sink = progress.ProgressSink(
            job_id=job.id,
            problem=prep.problem,
            algorithm=prep.algorithm,
            lower_bound=prep.decomp.lower_bound,
        )
        return
    from vrpms_tpu.io.bounds import quick_lower_bound

    job.sink = progress.ProgressSink(
        job_id=job.id,
        problem=prep.problem,
        algorithm=prep.algorithm,
        lower_bound=quick_lower_bound(prep.inst),
    )


# ---------------------------------------------------------------------------
# The runner (worker-thread side)
# ---------------------------------------------------------------------------


def _remaining_budget(job: Job):
    """The job's deadline minus its queue wait (the worker already
    expired jobs whose wait spent the whole budget; explicit 0 keeps its
    stop-ASAP meaning)."""
    tl = job.time_limit
    if not tl or tl <= 0:
        return None if tl is None else tl
    return max(0.0, tl - (job.queue_wait_s or 0.0))


def _record_queue_wait(job: Job) -> None:
    """Retroactive queue.wait span — the worker can only measure the
    wait once the job pops. Recorded at most once per admission (the
    batch-fallback solo retry must not duplicate it; a watchdog requeue
    resets submitted_mono, so the SECOND wait records again — span
    continuity across the crash, attempt marked requeued)."""
    if job.trace is None or job.queue_wait_s is None:
        return
    if getattr(job, "_qw_span_mark", None) == job.submitted_mono:
        return
    job._qw_span_mark = job.submitted_mono
    job.trace.span_at(
        "queue.wait",
        parent_id=job.span.span_id if job.span is not None else None,
        start_mono=job.submitted_mono,
        duration_s=job.queue_wait_s,
        jobId=job.id,
        requeued=job.requeued or None,
    )


def _activate_job_context(job: Job):
    """Re-activate a job's carried trace context on the worker thread
    (the explicit cross-thread hop), recording the queue wait the
    worker just measured as a retroactive span. Returns deactivation
    tokens (None when the job carries no trace)."""
    if job.trace is None:
        return None
    tokens = spans.activate(job.trace, job.span)
    _record_queue_wait(job)
    return tokens


def _solve_span_attrs(job: Job) -> dict:
    return {
        "jobId": job.id,
        "batchSize": job.batch_size or 1,
        "bucket": None if job.bucket is None else str(job.bucket),
        # a requeued job's second attempt parents under the SAME trace:
        # the waterfall shows both attempts, attempt 2 annotated
        "attempt": 2 if job.requeued else 1,
    }


def _inject_span_stats(job: Job) -> None:
    """includeStats responses gain the request waterfall (stats.spans).
    Injected at solve completion on the worker; the sync handler
    rebuilds it at respond time to include post-solve store spans."""
    if job.trace is None or not isinstance(job.result, dict):
        return
    stats = job.result.get("stats")
    if isinstance(stats, dict):
        stats["spans"] = job.trace.waterfall()
        stats["traceId"] = job.trace.trace_id


def _run_solo(job: Job) -> None:
    prep: Prepared = job.payload["prep"]
    if job.requeued and not (job.payload or {}).get("dist"):
        # watchdog-requeue resume: the Job (and its Prepared) survived
        # the worker crash in-process — seed it from the durable
        # checkpoint so attempt=2 continues instead of restarting
        # (distributed reclaims resumed at materialize already)
        ckpt_mod.apply_local_resume(job)
    if job.time_limit and job.time_limit > 0:
        prep.opts = dict(prep.opts, time_limit=_remaining_budget(job))
        ckpt_elapsed = (job.payload or {}).get("ckpt_elapsed_s")
        if ckpt_elapsed:
            # a RESUMED attempt runs on the REMAINING budget: the
            # requeue forgave the crashed run's clock, the checkpoint
            # remembers how much of it was spent
            current = prep.opts.get("time_limit")
            remaining = max(0.0, float(job.time_limit) - float(ckpt_elapsed))
            prep.opts = dict(
                prep.opts,
                time_limit=(
                    remaining
                    if current is None
                    else min(float(current), remaining)
                ),
            )
    errors: list = []
    token = set_request_id(job.request_id)
    span_tokens = _activate_job_context(job)
    try:
        # the sink rides the contextvar through the solve so the
        # deadline drivers publish each block's incumbent to it (and
        # honor a pending cancel between blocks)
        with progress.attach(job.sink):
            with spans.span("solve", **_solve_span_attrs(job)):
                job.result = solve_prepared(prep, errors)
        _mark_cancelled(job)
        _inject_span_stats(job)
    except Exception as e:  # solve_prepared's own envelope paths missed
        log_event(
            "solve.exception",
            algorithm=prep.algorithm,
            error=f"{type(e).__name__}: {e}",
            traceback=traceback.format_exc(),
        )
        errors += [
            {"what": "Data error", "reason": f"{type(e).__name__}: {e}"}
        ]
    finally:
        if span_tokens is not None:
            spans.deactivate(span_tokens)
        reset_request_id(token)
    if job.result is None:
        job.errors = errors or [
            {"what": "Solver error", "reason": "solve returned no result"}
        ]


def _mark_cancelled(job: Job) -> None:
    """A cooperatively-cancelled solve still returns its incumbent —
    the contract marks it so the client knows the budget was cut short
    by its own DELETE, not exhausted. Gated on the driver having
    ACKNOWLEDGED the cancel at a boundary: a deadline-free single-block
    solve has no boundary left once launched, runs its full budget, and
    must not claim it was cut short."""
    if (
        job.sink is not None
        and job.sink.cancel_acknowledged
        and isinstance(job.result, dict)
    ):
        job.result["cancelled"] = True


def _run_batched(jobs: list[Job]) -> None:
    """One vmapped SA launch for same-bucket jobs, split back per job."""
    from vrpms_tpu.sched.batch import solve_sa_batch
    from vrpms_tpu.solvers import SAParams

    preps = [j.payload["prep"] for j in jobs]
    o = preps[0].opts
    params = SAParams(
        n_chains=int(o.get("population_size") or 128),
        n_iters=int(o.get("iteration_count") or 5000),
    )
    seeds = [int(p.opts.get("seed") or 0) for p in preps]
    deadline = None
    if o.get("time_limit") is not None:
        # every job shares the nominal limit (bucket key); the batch runs
        # under the MINIMUM remaining budget so no merged job overshoots
        deadline = min(_remaining_budget(j) for j in jobs)
    # each batched job gets its OWN solve span in its OWN trace (the
    # launch is shared; the latency story is per request): opened before
    # the launch, annotated with batch size + bucket, closed after its
    # decode — so batch-neighbor interference is visible as K solve
    # spans of near-identical duration across K traces
    solve_spans = []
    for job in jobs:
        if job.trace is None:
            solve_spans.append(None)
            continue
        _record_queue_wait(job)
        s = job.trace.span(
            "solve",
            parent_id=job.span.span_id if job.span is not None else None,
        )
        s.set(**_solve_span_attrs(job))
        solve_spans.append(s)
    t0 = time.perf_counter()
    try:
        # per-job sinks behind ONE contextvar slot: the fanout splits
        # each synced [K, B] best row to its job's sink, and reports
        # cancelled only when every member job cancelled (one job's
        # DELETE must not cut its batch-mates' budget). No member with
        # a sink (VRPMS_PROGRESS=off) -> attach nothing, keeping the
        # off switch's no-extra-host-work contract on the fast path
        sinks = [j.sink for j in jobs]
        # one flight timer for the shared launch (ISSUE 20): device/host
        # split and batch fill are launch-wide facts, attributed to
        # every member's record below
        ftimer = analytics.FlightTimer() if analytics.enabled() else None
        with progress.attach(
            progress.ProgressFanout(sinks)
            if any(s is not None for s in sinks)
            else None
        ), analytics.flight(ftimer):
            results = solve_sa_batch(
                [p.inst for p in preps], seeds, params=params,
                deadline_s=deadline,
            )
    except BaseException:
        # the batch-fallback path (_runner) will re-run each job solo
        # with a fresh solve span; this attempt's spans must terminate
        # as errors, not dangle open and inflate the trace duration
        for s in solve_spans:
            if s is not None:
                s.end(status="error")
        raise
    wall = time.perf_counter() - t0
    obs.SOLVE_SECONDS.labels(
        problem=preps[0].problem, algorithm="sa"
    ).observe(wall, trace_id=jobs[0].trace.trace_id if jobs[0].trace else None)
    for job, prep, res, solve_span in zip(jobs, preps, results, solve_spans):
        errors: list = []
        token = set_request_id(job.request_id)
        span_tokens = (
            spans.activate(job.trace, solve_span)
            if job.trace is not None
            else None
        )
        try:
            obs.SOLVE_EVALS.observe(float(res.evals))
            extras: dict = {}
            if ftimer is not None:
                extras["flight"] = flight_partial(
                    ftimer, wall, int(res.evals)
                )
            # the job's own sink rides the contextvar through finish so
            # the flight record sees its jobId, lower bound, and profile
            with progress.attach(job.sink):
                if prep.problem == "vrp":
                    job.result = finish_vrp(prep, res, None, extras, errors)
                else:
                    job.result = finish_tsp(prep, res, None, extras, errors)
            _mark_cancelled(job)
        except Exception as e:
            log_event(
                "solve.exception",
                algorithm=prep.algorithm,
                error=f"{type(e).__name__}: {e}",
                traceback=traceback.format_exc(),
            )
            errors += [
                {"what": "Data error", "reason": f"{type(e).__name__}: {e}"}
            ]
        finally:
            if span_tokens is not None:
                spans.deactivate(span_tokens)
            if solve_span is not None:
                solve_span.end(
                    status="error" if job.result is None else None
                )
            reset_request_id(token)
        if job.result is None:
            job.errors = errors


def _runner(jobs: list[Job]) -> None:
    """Scheduler worker entry: batches of >1 are same-bucket by
    construction (sched.batcher) and ride the vmapped launch; anything
    else runs the exact single-request pipeline. A batched-path failure
    falls back to solo solves so a vmap edge case degrades to PR-1
    behavior instead of failing K requests."""
    solo = list(jobs)
    if len(jobs) > 1:
        # the batch runs under the MINIMUM remaining budget: a job
        # whose queue wait already ate most of its own timeLimit must
        # not drag fresh batch-mates down to its sliver of budget —
        # below half the nominal limit it solves alone (bounded loss:
        # a merged job is cut by at most half its budget)
        batch = [
            j for j in jobs
            if (
                not (j.time_limit and j.time_limit > 0)
                or _remaining_budget(j) >= 0.5 * j.time_limit
            )
            # a requeued job may hold a checkpoint to resume from; the
            # batched launch has no per-job init, so it solves solo
            # (its seed, continuation schedule, and remaining budget
            # all apply there) — without checkpointing the requeue
            # keeps its batched path exactly as before
            and not (j.requeued and ckpt_mod.enabled())
        ]
        if len(batch) > 1:
            t0 = time.monotonic()
            try:
                _run_batched(batch)
                batched = {id(j) for j in batch}
                solo = [j for j in jobs if id(j) not in batched]
            except Exception as e:
                log_event(
                    "sched.batch_fallback",
                    error=f"{type(e).__name__}: {e}",
                    traceback=traceback.format_exc(),
                    batchSize=len(batch),
                )
                # the failed attempt consumed real wall clock: charge it
                # to each job's wait so the solo retry's remaining budget
                # (and the deadline contract) stays honest
                burned = time.monotonic() - t0
                for job in batch:
                    job.result, job.errors = None, []
                    if job.queue_wait_s is not None:
                        job.queue_wait_s += burned
    for job in solo:
        _run_solo(job)


# ---------------------------------------------------------------------------
# Job records (persisted through the store seam)
# ---------------------------------------------------------------------------

def _job_record(job: Job) -> dict:
    rec = {
        "id": job.id,
        "status": job.status,
        "problem": job.payload.get("problem"),
        "algorithm": job.payload.get("algorithm"),
        "submittedAt": job.submitted_at,
        "startedAt": job.started_at,
        "finishedAt": job.finished_at,
        "queueWaitMs": (
            None
            if job.queue_wait_s is None
            else round(job.queue_wait_s * 1e3, 2)
        ),
        "batchSize": job.batch_size or None,
        "requestId": job.request_id,
        "traceId": job.trace.trace_id if job.trace is not None else None,
    }
    resolved_from = (job.payload or {}).get("resolved_from")
    if resolved_from:
        # cancel-and-resolve lineage: this job continued that one's
        # incumbent (POST /api/jobs/{id}/resolve)
        rec["resolvedFrom"] = resolved_from
    attempt = (job.payload or {}).get("dist_attempt")
    if attempt:
        # distributed-queue lineage: which claim generation produced
        # this record (2 = a peer reclaimed a crashed replica's lease)
        rec["attempt"] = attempt
    if job.sink is not None:
        snap = job.sink.snapshot()
        if snap is not None:
            # latest incumbent: cost monotone non-increasing across
            # polls by sink construction
            rec["incumbent"] = snap
        if job.status in (DONE, FAILED):
            # terminal: the convergence profile (every improving
            # snapshot, bounded) persists with the record so the
            # post-hoc view survives this process
            prof = job.sink.profile()
            if prof is not None:
                rec["progress"] = prof
    if job.status == DONE:
        rec["message"] = job.result
    if job.status == FAILED:
        rec["errors"] = job.errors
    return rec


def _persist(job: Job) -> None:
    """Write the job's current record (one blind upsert, no read guard:
    the submit thread persists 'queued' BEFORE pushing the job, and
    every later transition is written by the one worker thread in
    order, so writes for a given job are strictly sequenced — a
    read-then-write here would only add a store round trip per
    transition to the device-owning loop)."""
    db = job.payload.get("job_db")
    if db is None:
        return
    if job.trace is None:
        db.save_job(job.id, _job_record(job))
        return
    # explicit span on the job's own trace: terminal persists run on
    # the worker/watchdog thread where no trace context is active
    s = job.trace.span(
        "store.persist_job",
        parent_id=job.span.span_id if job.span is not None else None,
    )
    s.set(status=job.status)
    try:
        db.save_job(job.id, _job_record(job))
    finally:
        s.end()


#: job transitions mirrored as events on the job's root span — the
#: waterfall tells the lifecycle story without cross-referencing logs
_SPAN_EVENTS = (
    "queued", "started", "expired", "requeued", "crashed", "drained",
    "runner_error",
)


def _on_event(name: str, job: Job) -> None:
    """Scheduler observer: metrics + structured log + store record +
    trace lifecycle (events on the root span; DEFERRED traces — async
    jobs whose 202 long left — finish here at the terminal transition,
    entering the debug ring / slow-capture)."""
    if job.trace is not None and name in _SPAN_EVENTS and job.span is not None:
        job.span.event(f"job.{name}", jobId=job.id)
    if name == "started":
        if job.queue_wait_s is not None:
            obs.SCHED_QUEUE_WAIT.observe(
                job.queue_wait_s,
                trace_id=job.trace.trace_id if job.trace else None,
            )
            # the per-class view: with QoS off every job is standard,
            # so the series stays one-dimensional
            obs.QOS_QUEUE_WAIT.labels(qos=job.qos).observe(
                job.queue_wait_s
            )
        obs.SCHED_BATCH_SIZE.observe(job.batch_size or 1)
    elif name == "expired":
        obs.SCHED_REJECTS.labels(reason="deadline_spent").inc()
        note_shed("deadline_exhausted", job.qos)
        obs.JOBS_TOTAL.labels(outcome="failed").inc()
    elif name == "drained":
        obs.SCHED_REJECTS.labels(reason="shutdown").inc()
        obs.JOBS_TOTAL.labels(outcome="failed").inc()
    elif name == "runner_error":
        # the worker already built the error envelope; without a metric
        # and a correlated event a scheduler/runner bug is invisible
        obs.JOBS_FAILED.labels(reason="runner").inc()
    elif name == "requeued":
        obs.SCHED_REQUEUES.inc()
    elif name == "crashed":
        obs.JOBS_FAILED.labels(reason="crash").inc()
        obs.JOBS_TOTAL.labels(outcome="failed").inc()
    elif name in ("done", "failed"):
        obs.JOBS_TOTAL.labels(outcome=name).inc()
    log_event(
        f"job.{name}",
        jobId=job.id,
        requestId=job.request_id,
        status=job.status,
        batchSize=job.batch_size or None,
        queueWaitMs=(
            None
            if job.queue_wait_s is None
            else round(job.queue_wait_s * 1e3, 2)
        ),
        errors=(
            job.errors or None
            if name in ("failed", "expired", "crashed", "runner_error")
            else None
        ),
    )
    terminal = name in ("done", "failed", "expired", "crashed", "drained")
    if terminal and name != "drained" and analytics.enabled():
        # SLO accounting (ISSUE 20): one deadline-met outcome per
        # terminal job. A job with no deadline cannot miss; any failure
        # path is a miss; a drained job resumes on a peer, so it
        # carries no verdict here.
        deadline = getattr(job, "deadline_at", None)
        met = name == "done" and (
            deadline is None
            or (job.finished_at or time.time()) <= float(deadline)
        )
        slo.note(getattr(job, "qos", None) or "standard", met)
    if terminal:
        # fairness bookkeeping: the tenant's quota slot frees the
        # moment the job is terminal, whatever path got it there
        _tenant_release(job)
        if not (job.payload or {}).get("dist"):
            # stale-checkpoint hygiene: a terminal local job's rows are
            # dead state (distributed jobs clean up in _dist_complete,
            # gated on the ack — an un-acked completion's rows belong
            # to the reclaiming peer)
            ckpt_mod.checkpointer().finished(job.id)
    if terminal and job.trace is not None and job.trace.deferred:
        # finish BEFORE the terminal persist: once a poll can read the
        # job as done, GET /api/debug/traces/{traceId} must find the
        # trace in the ring
        if (job.payload or {}).get("dist") and job.span is not None:
            # distributed jobs own their root span (no HTTP handler
            # closes it on this replica): end it so the waterfall's
            # duration is the execution, not open-ended
            job.span.end(status=None if name == "done" else "error")
        job.trace.finish(status="ok" if name == "done" else "error")
    if name not in ("queued", "runner_error", "requeued"):
        # queued is persisted synchronously at submit; runner_error is
        # always followed by the terminal `failed` persist; requeued is
        # NOT persisted — it would race the abandoned worker's own
        # in-order writes for the same job (two threads blind-upserting
        # could leave a finished job recorded 'queued' forever), and
        # the record's stale 'running' is true enough: the retry is
        # about to run it again
        _persist(job)
    if terminal and not (job.payload or {}).get("dist"):
        # wake every stream waiter AFTER the terminal persist: a
        # reader woken by the close may poll GET /api/jobs/{id}
        # immediately and must find the terminal record, not the stale
        # 'running' one; then drop the live-registry entry. For
        # DISTRIBUTED jobs the terminal persist is ack-gated and
        # happens in _dist_complete — close/drop there, after it, for
        # exactly the same reason.
        if job.sink is not None:
            job.sink.close("done" if name == "done" else "failed")
        _drop_live(job.id)


def _on_worker_event(name: str, backend: str, reason: str) -> None:
    """Watchdog observer: a restart is an operator-grade incident."""
    if name == "restart":
        obs.WORKER_RESTARTS.labels(backend=backend, reason=reason).inc()
    log_event(f"sched.worker_{name}", backend=backend, reason=reason)


# ---------------------------------------------------------------------------
# Scheduler singleton
# ---------------------------------------------------------------------------

_scheduler: Scheduler | None = None
_sched_lock = threading.Lock()
# True between a drain (shutdown_scheduler) and the lazy rebuild of a
# fresh scheduler — the readiness probe's only window to observe "the
# scheduler was shut down" (the global is None by then)
_drained = False


def _queue_depths() -> dict:
    s = _scheduler
    return s.queues() if s is not None else {}


def get_scheduler() -> Scheduler:
    global _scheduler, _drained
    with _sched_lock:
        if _scheduler is None:
            _drained = False
            _scheduler = Scheduler(
                _runner,
                queue_limit=config.get("VRPMS_SCHED_QUEUE"),
                window_s=config.get("VRPMS_SCHED_WINDOW_MS") / 1e3,
                max_batch=config.get("VRPMS_SCHED_MAX_BATCH"),
                on_event=_on_event,
                watchdog_s=config.get("VRPMS_SCHED_WATCHDOG_MS") / 1e3,
                wedge_grace_s=config.get("VRPMS_SCHED_WEDGE_GRACE_S"),
                on_worker_event=_on_worker_event,
                # QoS: priority pop + selective shed + free-rider
                # gather on every backend queue; off = plain FIFO
                queue_policy=get_qos_policy() if qos_enabled() else None,
            )
            obs.set_queue_depth_provider(_queue_depths)
        return _scheduler


def shutdown_scheduler() -> int:
    """Drain-on-shutdown: fail queued jobs cleanly, stop workers, and
    forget the singleton (a later submit builds a fresh scheduler —
    what tests and long-lived embedding processes need). Stops the
    distributed-queue replica FIRST (drain: in-flight leased jobs get a
    window to finish and ack; anything still running re-queues to peers
    via lease expiry — never silent loss)."""
    global _scheduler, _drained, _replica
    try:
        # park the subscription manager FIRST: its debounce/cadence
        # timers must not fire a generation into a scheduler that is
        # mid-teardown (pending state is already durable in the store)
        from service import subscriptions as subs_mod

        subs_mod.reset()
    except Exception:
        pass
    try:
        # forget the elastic-fleet controller too: a rebuilt service
        # must not inherit a cooldown clock or a phantom previous ring
        from service import autoscale as autoscale_mod

        autoscale_mod.reset()
    except Exception:
        pass
    with _replica_lock:
        r, _replica = _replica, None
    if r is not None:
        if ckpt_mod.enabled() and not r.draining:
            # SIGTERM = graceful drain: in-flight leases get the grace
            # window, the rest checkpoint-and-nack to peers (no burned
            # attempt, no lease-expiry wait)
            r.drain(
                config.get("VRPMS_DRAIN_GRACE_S"), requeue=_drain_requeue
            )
        r.stop(drain_s=config.get("VRPMS_REPLICA_DRAIN_S"))
    _reset_drain()  # a rebuilt service starts undrained
    global _replica_id_cached
    _replica_id_cached = None  # a rebuilt service re-reads the env
    with _depth_lock:
        _memos.clear()  # a rebuilt service re-reads its own queue
    with _read_lock:
        _read_cache.clear()  # and serves no stale job reads
    global _advertised_addr
    _advertised_addr = None  # a rebuilt server re-registers its bind
    global _qos_policy
    with _qos_policy_lock:
        _qos_policy = None  # fresh per-class drain EWMAs on rebuild
    with _tenant_lock:
        _tenant_active.clear()
    # stop the analytics flusher and forget SLO windows: a rebuilt
    # service re-reads the knobs and starts with clean burn rates
    analytics.reset_analytics()
    slo.reset_tracker()
    with _sched_lock:
        s, _scheduler = _scheduler, None
        if s is not None:
            _drained = True
    if s is None:
        return 0
    drained = s.shutdown()
    if drained:
        log_event("sched.drained", jobs=drained)
    return drained


# ---------------------------------------------------------------------------
# Distributed job queue (horizontal scale-out)
# ---------------------------------------------------------------------------
# VRPMS_QUEUE=store swaps the async jobs surface from the process-local
# admission queue to the store-backed SHARED queue (store.base.
# JobQueueStore): submits enqueue the raw request; every replica runs a
# claim loop (vrpms_tpu.sched.Replica) that leases jobs — preferring
# the consistent-hash arc of tier keys it owns, so the tier compile
# cache and take_matching micro-batching keep their hit rates — and
# executes them on its own local scheduler under a heartbeat-renewed
# lease. Terminal records are ACK-GATED: only the replica that still
# holds the lease publishes, so a crashed replica's jobs are reclaimed
# and completed by peers exactly once. The default (VRPMS_QUEUE=local)
# path is untouched. Sync endpoints keep the local scheduler either
# way: their submit-and-wait contract parks on the in-process job
# event, and a same-box solve needs no routing.


def dist_queue_enabled() -> bool:
    return config.get("VRPMS_QUEUE").strip().lower() in (
        "store", "shared", "dist",
    )


_replica = None
_replica_lock = threading.Lock()
_replica_id_cached: str | None = None


def replica_id() -> str:
    """This process's stable replica identity: VRPMS_REPLICA_ID (set it
    to the pod/host name in real deployments so restarts keep their
    ring arcs — and their warmed tiers) or a generated one."""
    global _replica_id_cached
    if _replica_id_cached is None:
        import uuid

        _replica_id_cached = (
            config.get("VRPMS_REPLICA_ID")
            or f"replica-{uuid.uuid4().hex[:8]}"
        )
    return _replica_id_cached


# exported trace rows, scraped metrics, and readiness must all name
# this process the same way: the exporter's identity IS replica_id
trace_export.set_replica_provider(replica_id)


_advertised_addr: str | None = None


def set_advertised_addr(host: str, port: int) -> None:
    """Register the HTTP address peers can reach THIS replica at
    (service.app calls it when the port binds). Published in the
    heartbeat doc so a non-owning replica's SSE relay can locate the
    owner; a wildcard bind advertises loopback — right for same-host
    fleets (tests, the two-replica bench), and real deployments bind
    the pod address their peers route to."""
    global _advertised_addr
    if not host or host in ("0.0.0.0", "::"):
        host = "127.0.0.1"
    _advertised_addr = f"{host}:{int(port)}"


def replica_info() -> dict:
    """This process's fleet-rollup heartbeat doc: what an operator (or
    autoscaler) polling GET /api/debug/fleet on ANY replica learns
    about THIS one — inflight leases, the observed claim mix, warmed
    tiers, and local queue depth. Published to the store's replica
    registry each heartbeat (sched.replica), so the rollup needs no
    replica-to-replica RPC."""
    info: dict = {"updatedAt": time.time()}
    if is_draining():
        # peers' fleet rollups (and the local overlay) see the drain:
        # this replica is finishing or handing off its leases
        info["draining"] = True
    rep = _replica
    if rep is not None:
        try:
            info["inflight"] = rep.inflight()
            mix = rep.claim_mix()
            # bounded: the hottest handful tells the routing story
            info["claimMix"] = {
                token: round(weight, 3)
                for token, weight in list(mix.items())[:8]
            }
        except Exception:
            pass
    s = _scheduler
    if s is not None:
        try:
            info["queued"] = sum(s.queues().values())
        except Exception:
            pass
        if qos_enabled():
            try:
                classes: dict = {}
                for depths in s.queues_by_class().values():
                    for cls, n in depths.items():
                        classes[cls] = classes.get(cls, 0) + n
                info["queuedByClass"] = classes
            except Exception:
                pass
    if _advertised_addr:
        # where peers' SSE relays reach this replica's live registry
        info["addr"] = _advertised_addr
    try:
        # checkpointer liveness: a wedged flusher shows up fleet-wide
        # as a growing lastFlushAgeMs with entries > 0, plus this
        # replica's own vrpms_ckpt_total split
        ck = ckpt_mod.checkpointer().health()
        for outcome in ("written", "resumed", "dropped"):
            ck[outcome] = round(
                obs.CKPT_TOTAL.labels(outcome=outcome).value
            )
        info["ckpt"] = ck
    except Exception:
        pass
    try:
        from service import subscriptions as subs_mod

        if subs_mod.enabled():
            # standing-subscription load: how many re-solve-on-change
            # entities this replica manages, how stale their newest
            # generation is, and how many deltas sit coalesced waiting
            # for a debounce window to close (a growing backlog with an
            # aging generation is a wedged manager, visible fleet-wide)
            info["subs"] = subs_mod.manager().stats()
    except Exception:
        pass
    try:
        from service import warmup as warmup_mod

        info["tiersWarmed"] = warmup_mod.warmed_tiers()
    except Exception:
        info["tiersWarmed"] = []
    return info


def ring_token(problem: str, inst) -> str | None:
    """The ring routing key: the PADDED tier shape plus the feature
    flags that split compiled programs — deliberately COARSER than
    _bucket_key (no chains/iters/deadline), so every job of a tier
    lands on the tier's owner regardless of its budget and the owner's
    warmed programs serve all of them."""
    if inst is None:
        return None
    shape = "x".join(str(int(d)) for d in inst.durations.shape)
    return (
        f"{problem}:{shape}x{int(inst.n_vehicles)}"
        f":tw{int(bool(inst.has_tw))}:het{int(bool(inst.het_fleet))}"
        f":td{int(inst.td_rank)}"
    )


def _dist_depth_provider() -> int:
    r = _replica
    return r.store.depth() if r is not None else 0


# Shared-depth memo: the 429 bound (every distributed POST /api/jobs)
# and GET /api/ready both read the shared queue's depth, which on the
# hosted store is a network round trip PER REQUEST. A sub-second memo
# caps that at ~1/TTL store reads per replica under any load — bounded
# staleness on a signal that is only ever a load-shedding heuristic.
_depth_lock = threading.Lock()
# one memo slot per store signal: "depth" (the 429 bound + readiness),
# "tenants" (quota accounting + readiness — the full map is one scan,
# so memoizing it caps cost regardless of tenant count), "classes"
# (readiness' per-class view). All share the VRPMS_DEPTH_MEMO_MS TTL.
_memos: dict[str, tuple[float, object]] = {}  # guarded-by: _depth_lock


def _memo_read(name: str, fetch):
    """Short-TTL memoized store read (VRPMS_DEPTH_MEMO_MS; 0 = read
    through). `fetch()` may raise or return None — both mean unknown,
    are NOT memoized, and return None so callers fail open."""
    ttl = config.get("VRPMS_DEPTH_MEMO_MS") / 1e3
    now = time.monotonic()
    if ttl > 0:
        with _depth_lock:
            memo = _memos.get(name)
        if memo is not None and now - memo[0] < ttl:
            return memo[1]
    try:
        value = fetch()
    except Exception:
        return None
    if value is None:
        return None
    with _depth_lock:
        _memos[name] = (now, value)
    return value


def _shared_depth(qs) -> int | None:
    """The shared queue's depth through the short-TTL memo. None when
    the store is unreadable AND no fresh memo exists — callers choose
    their fallback (admission: don't block; readiness: omit the
    field)."""
    return _memo_read("depth", qs.depth)


def _tenant_shared_map(qs) -> dict | None:
    """The shared queue's {tenant: active entries} map (quota checks
    AND the readiness probe read it). None = unknown (store
    unreadable, or a backend predating tenant fields) — callers must
    fail open."""
    return _memo_read("tenants", qs.tenant_depths)


def _tenant_shared_depth(qs, tenant: str) -> int | None:
    """This tenant's ACTIVE (queued + leased) entries in the shared
    queue; None = unknown (quota checks fail open)."""
    depths = _tenant_shared_map(qs)
    return None if depths is None else depths.get(tenant, 0)


def _shared_class_depths(qs) -> dict | None:
    """The shared queue's {class: queued} map (readiness-only; on the
    hosted store each refresh costs one count query per class). None =
    unreadable or predates the QoS columns — the probe omits the
    field."""
    return _memo_read("classes", qs.depth_by_class)


def _fleet_infos(qs) -> tuple | None:
    """Membership + status docs through the short-TTL memo — the
    elastic-fleet controller's live-member read costs one registry
    scan per TTL no matter how often it observes (the fleet DEBUG
    surface still reads the store directly: operators want fresh).
    None = store unreadable and no fresh memo (the controller
    freezes, degraded)."""

    def fetch():
        members = qs.replicas()
        if members is None:
            return None
        return (list(members), dict(qs.replica_infos() or {}))

    return _memo_read("fleet", fetch)


# Watcher-scale read cache (the depth memo generalized to the job-read
# path): N clients polling ONE job's record / checkpoint overlay /
# owner lookup cost one store read per VRPMS_READ_TTL_MS instead of N.
# Engaged ONLY on the distributed queue with a positive TTL — the
# local-queue path never touches it, so local-mode responses stay
# byte-identical by construction, and TTL=0 reads through.
_read_lock = threading.Lock()
_read_cache: dict[str, tuple[float, object]] = {}  # guarded-by: _read_lock
#: insertion-order bound: watchers concentrate on few hot jobs, so a
#: small cap holds the working set; overflow evicts the oldest entry
_READ_CACHE_CAP = 512


def _read_cache_enabled() -> bool:
    return dist_queue_enabled() and config.get("VRPMS_READ_TTL_MS") > 0


def _cached_read(key: str, fetch, cacheable=None):
    """Bounded read-through memo on the job-read path. `fetch()`
    exceptions propagate uncached (callers keep their own degraded
    ladders); a value failing `cacheable` (default: any non-None) is
    returned but never memoized, so errored/degraded reads are retried
    at the very next poll instead of being served for a TTL."""
    if not _read_cache_enabled():
        return fetch()
    now = time.monotonic()
    ttl = config.get("VRPMS_READ_TTL_MS") / 1e3
    with _read_lock:
        memo = _read_cache.get(key)
    if memo is not None and now - memo[0] < ttl:
        obs.READ_CACHE.labels(outcome="hit").inc()
        return memo[1]
    obs.READ_CACHE.labels(
        outcome="miss" if memo is None else "stale"
    ).inc()
    value = fetch()
    if (cacheable or (lambda v: v is not None))(value):
        with _read_lock:
            if key not in _read_cache:
                while len(_read_cache) >= _READ_CACHE_CAP:
                    _read_cache.pop(next(iter(_read_cache)))
            _read_cache[key] = (now, value)
    return value


def _dist_event(name: str, replicaId: str | None = None, **kw) -> None:
    """Replica observer: lease/steal/claim telemetry -> Prometheus +
    structured log (claim-CONFLICT counts arrive separately, via the
    store.base queue-observer seam — conflicts happen inside backend
    conditional updates, not in the replica loop)."""
    if name == "claim":
        obs.DIST_CLAIMS.labels(
            kind=kw.get("kind") or "own",
            batch="multi" if (kw.get("batch") or 1) > 1 else "solo",
        ).inc()
    elif name == "claim_batch":
        # one observation per claim ROUND (not per entry): the
        # histogram answers "how full are the batches we assemble"
        obs.DIST_CLAIM_BATCH.observe(float(kw.get("size") or 1))
    elif name == "lease_renewed":
        obs.DIST_LEASES.labels(event="renewed").inc()
        return  # heartbeat cadence: counter only, no log line
    elif name == "lease_reclaimed":
        obs.DIST_LEASES.labels(event="reclaimed").inc()
    elif name == "lease_expired_dead":
        obs.DIST_LEASES.labels(event="expired_dead").inc()
    elif name == "lease_lost":
        obs.DIST_LEASES.labels(event="lost").inc()
    elif name == "drain_requeued":
        obs.DIST_LEASES.labels(event="drain_requeued").inc()
    elif name == "ack_lost":
        obs.DIST_LEASES.labels(event="ack_lost").inc()
    elif name == "nack":
        obs.DIST_LEASES.labels(event="nack").inc()
    log_event(
        f"dist.{name}", replicaId=replicaId or replica_id(), **kw
    )


def _materialize_entry(entry: dict, rid: str | None = None) -> Job:
    """Rebuild a leased queue entry into a runnable local Job on THIS
    replica: same parse (_parse_content), same prepare_request — so the
    leasing replica pads to ITS tier ladder, hits ITS compile cache,
    and its micro-batcher sees the same bucket keys a local submit
    would. Never raises: parse/prepare failures return an
    already-FAILED job (the replica acks it and publishes the clean
    envelope); a cache exact-hit or trivial request returns a born-DONE
    job. Trace continuity: the entry's traceparent re-roots this
    attempt under the SUBMITTING request's trace, and a reclaimed
    entry (attempt > 0) is marked requeued so its solve span carries
    attempt=2 — the PR-3/PR-5 crash-continuity contract, across
    replicas."""
    payload = entry.get("payload") or {}
    content = payload.get("content") or {}
    problem = payload.get("problem") or content.get("problem")
    algorithm = payload.get("algorithm") or content.get("algorithm")
    attempt = int(entry.get("attempt") or 0) + 1
    job = Job(
        payload={
            "problem": problem,
            "algorithm": algorithm,
            # ack-gated publishing: the scheduler's observer must NOT
            # persist this job's records — the replica does, only
            # after the store confirms it still held the lease
            "job_db": None,
            "dist": True,
            "dist_attempt": attempt,
        },
        time_limit=entry.get("time_limit"),
        request_id=payload.get("requestId"),
    )
    job.id = str(entry.get("id") or job.id)
    # claimed entries already passed the SHARED admission bound at
    # submit: the local class-fraction shed must not bounce them back
    # to the store (claim/nack livelock); only the hard bound applies
    job.preadmitted = True
    if qos_enabled():
        # the entry's claim-ordering fields become the local job's:
        # the leasing replica's queue applies the same class/EDF rule
        # the store claim just did
        cls = entry.get("qos")
        job.qos = cls if cls in qos_mod.RANK else qos_mod.DEFAULT_CLASS
        job.deadline_at = entry.get("deadline_at")
        job.tenant = entry.get("tenant")
    if payload.get("resolvedFrom"):
        job.payload["resolved_from"] = payload["resolvedFrom"]
    if entry.get("submitted_at"):
        # the deadline budget includes SHARED-queue wait: back-date the
        # monotonic submit clock by the entry's wall-clock age so the
        # worker's expiry check measures from the original submit
        job.submitted_at = float(entry["submitted_at"])
        age = max(0.0, time.time() - job.submitted_at)
        job.submitted_mono = time.monotonic() - age
    if entry.get("attempt"):
        job.requeued = True  # reclaimed once already: attempt=2, and
        # at-most-once parity with the local watchdog (a local crash
        # on top of a reclaim fails clean instead of a third run)
    tp = payload.get("traceparent")
    if tp:
        trace = spans.start_trace(tp)
        if trace is not None:
            # this attempt's spans export under the LEASING replica's
            # identity: the submitting replica's row for the same
            # trace_id stays intact (federated reads union them)
            trace.export_replica = rid or replica_id()
            root = trace.span("dist.execute")
            root.set(
                jobId=job.id,
                replicaId=rid or replica_id(),
                # same value under the cross-surface attr name every
                # trace root carries (service.obs.begin_request_obs)
                replica=rid or replica_id(),
                attempt=attempt,
            )
            if entry.get("_claim_batch"):
                # how this job was claimed: the waterfall shows whether
                # the fleet assembled it into a claim-K batch (and how
                # full) without cross-referencing replica logs
                s = trace.span(
                    "dist.claim_batch", parent_id=root.span_id
                )
                s.set(
                    size=entry["_claim_batch"],
                    kind=entry.get("_claim_kind"),
                    qos=job.qos,
                    deadlineAt=job.deadline_at,
                )
                s.end()
            trace.deferred = True
            job.trace, job.span = trace, root
    if (
        qos_enabled()
        and job.time_limit
        and job.time_limit > 0
        and entry.get("submitted_at")
    ):
        # stale-deadline fast-fail: a claimed job whose whole budget
        # was spent waiting in the shared queue dies HERE, with the
        # clean envelope — before parse/prepare would burn an instance
        # build and a compiled launch on a solve doomed to time out
        # (the local worker's expiry check fires after those). The
        # replica acks it as born-terminal and publishes the record.
        waited = time.time() - float(entry["submitted_at"])
        if waited >= float(job.time_limit):
            note_shed("deadline_exhausted", job.qos)
            log_event(
                "dist.deadline_exhausted",
                jobId=job.id,
                waitedMs=round(waited * 1e3, 2),
                timeLimit=job.time_limit,
            )
            job.errors = [{
                "what": "Deadline exceeded",
                "reason": (
                    f"deadline exhausted: job waited {waited:.3f}s in "
                    f"the shared queue, past its timeLimit of "
                    f"{job.time_limit}s — not launching a doomed solve"
                ),
            }]
            job.finish(FAILED)
            return job
    token = set_request_id(job.request_id)
    span_tokens = (
        spans.activate(job.trace, job.span)
        if job.trace is not None
        else None
    )
    errors: list = []
    try:
        ctx = _parse_content(content, errors)
        # crash-resume: a reclaimed entry (attempt > 0) or a drain-
        # nacked one (payload marked "ckpt") loads the predecessor
        # attempt's durable checkpoint and enters through the EXISTING
        # Prepared.resolve continuation path — the routes become an
        # inline warmStart tour, so SA re-enters at the seed-estimated
        # temperature, GA ramps, ACO pre-deposits, all with the
        # remaining budget (submitted_mono is back-dated below).
        # Best-effort: a missing/unreadable checkpoint solves from zero.
        resume_state = None
        if (
            ctx is not None
            and ckpt_mod.enabled()
            and (entry.get("attempt") or payload.get("ckpt"))
        ):
            resume_state = ckpt_mod.load_resume(job.id)
            if resume_state is not None and (
                resume_state.get("problem") != ctx["problem"]
                or resume_state.get("algorithm") != ctx["algorithm"]
            ):
                resume_state = None
            if (
                resume_state is not None
                and resume_state.get("routes")
                and not resume_state.get("shards")
            ):
                ctx["opts"]["warm_start"] = {
                    "tour": resume_state["routes"]
                }
        prep = None
        if ctx is not None:
            prep = prepare_request(
                ctx["problem"], ctx["algorithm"], ctx["params"],
                ctx["opts"], ctx["algo_params"], ctx["locations"],
                ctx["durations"], errors, ctx["database"],
            )
        if prep is None or errors:
            job.errors = errors or [{
                "what": "Data error",
                "reason": "request could not be rebuilt from the "
                "shared-queue entry",
            }]
            job.finish(FAILED)
            return job
        if prep.trivial is not None or prep.cached is not None:
            # born done on the leasing replica (e.g. the cache filled
            # between submit and claim): serve it, skip the scheduler
            if prep.cached is not None:
                job.result = solution_cache.serve_hit(prep)
            else:
                job.result = _mark_degraded(
                    prep, solution_cache.mark_trivial(prep)
                )
            job.finish(DONE)
            return job
        if resume_state is not None and resume_state.get("shards"):
            if prep.decomp is not None:
                # resumed decomposition: solve only the shards the
                # checkpoint does not already carry (service.solve
                # validates them against the rebuilt plan)
                prep.ckpt = resume_state
            else:
                resume_state = None  # plan gone (config drift): cold
        job.payload["prep"] = prep
        job.payload["backend"] = _backend_label(ctx["opts"])
        job.bucket = _bucket_key(prep)
        _attach_sink(job, prep)
        ckpt_mod.checkpointer().register(job, prep, attempt=attempt)
        if resume_state is not None and (
            prep.ckpt is not None
            or (prep.resolve is not None and prep.resolve.get("seeded"))
        ):
            ckpt_mod.note_resumed(
                job,
                resume_state,
                source="reclaim" if entry.get("attempt") else "drain",
            )
        _register_live(job)
        return job
    except Exception as e:
        log_event(
            "dist.materialize_error",
            jobId=job.id,
            error=f"{type(e).__name__}: {e}",
            traceback=traceback.format_exc(),
        )
        job.errors = [{
            "what": "Scheduler error",
            "reason": f"{type(e).__name__}: {e}",
        }]
        job.finish(FAILED)
        return job
    finally:
        if span_tokens is not None:
            spans.deactivate(span_tokens)
        reset_request_id(token)


def _dist_complete(job: Job, entry: dict, acked: bool) -> None:
    """Replica completion hook: publish the terminal record IFF the ack
    confirmed we still held the lease. An ack-refused completion is a
    lease we lost — the reclaiming peer owns the record, and writing
    ours too is exactly the duplicate-terminal bug leases prevent."""
    if (
        job.trace is not None
        and job.trace.deferred
        and not job.trace.finished
    ):
        # born-terminal jobs never reach the scheduler's observer, so
        # their deferred trace closes here
        status = "ok" if job.status == DONE else "error"
        if job.span is not None:
            job.span.end(status=None if job.status == DONE else "error")
        job.trace.finish(status=status)
    if acked:
        # persist BEFORE waking stream/poll waiters (below): a reader
        # woken by the sink close must find the terminal record
        db = store.get_database(job.payload.get("problem") or "vrp", None)
        job.payload["job_db"] = db
        _persist(job)
        # ack confirmed: this replica owns the terminal — its
        # checkpoint rows are dead state now (stale-checkpoint hygiene)
        ckpt_mod.checkpointer().finished(job.id)
        if "prep" not in job.payload:
            # born terminal at materialize (cache hit, trivial, or
            # build failure): never passed through the scheduler, so
            # its terminal was not counted by _on_event
            obs.JOBS_TOTAL.labels(
                outcome="done" if job.status == DONE else "failed"
            ).inc()
    if not acked:
        # lease lost: the reclaiming peer owns the job NOW — stop our
        # captures but keep the rows (the peer's resume reads them)
        ckpt_mod.checkpointer().finished(job.id, delete=False)
    # an un-acked completion publishes nothing (the reclaimer owns the
    # record — counted + logged by the replica's ack_lost event), but
    # local waiters still get released
    if job.sink is not None:
        job.sink.close("done" if job.status == DONE else "failed")
    _drop_live(job.id)


def _dist_dead(entry: dict) -> None:
    """A twice-crashed entry (lease expired at the attempt ceiling):
    write its clean failure record — the cross-replica analog of the
    watchdog's 'Scheduler crashed' envelope."""
    payload = entry.get("payload") or {}
    job_id = str(entry.get("id"))
    rec = {
        "id": job_id,
        "status": FAILED,
        "problem": payload.get("problem"),
        "algorithm": payload.get("algorithm"),
        "submittedAt": entry.get("submitted_at"),
        "startedAt": None,
        "finishedAt": time.time(),
        "requestId": payload.get("requestId"),
        "attempt": int(entry.get("attempt") or 0),
        "errors": [{
            "what": "Scheduler crashed",
            "reason": "replica lease expired twice while running this "
            "job; not requeueing again",
        }],
    }
    tp = payload.get("traceparent")
    if tp:
        rec["traceId"] = spans.parse_traceparent(tp)[0]
    try:
        store.get_database(payload.get("problem") or "vrp", None).save_job(
            job_id, rec
        )
    except Exception:
        pass  # save_job is already best-effort; never kill the loop
    # nack-dead hygiene: a twice-crashed job will never resume — its
    # checkpoint rows (possibly written by ANOTHER replica) are garbage
    ckpt_mod.checkpointer().delete_for(job_id)
    obs.JOBS_FAILED.labels(reason="crash").inc()
    obs.JOBS_TOTAL.labels(outcome="failed").inc()


def _subs_tick() -> None:
    """Replica-heartbeat hook: run the subscription manager's due-work
    check (cadence fires + store adoption) on this replica. Lazy import
    — subscriptions imports this module."""
    from service import subscriptions as subs_mod

    if subs_mod.enabled():
        subs_mod.manager().tick()


def _replica_tick() -> None:
    """The replica's heartbeat-hook bundle: subscriptions first, then
    the elastic-fleet controller (recommendation refresh + ring-churn
    pre-warm). Each part guarded — one subsystem's failure must not
    starve the other of its beat."""
    try:
        _subs_tick()
    except Exception:
        pass
    try:
        from service import autoscale as autoscale_mod

        autoscale_mod.tick()
    except Exception:
        pass


def build_replica(rid: str, scheduler=None, **kw):
    """A Replica wired to the service's materialize/complete path — the
    in-process multi-replica harness (tests, benchmarks/multi_replica)
    and the production singleton both build here. `scheduler` defaults
    to the process scheduler; pass a dedicated Scheduler to model
    one-replica-per-box."""
    from vrpms_tpu.sched import Replica

    def submit(job):
        target = scheduler if scheduler is not None else get_scheduler()
        try:
            target.submit(
                job, backend=job.payload.get("backend") or "default"
            )
        except QueueFull:
            # the replica nacks the entry back to the shared queue —
            # this process no longer owns the job, so its live-registry
            # entry must go too, or polls here would overlay a ghost
            # 'queued' over the eventual peer-published terminal record
            # forever (and the prepared instance would leak). The sink
            # stays open: attached streams ride keep-alives to their
            # timeout and reconnect onto the record-follow path.
            # Checkpoint captures stop too — the next claimant owns the
            # job (its rows, if any, stay for that claimant's resume).
            ckpt_mod.checkpointer().finished(job.id, delete=False)
            _drop_live(job.id)
            raise

    defaults = dict(
        lease_s=config.get("VRPMS_LEASE_S"),
        poll_s=config.get("VRPMS_QUEUE_POLL_MS") / 1e3,
        heartbeat_s=config.get("VRPMS_HEARTBEAT_S"),
        reclaim_s=config.get("VRPMS_RECLAIM_S"),
        max_inflight=config.get("VRPMS_QUEUE_MAX_INFLIGHT"),
        steal=config.enabled("VRPMS_QUEUE_STEAL"),
        vnodes=config.get("VRPMS_RING_VNODES"),
        claim_batch=config.get("VRPMS_CLAIM_BATCH"),
    )
    defaults.update(kw)
    return Replica(
        store.get_queue_store(),
        rid,
        materialize=lambda entry: _materialize_entry(entry, rid),
        submit=submit,
        complete=_dist_complete,
        dead=_dist_dead,
        on_event=lambda name, **ekw: _dist_event(name, replicaId=rid, **ekw),
        # heartbeat status doc: what GET /api/debug/fleet on any peer
        # reports about this replica
        info=replica_info,
        # standing-subscription scheduling and the elastic-fleet
        # controller both ride the heartbeat: due cadences fire,
        # orphaned pending deltas are adopted, the desired-replica
        # recommendation refreshes, and ring churn triggers
        # inherited-tier pre-warm on whichever live replica beats next
        on_tick=_replica_tick,
        **defaults,
    )


def get_replica():
    """The process replica singleton (started lazily at the first
    store-queue submit, or eagerly by warmup)."""
    global _replica
    with _replica_lock:
        if _replica is None or not _replica.alive:
            _replica = build_replica(replica_id()).start()
            obs.set_dist_depth_provider(_dist_depth_provider)
        return _replica


def _submit_distributed(handler, ctx, job: Job, prep, resolve_from=None):
    """Enqueue an async job onto the SHARED store-backed queue.

    Backpressure accounts for the shared queue, not just the local
    bound: the admission ceiling scales with live membership (each
    replica brings one local queue's worth of capacity), and
    Retry-After divides the shared backlog by the fleet's drain rate."""
    self = handler
    replica = get_replica()
    qs = replica.store
    limit = config.get("VRPMS_SCHED_QUEUE")
    # membership from the replica's cached ring (refreshed every
    # heartbeat) — the admission hot path pays ONE store read (depth),
    # not two
    ring = replica.ring()
    members = max(1, len(ring.members)) if ring is not None else 1
    depth = _shared_depth(qs)
    if depth is None:
        depth = 0  # unreadable depth must not block admits
    # selective shed: each class admits up to ITS fraction of the
    # fleet bound, so as the shared backlog grows batch 429s first,
    # then standard, and interactive keeps the full bound — with
    # Retry-After priced from the shed class's OWN observed drain.
    # A POSITIVE bound floors each class at 1 (a tiny bound must not
    # lock a class out entirely); a ZERO bound keeps its pre-QoS
    # shed-everything meaning.
    bound = limit * members
    if qos_enabled() and bound > 0:
        bound = max(1, int(bound * qos_mod.shed_fraction(job.qos)))
    if depth >= bound:
        if qos_enabled():
            retry_after = get_qos_policy().retry_after(
                job.qos, depth, drains=members
            )
        else:
            retry_after = min(
                max(1.0, depth * replica.job_seconds_ewma() / members),
                60.0,
            )
        obs.SCHED_REJECTS.labels(reason="queue_full").inc()
        note_shed("queue_full", job.qos)
        obs.JOBS_TOTAL.labels(outcome="failed").inc()
        job.errors = [{
            "what": "Too busy",
            "reason": "shared solver queue was full at submit",
        }]
        job.finish(FAILED)
        _persist(job)
        too_busy(self, retry_after)
        return
    if qos_enabled() and job.tenant is not None:
        # fleet-wide fairness: count the tenant's ACTIVE (queued +
        # leased) entries in the shared queue — accounting every
        # replica's work, not just ours. Unreadable counts fail open:
        # a store blip must not lock tenants out.
        quota = qos_mod.tenant_quota()
        active = (
            _tenant_shared_depth(qs, job.tenant) if quota > 0 else None
        )
        if active is not None and active >= quota:
            obs.SCHED_REJECTS.labels(reason="tenant_quota").inc()
            note_shed("tenant_quota", job.qos)
            obs.JOBS_TOTAL.labels(outcome="failed").inc()
            job.errors = [{
                "what": "Too busy",
                "reason": QuotaExceeded.reason,
            }]
            job.finish(FAILED)
            _persist(job)
            too_busy(
                self, _quota_retry_after(job.qos),
                reason=QuotaExceeded.reason,
            )
            return
    token = ring_token(ctx["problem"], prep.inst)
    payload = {
        "content": ctx["content"],
        "requestId": self._request_id,
        "problem": ctx["problem"],
        "algorithm": ctx["algorithm"],
    }
    if resolve_from:
        payload["resolvedFrom"] = resolve_from
    if self._trace is not None and self._trace_root is not None:
        payload["traceparent"] = spans.format_traceparent(
            self._trace.trace_id, self._trace_root.span_id
        )
    from vrpms_tpu.sched import ring as ring_mod

    entry = {
        "id": job.id,
        "slot": ring_mod.slot(token if token is not None else job.id),
        "bucket": token,
        "time_limit": job.time_limit,
        "submitted_at": job.submitted_at,
        "payload": payload,
    }
    if qos_enabled():
        # claim-ordering fields (store.base contract): class + EDF
        # deadline sort claims, tenant feeds fleet-wide quota
        # accounting. Written ONLY with QoS on, so off-path entries
        # stay byte-identical to pre-QoS ones.
        entry["qos"] = job.qos
        entry["deadline_at"] = job.deadline_at
        entry["tenant"] = job.tenant
    _persist(job)  # queued record first: a poll can never 404 a jobId
    # this 202 is about to hand out
    try:
        qs.enqueue(entry)
    except Exception as e:
        job.errors = [{
            "what": "Service unavailable",
            "reason": f"shared job queue enqueue failed: "
            f"{type(e).__name__}: {e}",
        }]
        job.finish(FAILED)
        _persist(job)
        self._obs_errors = ["Service unavailable"]
        obs.JOBS_TOTAL.labels(outcome="failed").inc()
        _respond(self, 503, {"success": False, "errors": job.errors})
        return
    log_event(
        "dist.enqueued", jobId=job.id, slot=entry["slot"], bucket=token
    )
    resp = {"success": True, "jobId": job.id, "status": job.status}
    if resolve_from:
        resp["resolvedFrom"] = resolve_from
    _respond(self, 202, resp)


# ---------------------------------------------------------------------------
# Submit-and-wait (the synchronous endpoints' path through the scheduler)
# ---------------------------------------------------------------------------


def scheduler_solve(problem, algorithm, params, opts, algo_params,
                    locations, matrix, errors, database):
    """Solve via the scheduler, blocking until the job completes.

    The synchronous endpoints' contract keeper: same envelopes as the
    old inline run_vrp/run_tsp call, but the device work runs on the
    scheduler's worker (merged with concurrent same-shape requests when
    possible). Raises QueueFull — the handler turns it into 429 +
    Retry-After. VRPMS_SCHED=off short-circuits to the inline path.
    """
    if not scheduler_enabled():
        run = run_vrp if problem == "vrp" else run_tsp
        return run(algorithm, params, opts, algo_params, locations, matrix,
                   errors, database=database)
    prep = prepare_request(problem, algorithm, params, opts, algo_params,
                           locations, matrix, errors, database)
    if prep is None or errors:
        return None
    if prep.trivial is not None:
        return _mark_degraded(prep, solution_cache.mark_trivial(prep))
    if prep.cached is not None:
        # exact cache hit: served at store-read latency, never enqueued
        # — immune to queue-full 429s and to solver wait entirely
        return solution_cache.serve_hit(prep)
    job = Job(
        payload={"prep": prep, "problem": problem, "algorithm": algorithm},
        bucket=_bucket_key(prep),
        time_limit=_job_time_limit(opts),
        request_id=current_request_id(),
        # span context crosses the thread hop ON the job: the worker
        # re-activates it (sync path: the trace stays the handler's to
        # finish — this thread parks right here until the job ends)
        trace=spans.current_trace(),
        span=spans.current_span(),
    )
    _apply_qos(job, opts, params)
    if not _tenant_admit(job):
        # fairness shed: the handler's QueueFull catch answers 429
        # with the quota reason + this class's drain-rate retry hint
        raise QuotaExceeded(0, _quota_retry_after(job.qos))
    try:
        get_scheduler().submit(job, backend=_backend_label(opts))
        job.wait()
    finally:
        # terminal events release too; this covers submit-time
        # QueueFull (the job never reached the scheduler) idempotently
        _tenant_release(job)
    if job.status == FAILED or job.result is None:
        errors += job.errors or [
            {"what": "Solver error", "reason": "job failed without detail"}
        ]
        return None
    return job.result


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


_respond = respond_json


class JobsHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """POST /api/jobs — submit a solve job, reply with its id at once."""

    algorithm = ""  # request-counter label (filled per request below)

    def do_GET(self):
        self.send_response(200)
        self.send_header("Content-type", "text/plain")
        self.end_headers()
        self.wfile.write(
            b"Hi, this is the async jobs endpoint: POST a solve request "
            b"with 'problem' and 'algorithm', poll GET /api/jobs/{id}"
        )

    def do_POST(self):
        obs.begin_request_obs(self)
        try:
            content = read_json_body(self)
            if content is not None:
                _submit_content(self, content)
        finally:
            obs.end_request_obs(self)


def _parse_content(content: dict, errors: list, handler=None) -> dict | None:
    """The fallible-without-side-effects front half of a submit: body
    shape, params/options parsing, store reads, and delta validation+
    application — everything that can reject a request WITHOUT
    consulting the scheduler (or, on the resolve path, before the
    predecessor job is touched). HEADLESS by design: the HTTP wrapper
    (_parse_submit) turns a None return into the 400 envelope, and the
    distributed-queue claim path (_materialize_entry) runs the same
    parse on whichever replica leased the job — one parser, every
    intake. Fills `errors` and returns None on rejection, or the parsed
    request context; `handler` (when given) only receives the
    request-counter labels."""
    with spans.span("parse"):
        problem = content.get("problem")
        algorithm = content.get("algorithm")
        if problem not in ("vrp", "tsp"):
            errors += [{
                "what": "Missing parameter",
                "reason": "'problem' must be 'vrp' or 'tsp'",
            }]
        if algorithm not in ("ga", "sa", "aco", "bf"):
            errors += [{
                "what": "Missing parameter",
                "reason": "'algorithm' must be one of ga|sa|aco|bf",
            }]
        if errors:
            return None
        if handler is not None:
            handler.algorithm = algorithm  # request-counter label parity
            handler.problem = problem

        parse_common, parse_algo = _PARSERS[(problem, algorithm)]
        params = parse_common(content, errors)
        algo_params = parse_algo(content, errors) if parse_algo else {}
        opts = parse_solver_options(content, errors)
        spec = opts.get("warm_start")
        if isinstance(spec, dict):
            # spec SHAPE errors are 400s and must surface here, before
            # any resolve-path cancellation (resolution itself — the
            # store reads — stays in prepare)
            try:
                solution_cache.validate_warm_spec(spec)
            except ValueError as e:
                errors += [{"what": "Data error", "reason": str(e)}]
    if errors:
        return None
    try:
        database = store.get_database(problem, params["auth"])
    except Exception as e:
        errors += [{"what": "Database error", "reason": str(e)}]
        return None
    with spans.span("store.read", tables="locations,durations"):
        locations = database.get_locations_by_id(params["locations_key"], errors)
        durations = database.get_durations_by_id(params["durations_key"], errors)
    if errors:
        return None
    # dynamic re-solve delta, same hook as the sync surface
    # (service.handler_base): the dataset view is rewritten before the
    # instance is built so fingerprints/tiers/cache keys see the
    # post-delta world
    if opts.get("delta") is not None:
        from vrpms_tpu.core.delta import apply_request_delta

        with spans.span("resolve.delta", problem=problem):
            locations = apply_request_delta(
                problem, params, locations, opts["delta"], errors
            )
        if locations is None or errors:
            return None
    return {
        "problem": problem,
        "algorithm": algorithm,
        "params": params,
        "algo_params": algo_params,
        "opts": opts,
        "database": database,
        "locations": locations,
        "durations": durations,
        "content": content,
    }


def _parse_submit(handler, content: dict) -> dict | None:
    """HTTP wrapper around _parse_content: responds with the error
    envelope itself and returns None, or returns the parsed context."""
    errors: list = []
    ctx = _parse_content(content, errors, handler=handler)
    if ctx is None:
        fail(handler, errors)
        return None
    return ctx


def _submit_content(handler, content: dict, resolve_from: str | None = None):
    """The async submit pipeline shared by POST /api/jobs and POST
    /api/jobs/{id}/resolve: parse -> store reads -> delta -> prepare ->
    enqueue (or born-done) -> 202. `resolve_from` marks a successor job
    from the cancel-and-resolve path: it rides the job payload into the
    persisted record (`resolvedFrom`) and annotates the trace root, so
    the lineage from the cancelled job to its successor is visible in
    both the record and the waterfall."""
    ctx = _parse_submit(handler, content)
    if ctx is None:
        return
    _submit_parsed(handler, ctx, resolve_from)


def _submit_parsed(handler, ctx: dict, resolve_from: str | None = None,
                   prepared=None):
    """The back half of an async submit: prepare (instance build + seed
    resolution) and enqueue. On the resolve path this runs AFTER the
    predecessor was cancelled and reached its terminal record — seed
    retrieval needs the final incumbent to exist. `prepared` (the
    subscription generation path) carries a Prepared this request
    already built — its no-op-delta dedupe needs the tier fingerprint
    BEFORE deciding to launch, and preparing twice would double the
    instance-build cost of every generation."""
    self = handler
    if is_draining():
        # a draining replica takes on nothing new: readiness already
        # steers load balancers away, this is the belt for requests
        # that still arrive (clients retry against a healthy peer)
        self._obs_errors = ["Service unavailable"]
        _respond(self, 503, {
            "success": False,
            "errors": [{
                "what": "Service unavailable",
                "reason": "replica is draining; submit to another "
                "replica (in-flight jobs are finishing or moving to "
                "peers)",
            }],
        })
        return
    problem, algorithm = ctx["problem"], ctx["algorithm"]
    params, opts, algo_params = ctx["params"], ctx["opts"], ctx["algo_params"]
    database = ctx["database"]
    errors: list = []
    prep = prepared
    if prep is None:
        prep = prepare_request(problem, algorithm, params, opts,
                               algo_params, ctx["locations"],
                               ctx["durations"], errors, database)
    if prep is None or errors:
        fail(self, errors)
        return

    if resolve_from and self._trace_root is not None:
        # the successor's waterfall names its predecessor — the other
        # half of the lineage lives in the persisted record below
        self._trace_root.set(resolvedFrom=resolve_from)
    payload = {
        "prep": prep,
        "problem": problem,
        "algorithm": algorithm,
        "job_db": store.get_database(problem, None),
    }
    if resolve_from:
        payload["resolved_from"] = resolve_from
    job = Job(
        payload=payload,
        bucket=_bucket_key(prep),
        time_limit=_job_time_limit(opts),
        request_id=self._request_id,
        trace=self._trace,
        span=self._trace_root,
    )
    _apply_qos(job, opts, params)
    if prep.trivial is not None or prep.cached is not None:
        # nothing to schedule: the job is born done (a trivial
        # zero-customer request, or an exact cache hit — the cached
        # routes/cost/certificate ARE the result, so the admission
        # queue and the solver are bypassed entirely)
        if prep.cached is not None:
            job.result = solution_cache.serve_hit(prep)
        else:
            job.result = _mark_degraded(
                prep, solution_cache.mark_trivial(prep)
            )
        job.finish(DONE)
        _persist(job)
        obs.JOBS_TOTAL.labels(outcome="done").inc()
        _respond(self, 202, {
            "success": True, "jobId": job.id, "status": job.status,
        })
        return
    if dist_queue_enabled() and scheduler_enabled():
        # store-backed shared queue: enqueue the REQUEST (not the
        # prepared instance) so any replica can lease, rebuild, and
        # solve it — the claim path re-runs this exact parse/prepare
        # on the leasing replica (_materialize_entry). Fairness there
        # is store-accounted (every replica's active entries count),
        # so the in-process quota ledger below is not consulted.
        _submit_distributed(self, ctx, job, prep, resolve_from)
        return
    if not _tenant_admit(job):
        # per-tenant fairness shed (local fleet = this process):
        # answered like a queue-full 429, but with the quota reason
        # and this class's own drain-rate retry hint
        obs.SCHED_REJECTS.labels(reason="tenant_quota").inc()
        note_shed("tenant_quota", job.qos)
        obs.JOBS_TOTAL.labels(outcome="failed").inc()
        job.errors = [{"what": "Too busy", "reason": QuotaExceeded.reason}]
        job.finish(FAILED)
        _persist(job)
        too_busy(
            self, _quota_retry_after(job.qos), reason=QuotaExceeded.reason
        )
        return
    # live-progress mailbox + registry entry BEFORE the submit: the
    # worker may pop the job the instant it lands, and the runner
    # reads job.sink then
    _attach_sink(job, prep)
    ckpt_mod.checkpointer().register(job, prep)
    _register_live(job)
    try:
        _persist(job)  # queued record first: a poll can never 404
        # a job whose id was already returned
        if self._trace is not None:
            # the 202 leaves now; the worker finishes the trace at
            # the job's terminal transition (service._on_event)
            self._trace.deferred = True
        get_scheduler().submit(job, backend=_backend_label(opts))
    except QueueFull as e:
        if self._trace is not None:
            self._trace.deferred = False  # never scheduled: ours again
        if job.sink is not None:
            job.sink.close("failed")
        # never scheduled: the checkpointer entry must go too, or every
        # overload-rejected submit would leak one registry slot forever
        ckpt_mod.checkpointer().finished(job.id, delete=False)
        _drop_live(job.id)
        _tenant_release(job)  # never scheduled: free the quota slot
        obs.SCHED_REJECTS.labels(reason="queue_full").inc()
        note_shed("queue_full", job.qos)
        obs.JOBS_TOTAL.labels(outcome="failed").inc()
        job.errors = [{
            "what": "Too busy",
            "reason": "solver admission queue was full at submit",
        }]
        job.finish(FAILED)
        _persist(job)
        too_busy(self, e.retry_after_s)
        return
    except BaseException:
        # any other submit-path failure: the job will never run —
        # a leaked registry entry would hold the prepared instance
        # forever and answer DELETEs 202 for a ghost
        if self._trace is not None:
            self._trace.deferred = False
        if job.sink is not None:
            job.sink.close("failed")
        ckpt_mod.checkpointer().finished(job.id, delete=False)
        _drop_live(job.id)
        _tenant_release(job)
        raise
    resp = {"success": True, "jobId": job.id, "status": job.status}
    if resolve_from:
        resp["resolvedFrom"] = resolve_from
    _respond(self, 202, resp)


class _HeadlessSubmit:
    """An HTTP-handler stand-in with no socket: subscription generation
    launches (service.subscriptions) ride the EXACT _submit_parsed /
    _submit_distributed pipeline — draining guard, QoS stamping, tenant
    quota, lineage, trace deferral — and this shim captures the
    envelope that would have gone over the wire. Every responder
    (respond_json, fail, too_busy) funnels through send_response /
    wfile, so capturing those two is capturing the contract."""

    def __init__(self, request_id=None, trace=None, trace_root=None):
        self._request_id = request_id
        self._trace = trace
        self._trace_id = trace.trace_id if trace is not None else None
        self._trace_root = trace_root
        self._obs_errors = None
        self.algorithm = ""
        self.problem = ""
        self.headers: dict = {}
        self.code: int | None = None
        self.wfile = io.BytesIO()

    def send_response(self, code):
        self.code = code

    def send_header(self, key, value):
        pass

    def end_headers(self):
        pass

    def result(self) -> tuple[int, dict]:
        raw = self.wfile.getvalue()
        try:
            body = json.loads(raw.decode("utf-8")) if raw else {}
        except ValueError:
            body = {}
        return self.code or 0, body


def submit_headless(ctx: dict, resolve_from: str | None = None,
                    prepared=None, request_id=None, trace=None,
                    trace_root=None) -> tuple[int, dict]:
    """Submit a parsed request with no HTTP handler — the jobs.py seam
    the subscription manager launches generations through. Returns the
    (status code, envelope) the pipeline would have answered: 202 with
    a jobId on an accepted (or born-done) submit, 400/429/503 with the
    contract's error envelope otherwise."""
    shim = _HeadlessSubmit(
        request_id=request_id, trace=trace, trace_root=trace_root
    )
    _submit_parsed(shim, ctx, resolve_from, prepared=prepared)
    return shim.result()


def _job_id_from_path(path: str) -> str:
    """The {id} segment of /api/jobs/{id}[/stream|/resolve|/timeline]
    — the ONE parser every per-job handler uses."""
    parts = [p for p in path.split("?", 1)[0].rstrip("/").split("/") if p]
    if parts and parts[-1] in ("stream", "resolve", "timeline"):
        parts = parts[:-1]
    return parts[-1] if parts else ""


def _federation_enabled() -> bool:
    """Federated reads: a non-owning replica overlays checkpoint (or
    relayed) incumbents on the store record. VRPMS_READ_RELAY=off (or
    the local queue, where every job IS owned here) restores the
    pre-federation responses byte-identically."""
    return dist_queue_enabled() and config.enabled("VRPMS_READ_RELAY")


def _checkpoint_incumbent(job_id: str) -> tuple[dict | None, bool]:
    """The latest durable checkpoint row as a MARKED incumbent snapshot
    for a job some OTHER replica is solving: (snapshot, degraded).
    The snapshot always carries `incumbentSource: "checkpoint"` and
    `staleMs` (age of the row's write; None for rows predating the
    writtenAt field) — an honest bounded-staleness view, never passed
    off as live. degraded=True means the store could not answer (the
    caller marks the response; a miss is NOT degraded — short solves
    legitimately never checkpoint)."""
    errors: list = []

    def fetch():
        db = store.get_database("vrp", None)
        with spans.span("read.federate", jobId=job_id):
            return db.get_checkpoint(job_id, errors)

    try:
        row = _cached_read(
            f"ckpt:{job_id}", fetch,
            cacheable=lambda v: v is not None and not errors,
        )
    except Exception:
        return None, True
    if errors:
        return None, True
    if not isinstance(row, dict):
        return None, False
    state = row.get("state")
    if not isinstance(state, dict) or state.get("cost") is None:
        return None, False
    written = state.get("writtenAt")
    snap = {
        "block": state.get("block"),
        "wallMs": state.get("elapsedMs"),
        "bestCost": state.get("cost"),
        "evals": state.get("evals"),
        "incumbentSource": "checkpoint",
        "staleMs": (
            None if written is None
            else max(0, round((time.time() - float(written)) * 1e3))
        ),
    }
    return snap, False


def _relay_snap(job_id: str) -> dict | None:
    """Live incumbent relayed from the OWNING replica (located via the
    queue entry's lease + the heartbeat registry's advertised address),
    marked `incumbentSource: "relay"`. Strictly best-effort: any gap —
    no replica loop here, unleased entry, owner gone, no advertised
    addr, fetch error, or the owner itself answering with second-hand
    (marked) state — returns None and the caller falls back to the
    checkpoint row. Never raises."""
    rep = _replica
    if rep is None:
        return None
    try:
        owner = _cached_read(
            f"owner:{job_id}", lambda: rep.owner_of(job_id)
        )
        if not owner or owner == replica_id():
            return None
        infos = _cached_read(
            "replica_infos", lambda: rep.store.replica_infos()
        )
    except Exception:
        return None
    addr = ((infos or {}).get(owner) or {}).get("addr")
    if not addr:
        return None

    def fetch():
        import urllib.request

        with spans.span("read.relay", jobId=job_id, owner=owner):
            req = urllib.request.Request(
                f"http://{addr}/api/jobs/{job_id}"
            )
            with urllib.request.urlopen(req, timeout=1.0) as resp:
                doc = json.loads(resp.read().decode("utf-8"))
        snap = (doc.get("job") or {}).get("incumbent")
        if not isinstance(snap, dict) or "incumbentSource" in snap:
            # the owner answered with its OWN federated overlay (it
            # lost the lease): second-hand state must not be re-marked
            # as a live relay
            return None
        return {"snap": snap, "at": time.time()}

    try:
        got = _cached_read(f"relay:{job_id}", fetch)
    except Exception:
        return None
    if got is None:
        return None
    snap = dict(got["snap"])
    snap["incumbentSource"] = "relay"
    snap["staleMs"] = max(0, round((time.time() - got["at"]) * 1e3))
    return snap


def _load_job_record(handler, job_id: str) -> dict | None:
    """Fetch a job's persisted record for an HTTP handler — the ONE
    store-read + error-envelope ladder behind the status poll, the
    cancel, and the stream. Writes the Database-error / 400 / 404
    envelope itself and returns None when it already responded; flags
    degraded reads on `handler._job_db_degraded`. On the distributed
    queue the read goes through the watcher-scale cache (clean,
    non-degraded records only; a hit costs no store round trip)."""
    errors: list = []
    handler._job_db_degraded = False

    def fetch():
        db = store.get_database("vrp", None)
        with spans.span("store.read", tables="jobs"):
            record = db.get_job(job_id, errors)
        handler._job_db_degraded = getattr(db, "degraded", False)
        return record

    try:
        record = _cached_read(
            f"job:{job_id}", fetch,
            cacheable=lambda v: (
                v is not None
                and not errors
                and not handler._job_db_degraded
            ),
        )
    except Exception as e:
        fail(handler, [{"what": "Database error", "reason": str(e)}])
        return None
    if errors:
        fail(handler, errors)
        return None
    if record is None:
        handler._obs_errors = ["Not found"]
        _respond(handler, 404, {
            "success": False,
            "errors": [{
                "what": "Not found",
                "reason": f"no job with id {job_id!r}",
            }],
        })
        return None
    return record


class JobStatusHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/jobs/{id} — poll a job's lifecycle record."""

    def do_GET(self):
        # header-sampled: a poll loop must not evict solve traces from
        # the debug ring; polls that DO carry traceparent join fully
        obs.begin_request_obs(self, sample="header")
        try:
            self._status()
        finally:
            obs.end_request_obs(self)

    def _status(self):
        job_id = _job_id_from_path(self.path)
        record = _load_job_record(self, job_id)
        if record is None:
            return
        live = get_live_job(job_id)
        if live is not None:
            # the store record only updates at lifecycle transitions —
            # overlay the live view, COPYING (the memory store hands
            # out its live row). The status overlays only while
            # PRE-terminal: a live job that just turned done has its
            # message/errors in the terminal persist, and handing out
            # status='done' off a stale 'running' record would end a
            # client's poll loop without the result.
            overlay: dict = {}
            if live.status in (QUEUED, RUNNING):
                overlay["status"] = live.status
            snap = live.sink.snapshot() if live.sink is not None else None
            if snap is not None:
                overlay["incumbent"] = snap
            if overlay:
                record = dict(record, **overlay)
            if _federation_enabled():
                obs.FEDERATED_READS.labels(source="live").inc()
        elif (
            _federation_enabled()
            and record.get("status") not in (DONE, FAILED)
        ):
            # another replica's live solve: overlay the latest durable
            # checkpoint as an HONESTLY MARKED incumbent (the live
            # overlay above never carries the markers). A store outage
            # degrades to the bare record with the degraded flag —
            # marked, never a 500.
            snap, ckpt_degraded = _checkpoint_incumbent(job_id)
            if snap is not None:
                record = dict(record, incumbent=snap)
                obs.FEDERATED_READS.labels(source="checkpoint").inc()
            if ckpt_degraded:
                self._job_db_degraded = True
                obs.FEDERATED_READS.labels(source="degraded").inc()
        payload = {"success": True, "job": record}
        if self._job_db_degraded:
            # the record came from the degraded-mode fallback (possibly
            # stale last-known state), not an authoritative store read
            payload["degraded"] = True
        _respond(self, 200, payload)

    def do_DELETE(self):
        """DELETE /api/jobs/{id} — cooperative cancellation: flags the
        job's sink; the deadline driver stops at the next block
        boundary and the job completes with its incumbent marked
        `cancelled: true`. Boundary-granular by design: a deadline-free
        solve runs as ONE device block, so a cancel landing mid-block
        runs out its budget and the (complete) result is NOT marked
        cancelled — the 202 records the request, the mark records that
        a driver actually stopped for it."""
        obs.begin_request_obs(self, sample="header")
        try:
            self._cancel()
        finally:
            obs.end_request_obs(self)

    def _cancel(self):
        job_id = _job_id_from_path(self.path)
        job = get_live_job(job_id)
        if job is not None and not job.done_event.is_set():
            if job.sink is None:
                self._obs_errors = ["Not cancellable"]
                _respond(self, 409, {
                    "success": False,
                    "errors": [{
                        "what": "Not cancellable",
                        "reason": "job carries no progress sink "
                        "(VRPMS_PROGRESS=off); it will run to completion",
                    }],
                })
                return
            job.sink.cancel()
            log_event("job.cancel_requested", jobId=job_id,
                      status=job.status)
            _respond(self, 202, {
                "success": True, "jobId": job_id, "status": job.status,
                "cancelRequested": True,
            })
            return
        # not live here: either already terminal (answer the record —
        # cancelling a finished job is a no-op, not an error) or unknown
        record = _load_job_record(self, job_id)
        if record is None:
            return
        _respond(self, 200, {
            "success": True, "job": record, "cancelRequested": False,
        })


class JobStreamHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/jobs/{id}/stream — Server-Sent Events of the live solve.

    Event protocol (SSE framing, one `event:` + one `data:` JSON line):

      * `progress` — an improving incumbent snapshot
        {block, wallMs, bestCost, gap, evals}; emitted once per
        improvement the sink publishes (the current incumbent is
        replayed first on connect, so a late subscriber starts from
        the latest state, never from silence);
      * `done` / `failed` — the terminal job record (same shape as
        GET /api/jobs/{id}); the stream closes after it;
      * `timeout` — the stream outlived VRPMS_STREAM_TIMEOUT_S
        (default 600 s) with the job still running; reconnect to
        resume (the replay-first rule makes that lossless for the
        incumbent).

    Keep-alive comment lines (`: keep-alive`) go out during quiet
    waits so a dead client surfaces as a write error — the handler
    logs `stream.disconnect` and returns; a mid-stream disconnect
    never touches the solve."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            self._stream()
        finally:
            obs.end_request_obs(self)

    def _stream(self):
        job_id = _job_id_from_path(self.path)
        job = get_live_job(job_id)
        record = None
        if job is None:
            record = _load_job_record(self, job_id)
            if record is None:
                return
        # reconnect contract: progress events carry `id: {block}`, so a
        # dropped watcher resends the last block it saw (Last-Event-ID
        # — possibly to a DIFFERENT replica) and resumes without the
        # already-seen incumbent being replayed
        last_id = None
        raw = self.headers.get("Last-Event-ID")
        if raw:
            try:
                last_id = int(raw)
            except (TypeError, ValueError):
                last_id = None
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream; charset=utf-8")
        self.send_header("Cache-Control", "no-cache")
        from service.helpers import send_static_headers

        send_static_headers(self)
        self.end_headers()
        try:
            if job is None:
                self._follow_record(job_id, record, last_id)
                return
            self._follow(job, last_id)
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            # client went away mid-stream; the solve is unaffected
            log_event(
                "stream.disconnect", jobId=job_id,
                error=f"{type(e).__name__}: {e}",
            )

    def _emit(self, name: str, payload: dict, event_id=None) -> None:
        frame = f"event: {name}\n"
        if event_id is not None:
            frame += f"id: {event_id}\n"
        frame += f"data: {json.dumps(payload)}\n\n"
        self.wfile.write(frame.encode("utf-8"))
        self.wfile.flush()

    def _federated_snap(self, job_id: str) -> dict | None:
        """A non-owning replica's freshest view of a running solve:
        relay from the owner when it is reachable, else the durable
        checkpoint row — both marked with incumbentSource/staleMs. A
        store outage counts one degraded read and returns None (the
        stream keeps heart-beating on the bare record; headers are long
        sent, so degrading is the only honest option — never a 500)."""
        snap = _relay_snap(job_id)
        if snap is not None:
            obs.FEDERATED_READS.labels(source="relay").inc()
            return snap
        snap, ckpt_degraded = _checkpoint_incumbent(job_id)
        if ckpt_degraded:
            obs.FEDERATED_READS.labels(source="degraded").inc()
            return None
        if snap is not None:
            obs.FEDERATED_READS.labels(source="checkpoint").inc()
        return snap

    def _follow_record(self, job_id: str, record: dict,
                       last_id=None) -> None:
        """Stream a job this process does NOT own (another replica's, or
        one predating a restart of this one): no live sink exists, so
        follow the persisted record — terminal already means one
        terminal event now; otherwise poll the store at a gentle cadence
        until it turns terminal, emitting its incumbent snapshots as
        they land. With federated reads on, each round also overlays the
        owner-relayed (or checkpoint-sourced) incumbent at the
        checkpoint cadence, so a watcher pinned to a NON-owning replica
        tracks the solve within one cadence of the owner's view. A
        non-terminal record must NEVER be reported as `failed`: the job
        is healthy, just not ours."""
        timeout_s = config.get("VRPMS_STREAM_TIMEOUT_S")
        deadline = time.monotonic() + timeout_s
        last_block = last_id
        federate = _federation_enabled()
        # the checkpoint row refreshes at the checkpoint cadence —
        # polling a non-owned job faster than that buys nothing
        poll_s = min(2.0, ckpt_mod.interval_s()) if federate else 2.0
        while True:
            status = record.get("status")
            snap = record.get("incumbent")
            if federate and status not in (DONE, FAILED):
                fed = self._federated_snap(job_id)
                if fed is not None:
                    snap = fed
            if snap is not None and snap.get("block") != last_block:
                last_block = snap.get("block")
                self._emit("progress", snap, event_id=last_block)
            if status in ("done", "failed"):
                self._emit("done" if status == "done" else "failed", record)
                return
            if time.monotonic() >= deadline:
                self._emit("timeout", {"jobId": job_id})
                return
            self.wfile.write(b": keep-alive\n\n")
            self.wfile.flush()
            time.sleep(max(0.05, poll_s))
            errors: list = []

            def fetch():
                db = store.get_database("vrp", None)
                return db.get_job(job_id, errors)

            try:
                fresh = _cached_read(
                    f"job:{job_id}", fetch,
                    cacheable=lambda v: v is not None and not errors,
                )
            except Exception:
                fresh = None
            if fresh is not None and not errors:
                record = fresh

    def _follow(self, job: Job, last_id=None) -> None:
        timeout_s = config.get("VRPMS_STREAM_TIMEOUT_S")
        deadline = time.monotonic() + timeout_s
        sink = job.sink
        if sink is None:
            # progress off: only the terminal event exists — park on
            # the job's own done event, heartbeating so disconnects
            # surface
            while not job.done_event.wait(timeout=15.0):
                if time.monotonic() >= deadline:
                    self._emit("timeout", {"jobId": job.id})
                    return
                self.wfile.write(b": keep-alive\n\n")
                self.wfile.flush()
            self._emit_terminal(job)
            return
        # a reconnecting watcher's Last-Event-ID primes the dedupe so
        # the replay-first rule skips the one block it already saw
        # (`!=`, not `>`: blocks legitimately restart at 0 on a
        # requeued/resumed attempt, which MUST stream again)
        seen, last_block = 0, last_id
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._emit("timeout", {"jobId": job.id})
                return
            seq, snap, closed = sink.wait_progress(
                seen, timeout=min(15.0, remaining)
            )
            if snap is not None and snap.get("block") != last_block:
                last_block = snap.get("block")
                self._emit("progress", snap, event_id=last_block)
            if closed:
                self._emit_terminal(job)
                return
            if seq == seen:
                # quiet wait elapsed with no movement: heartbeat
                self.wfile.write(b": keep-alive\n\n")
                self.wfile.flush()
            seen = seq

    def _emit_terminal(self, job: Job) -> None:
        # the live Job is authoritative here (the terminal store
        # persist may still be in flight when the close wakes us)
        job.wait(timeout=30.0)
        self._emit(
            "done" if job.status == DONE else "failed", _job_record(job)
        )


class JobResolveHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """POST /api/jobs/{id}/resolve — cancel-and-resolve for dynamic
    re-solves: cooperatively cancel a running job, take its final
    incumbent as the warm seed, apply the request's `delta`, and submit
    the successor job.

    The body is a full solve request (same schema as POST /api/jobs,
    `delta` and `warmStart` included); when it carries no explicit
    `warmStart`, `{"jobId": "{id}"}` is injected so the successor seeds
    from the predecessor's result. Sequence:

      1. fully parse and validate the body — params, options, the
         warm-spec shape, and the delta against the dataset — so every
         400 lands BEFORE the predecessor is touched (a malformed
         successor must not cost the running job its budget);
      2. if the job is live here, flag its sink (the PR-7 cooperative
         cancel) and wait for the terminal transition — the cancelled
         job completes with its incumbent as a normal `done` record;
      3. submit the successor through the standard async pipeline; the
         202 carries the new jobId plus `resolvedFrom`, the successor's
         record and trace are linked the same way, and — because clone
         0 of a warm seed is exactly the seed — its first published
         incumbent is never worse than the predecessor's final one on
         the unchanged customer set.

    Answers: 202 (submitted), 400 (bad body), 404 (unknown job), 409
    (the predecessor did not reach a terminal state in time — e.g. a
    sink-less VRPMS_PROGRESS=off job mid-solve)."""

    algorithm = ""

    def do_POST(self):
        obs.begin_request_obs(self)
        try:
            self._resolve()
        finally:
            obs.end_request_obs(self)

    def _resolve(self):
        job_id = _job_id_from_path(self.path)
        content = read_json_body(self)
        if content is None:
            return
        # the FULL fallible front half — body shape, params, options,
        # warm-spec shape, store reads, delta validation — runs before
        # the predecessor is touched: a malformed successor must not
        # cost the running job its budget (every 400 lands here)
        ctx = _parse_submit(self, content)
        if ctx is None:
            return
        live = get_live_job(job_id)
        if live is not None and not live.done_event.is_set():
            if live.sink is not None:
                live.sink.cancel()
                log_event(
                    "job.cancel_requested", jobId=job_id,
                    status=live.status, resolve=True,
                )
            wait_s = config.get("VRPMS_RESOLVE_WAIT_S")
            if not live.wait(timeout=wait_s):
                self._obs_errors = ["Conflict"]
                _respond(self, 409, {
                    "success": False,
                    "errors": [{
                        "what": "Conflict",
                        "reason": f"job {job_id!r} did not reach a "
                        f"terminal state within {wait_s:g}s "
                        "(cancellation is cooperative; a sink-less job "
                        "runs to completion) — retry once it finishes",
                    }],
                })
                return
        elif live is None:
            # not ours and not live: the persisted record decides 404
            # vs. proceed (another replica's finished job seeds fine)
            record = _load_job_record(self, job_id)
            if record is None:
                return
            if (
                dist_queue_enabled()
                and record.get("status") not in (DONE, FAILED)
            ):
                # the job is executing on ANOTHER replica: cooperative
                # cancellation is replica-local, so proceeding would
                # silently skip the cancel, seed from a record with no
                # final incumbent, and leave two solves burning budget
                # on the same request — refuse honestly instead
                self._obs_errors = ["Conflict"]
                _respond(self, 409, {
                    "success": False,
                    "errors": [{
                        "what": "Conflict",
                        "reason": f"job {job_id!r} is in progress on "
                        "another replica; cancellation is replica-local "
                        "— retry once it reaches a terminal state (or "
                        "route the resolve to the replica running it)",
                    }],
                })
                return
        if ctx["opts"].get("warm_start") is None:
            ctx["opts"]["warm_start"] = {"jobId": job_id}
            # the raw content is what a distributed-queue entry carries
            # (the leasing replica re-parses it): the injected seed
            # source must ride along or a cross-replica resolve would
            # silently solve cold
            ctx["content"] = dict(ctx["content"], warmStart={"jobId": job_id})
        log_event("job.resolve", jobId=job_id)
        _submit_parsed(self, ctx, resolve_from=job_id)


# ---------------------------------------------------------------------------
# Graceful drain (POST /api/admin/drain + SIGTERM)
# ---------------------------------------------------------------------------
# A draining replica stops taking on new work — async submits shed with
# 503 and the readiness probe reports `draining` so load balancers
# rotate it out — while in-flight jobs get VRPMS_DRAIN_GRACE_S to
# finish. Whatever cannot finish in the grace window is checkpointed
# (the freshest captured incumbent / completed shards flush
# synchronously) and NACKED back to the shared queue with a
# {"ckpt": true} payload marker, so a peer claims it, loads the
# checkpoint, and resumes exactly-once — the voluntary twin of the
# lease-reclaim crash path, without burning an attempt or waiting out
# a lease expiry. Local-queue deployments (no peers) simply let
# in-flight work finish. SIGTERM runs the same sequence through
# shutdown_scheduler (service.app).

_drain_lock = threading.Lock()
_drain_state: dict = {  # guarded-by: _drain_lock
    "draining": False,
    "startedAt": None,
    "requeued": 0,
    "complete": False,
}


def is_draining() -> bool:
    with _drain_lock:
        return bool(_drain_state["draining"])


def drain_info() -> dict | None:
    """The drain state doc for readiness / fleet surfaces; None when
    not draining."""
    with _drain_lock:
        if not _drain_state["draining"]:
            return None
        return dict(_drain_state)


def _reset_drain() -> None:
    with _drain_lock:
        _drain_state.update(
            draining=False, startedAt=None, requeued=0, complete=False
        )


def _drain_requeue(job: Job, entry: dict):
    """Replica.drain's per-job hook: flush the job's freshest captured
    checkpoint state NOW (the nack is about to hand the job to a peer)
    and stop local captures without deleting the rows — the peer's
    resume reads them. The returned note marks the queue entry so the
    claimant probes the checkpoint store even at attempt=0."""
    try:
        ckpt_mod.checkpointer().flush_job(job.id)
    except Exception:
        pass
    ckpt_mod.checkpointer().finished(job.id, delete=False)
    return {"ckpt": True} if ckpt_mod.enabled() else None


def _drain_worker(grace_s: float) -> None:
    rep = _replica
    requeued = 0
    if rep is not None:
        requeued = rep.drain(grace_s, requeue=_drain_requeue)
    else:
        # local queue: no peers to hand work to — in-flight jobs just
        # finish (cooperative; the grace bounds how long we watch)
        deadline = time.monotonic() + max(0.0, grace_s)
        while _running_count() and time.monotonic() < deadline:
            time.sleep(0.05)
    with _drain_lock:
        _drain_state.update(requeued=requeued, complete=True)
    log_event("drain.complete", requeued=requeued)


def start_drain(grace_s: float | None = None) -> dict:
    """Flip this replica into drain mode (idempotent) and run the
    drain on a background thread; returns the current drain state."""
    grace = (
        float(grace_s)
        if grace_s is not None
        else config.get("VRPMS_DRAIN_GRACE_S")
    )
    with _drain_lock:
        if _drain_state["draining"]:
            # idempotent: a second request reports the in-flight
            # drain's progress (marked) instead of spawning a second
            # drain thread — the marker lives only in the RETURN value,
            # never in the state doc
            return dict(_drain_state, alreadyDraining=True)
        _drain_state.update(
            draining=True, startedAt=time.time(), requeued=0,
            complete=False,
        )
        state = dict(_drain_state)
    log_event("drain.started", graceS=grace)
    threading.Thread(
        target=_drain_worker, args=(grace,), name="vrpms-drain",
        daemon=True,
    ).start()
    return state


class DrainHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """POST /api/admin/drain — begin a graceful drain: stop claiming,
    let in-flight jobs finish within the grace window, checkpoint-and-
    requeue the rest to peers, deregister the heartbeat. 202 with the
    drain state; idempotent (a second POST reports progress). GET
    answers the current state without starting anything."""

    def do_POST(self):
        obs.begin_request_obs(self)
        try:
            state = start_drain()
            _respond(self, 202, {"success": True, "drain": state})
        finally:
            obs.end_request_obs(self)

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            _respond(self, 200, {
                "success": True,
                "drain": drain_info() or {"draining": False},
            })
        finally:
            obs.end_request_obs(self)


# ---------------------------------------------------------------------------
# Readiness probe
# ---------------------------------------------------------------------------


def readiness() -> tuple[int, dict]:
    """Compute the service's readiness: (http status, body).

    `ok`       — everything healthy.
    `degraded` — still answering, but on fallbacks: a store circuit is
                 open/half-open, spooled writes await replay, a worker
                 is wedged (restart imminent), or a worker restarted in
                 the last VRPMS_READY_RESTART_WINDOW_S seconds.
    `down`     — not serving solves: the scheduler was shut down, or a
                 worker is dead with the watchdog disabled (nothing
                 will ever drain its queue). Answers 503 so load
                 balancers rotate the instance out.
    """
    try:
        from store import resilient

        circuits = resilient.circuit_states()
        journal = resilient.journal_depths()
    except Exception:  # pragma: no cover - resilient always importable
        circuits, journal = {}, {}
    s = _scheduler
    workers = s.worker_health() if s is not None else {}
    restarts = dict(s.restarts) if s is not None else {}
    window_s = config.get("VRPMS_READY_RESTART_WINDOW_S")
    recent_restart = (
        s is not None
        and s.last_restart_mono is not None
        and time.monotonic() - s.last_restart_mono < window_s
    )
    drain = drain_info()
    status = "ok"
    if (
        any(state != "closed" for state in circuits.values())
        or any(journal.values())
        or any(state == "wedged" for state in workers.values())
        or recent_restart
        # a draining replica still answers, but load balancers should
        # rotate it out — in-flight work is finishing or moving to
        # peers and nothing new will be claimed
        or drain is not None
    ):
        status = "degraded"
    watchdog_on = config.get("VRPMS_SCHED_WATCHDOG_MS") > 0
    if (
        (s is None and _drained)  # drained, no rebuild yet
        or (s is not None and s.is_shutdown)
        or (not watchdog_on and any(st == "dead" for st in workers.values()))
    ):
        status = "down"
    body = {
        "status": status,
        "circuits": circuits,
        "journalDepths": journal,
        "workers": workers,
        "workerRestarts": restarts,
    }
    if drain is not None:
        body["draining"] = True
        body["drain"] = drain
    if dist_queue_enabled():
        # operators see the ring from any replica: who am I, who else
        # is alive, which share of the tier space (and therefore which
        # warmed tiers) this replica owns, and the shared backlog
        info: dict = {"replicaId": replica_id(), "queue": "store"}
        rep = _replica
        if rep is not None:
            ring = rep.ring()
            if ring is not None:
                info["ringMembers"] = ring.members
                info["ringArcs"] = len(ring.arcs(rep.replica_id))
                info["arcShare"] = round(ring.share(rep.replica_id), 4)
            info["inflight"] = rep.inflight()
            # memoized: readiness probes at LB cadence must not add a
            # store round trip each (a queue-store blip omits the field
            # rather than failing readiness)
            depth = _shared_depth(rep.store)
            if depth is not None:
                info["sharedDepth"] = depth
        try:
            from service import warmup as warmup_mod

            info["tiersWarmed"] = warmup_mod.warmed_tiers()
        except Exception:
            info["tiersWarmed"] = []
        body["replica"] = info
    if qos_enabled():
        # the QoS operator view alongside the replica block: who is
        # queued by class (local admission queues; plus the SHARED
        # queue's per-class depth on the store path) and which tenants
        # hold how much in-flight work — i.e. who is being shed and why
        classes = {name: 0 for name in qos_mod.CLASSES}
        if s is not None:
            for depths in s.queues_by_class().values():
                for cls, n in depths.items():
                    classes[cls] = classes.get(cls, 0) + n
        qinfo: dict = {"queued": classes}
        tenants = _tenant_map()
        if dist_queue_enabled():
            rep = _replica
            if rep is not None:
                # memoized (VRPMS_DEPTH_MEMO_MS): probes at LB cadence
                # must not add store round trips each; a store blip
                # omits the fields rather than failing readiness
                shared = _shared_class_depths(rep.store)
                if shared is not None:
                    qinfo["sharedQueued"] = shared
                fleet_tenants = _tenant_shared_map(rep.store)
                if fleet_tenants is not None:
                    # the fleet-wide map (what quotas actually divide
                    # by) supersedes the process-local ledger
                    tenants = fleet_tenants
        qinfo["tenants"] = tenants
        qinfo["tenantQuota"] = qos_mod.tenant_quota() or None
        body["qos"] = qinfo
    return (503 if status == "down" else 200), body


class ReadyHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/ready — ok|degraded|down readiness probe (503 on down).
    The 503 envelope carries requestId/traceId like every error path:
    an outage answer is exactly the response that must correlate."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            code, body = readiness()
            if code != 200:
                self._obs_errors = [body["status"]]
            _respond(self, code, dict(body, success=code == 200))
        finally:
            obs.end_request_obs(self)


# scrape-time vrpms_jobs_running comes from the live registry (the
# same pattern as the queue-depth provider above)
obs.set_jobs_running_provider(_running_count)
