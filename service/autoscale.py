"""Elastic-fleet wiring: the autoscale controller's service surfaces.

The policy lives in :mod:`vrpms_tpu.sched.autoscale` (pure arithmetic,
stdlib-only); this module feeds it the fleet's signals and exposes the
three surfaces ISSUE 18 names:

  * **recommendation** — :func:`observe` gathers shared depth (PR 11's
    depth memo), per-class drain EWMAs (PR 12's QosPolicy), and the
    stale-filtered live-member count (PR 14's heartbeat docs) through
    the existing memoized fail-open read paths, folds them into the
    controller, and publishes the result as the
    ``vrpms_fleet_desired_replicas`` gauge and the ``autoscale`` block
    on GET /api/debug/fleet. A store outage yields ``None`` inputs and
    the controller freezes the last-known value marked ``degraded`` —
    the solve path is never touched.
  * **safe scale-in** — :class:`ScaleInHandler` (POST
    /api/admin/scalein) picks the victim by claim-mix overlap (drain
    the replica whose hot tiers the survivors already have warm) and
    runs PR 15's checkpoint-drain against it: locally via
    ``start_drain``, or relayed to the victim's advertised address.
  * **churn hardening** — :func:`tick` (riding the replica heartbeat)
    watches ring membership; when it changes (and VRPMS_WARMUP says
    this deployment warms tiers), the tiers this replica newly owns
    pre-warm on a background thread via PR 11's warmup, so post-churn
    traffic meets warm caches instead of a compile storm.
    The heartbeat hook itself never touches the store: recommendation
    refreshes run on a dedicated observer thread, so the claim loop
    pays nothing for the controller (the <1% solve-path budget).

``VRPMS_AUTOSCALE=off`` removes all of it: no controller runs, the
scalein route 404s, and every pre-autoscale response stays
byte-identical.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler

import store
from service import obs
from service import jobs as jobs_mod
from service.helpers import read_json_body, respond_json
from vrpms_tpu import config
from vrpms_tpu.obs import log_event, spans
from vrpms_tpu.sched import autoscale as policy
from vrpms_tpu.sched import qos as qos_mod

enabled = policy.enabled

_lock = threading.Lock()
_controller: policy.Controller | None = None  # guarded-by: _lock
_prev_ring = None  # guarded-by: _lock
_last_scalein: dict | None = None  # guarded-by: _lock
_ticker: threading.Thread | None = None  # guarded-by: _lock
_ticker_stop: threading.Event | None = None  # guarded-by: _lock


def controller() -> policy.Controller:
    """The process controller singleton (hysteresis/cooldown state)."""
    global _controller
    with _lock:
        if _controller is None:
            _controller = policy.Controller()
        return _controller


def reset() -> None:
    """Forget controller + churn state and stop the observer thread
    (shutdown_scheduler calls this: a rebuilt service starts with fresh
    cooldowns and no phantom previous ring)."""
    global _controller, _prev_ring, _last_scalein, _ticker, _ticker_stop
    with _lock:
        _controller = None
        _prev_ring = None
        _last_scalein = None
        if _ticker_stop is not None:
            _ticker_stop.set()
        _ticker = None
        _ticker_stop = None


# -- heartbeat-registry hygiene ---------------------------------------------


def split_stale(members, infos, now=None) -> tuple[list, list]:
    """Partition a membership snapshot into (live, stale) replica ids:
    a member is STALE when its status doc's ``updatedAt`` is older than
    the lease window (VRPMS_LEASE_S) — a crashed replica whose
    heartbeat row has not yet TTL-expired must not inflate the live
    count or the fleet aggregates. Members without a doc (or a doc
    without a timestamp) count live: absence of evidence must not
    shrink the fleet."""
    now = time.time() if now is None else now
    window = max(0.0, float(config.get("VRPMS_LEASE_S")))
    live, stale = [], []
    for rid in members:
        doc = (infos or {}).get(rid) or {}
        at = doc.get("updatedAt")
        if window > 0 and isinstance(at, (int, float)) and now - at > window:
            stale.append(rid)
        else:
            live.append(rid)
    return live, stale


# -- recommendation ---------------------------------------------------------


def _gather() -> dict | None:
    """The controller's input bundle, every field through an existing
    memoized/fail-open read: shared depth + class split (the depth
    memo), membership + docs (the fleet memo, stale-filtered), drain
    EWMAs (QosPolicy, in-process). None = the store is unreadable and
    no fresh memo exists — the controller must freeze, not guess."""
    per = max(1, int(config.get("VRPMS_QUEUE_MAX_INFLIGHT")))
    job_seconds = 1.0
    if jobs_mod.dist_queue_enabled():
        rep = jobs_mod._replica  # peek — observing must not start a loop
        try:
            qs = rep.store if rep is not None else store.get_queue_store()
        except Exception:
            return None
        depth = jobs_mod._shared_depth(qs)
        if depth is None:
            return None
        classes = jobs_mod._shared_class_depths(qs)
        members = 1
        fleet = jobs_mod._fleet_infos(qs)
        if fleet is not None:
            live, _stale = split_stale(fleet[0], fleet[1])
            members = max(1, len(live))
        elif rep is not None and rep.ring() is not None:
            # registry unreadable but depth memo fresh: the cached ring
            # is the best live-membership estimate (display-only — the
            # desired count depends on backlog, not member count)
            members = max(1, len(rep.ring().members))
        if rep is not None:
            job_seconds = rep.job_seconds_ewma()
    else:
        # local queue: a fleet of one, but the recommendation still
        # tells an operator when one box stops being enough
        s = jobs_mod._scheduler
        depth = sum(s.queues().values()) if s is not None else 0
        classes = None
        if s is not None and jobs_mod.qos_enabled():
            try:
                classes = {}
                for depths in s.queues_by_class().values():
                    for cls, n in depths.items():
                        classes[cls] = classes.get(cls, 0) + n
            except Exception:
                classes = None
        members = 1
    class_seconds = None
    if jobs_mod.qos_enabled():
        pol = jobs_mod.get_qos_policy()
        class_seconds = {c: pol.class_seconds(c) for c in qos_mod.CLASSES}
    return {
        "depth": depth,
        "classDepths": classes,
        "classSeconds": class_seconds,
        "jobSeconds": job_seconds,
        "members": members,
        "perReplica": per,
    }


def observe(now=None) -> dict:
    """One controller observation: gather signals, fold, publish.
    Never raises — any gathering failure is a ``None`` input and the
    last-known recommendation survives marked degraded."""
    ctl = controller()
    now = time.monotonic() if now is None else now
    try:
        inputs = _gather()
    except Exception:
        inputs = None
    rec = ctl.observe(inputs, now)
    decision = rec.get("decision")
    if decision in ("up", "down"):
        obs.AUTOSCALE_TOTAL.labels(event=decision).inc()
        log_event(
            "autoscale.decision",
            decision=decision,
            desired=rec.get("desired"),
            workSeconds=rec.get("workSeconds"),
            members=rec.get("members"),
        )
    elif decision == "frozen":
        obs.AUTOSCALE_TOTAL.labels(event="frozen").inc()
    return rec


def fleet_block() -> dict:
    """The ``autoscale`` block GET /api/debug/fleet publishes: the
    recommendation (inputs, decision, cooldown state), refreshed by the
    poll itself so an HPA needs no replica tick to have run; plus the
    last scale-in decision, for the runbook's audit trail."""
    rec = observe()
    with _lock:
        last = dict(_last_scalein) if _last_scalein else None
    if last is not None:
        rec["lastScalein"] = last
    return rec


def _ticker_loop(stop: threading.Event) -> None:
    """Dedicated observer thread: refresh the recommendation at
    heartbeat cadence so the gauge stays live without debug polls. The
    store reads (and their latency) happen HERE, never on the claim
    loop — the controller's cost to the solve path is a thread-alive
    check. Exits when reset() signals or the switch turns off."""
    while not stop.is_set():
        if not enabled():
            return  # next tick() starts a fresh ticker if re-enabled
        try:
            observe()
        except Exception:
            pass
        stop.wait(max(0.2, float(config.get("VRPMS_HEARTBEAT_S"))))


def _ensure_ticker() -> None:
    global _ticker, _ticker_stop
    with _lock:
        if _ticker is not None and _ticker.is_alive():
            return
        _ticker_stop = threading.Event()
        _ticker = threading.Thread(
            target=_ticker_loop,
            args=(_ticker_stop,),
            name="vrpms-autoscale",
            daemon=True,
        )
        _ticker.start()


def tick() -> None:
    """Replica-heartbeat hook (service.jobs wires it next to the
    subscription tick): ensure the observer thread is running and watch
    the (in-memory) ring snapshot for membership churn. Does no store
    I/O itself and never raises — the claim loop must not care."""
    if not enabled():
        return
    _ensure_ticker()
    try:
        _watch_churn()
    except Exception:
        pass


# -- churn hardening --------------------------------------------------------


def ladder_tokens() -> list[tuple[str, str]]:
    """``[("NxV" shape, ring token)]`` over the tier-ladder warm shapes
    — the universe churn-hardening reasons over. Instances pad through
    the SAME tiers.maybe_pad path requests take, so the tokens are
    exactly the ones traffic routes by."""
    from service import warmup as warmup_mod

    spec = warmup_mod.tier_warm_shapes()
    if not spec:
        return []
    from vrpms_tpu.core import tiers
    from vrpms_tpu.io.synth import synth_cvrp

    out = []
    for n, v, _pop in warmup_mod.parse_shapes(spec):
        inst = tiers.maybe_pad(synth_cvrp(n, v, seed=0))
        tok = jobs_mod.ring_token("vrp", inst)
        if tok is not None:
            out.append((f"{n}x{v}", tok))
    return out


def inherited_spec(prev_ring, new_ring, rid: str) -> str:
    """The warmup spec for exactly the tier-ladder tiers ``rid`` owns
    on the new ring but not the old one — what the churn-hardening
    pre-warm compiles, and what the ring-churn property test asserts
    equals the inherited arcs."""
    pairs = ladder_tokens()
    if not pairs:
        return ""
    by_tok = {tok: shape for shape, tok in pairs}
    toks = policy.inherited_tokens(
        prev_ring, new_ring, rid, [t for _, t in pairs]
    )
    return ",".join(by_tok[t] for t in toks)


def _launch_warmup(spec: str) -> None:
    """Background-compile the inherited tiers (the monkeypatch seam the
    tests and the bench intercept). owned_only re-checks ownership at
    compile time — membership may move again before the thread runs."""
    from service import warmup as warmup_mod

    warmup_mod.start_background_warmup(
        warmup_mod.warmup, spec, ("sa",), False, True
    )


def _watch_churn() -> None:
    """Compare successive ring snapshots; on a membership change,
    pre-warm whatever this replica inherited. First observation is a
    no-op (boot warmup already covers the initial arcs). Rides the
    VRPMS_WARMUP switch: a deployment that does not warm tiers at boot
    has no warm tiers to inherit, so churn compiles nothing either —
    membership-churning test fleets never pay compile storms."""
    if not str(config.get("VRPMS_WARMUP") or "").strip():
        return
    rep = jobs_mod._replica
    if rep is None:
        return
    ring = rep.ring()
    if ring is None:
        return
    global _prev_ring
    with _lock:
        prev, _prev_ring = _prev_ring, ring
    if prev is None or set(prev.members) == set(ring.members):
        return
    spec = inherited_spec(prev, ring, rep.replica_id)
    if not spec:
        return
    obs.AUTOSCALE_TOTAL.labels(event="churn_warm").inc()
    log_event(
        "autoscale.churn_warm",
        spec=spec,
        members=len(ring.members),
        was=len(prev.members),
    )
    _launch_warmup(spec)


# -- safe scale-in ----------------------------------------------------------


def _candidates() -> tuple[dict, str]:
    """(status docs of live candidates, self id) — the stale-filtered
    registry view with this process's doc overlaid live, the input
    :func:`vrpms_tpu.sched.autoscale.choose_victim` scores."""
    self_id = jobs_mod.replica_id()
    docs: dict = {}
    if jobs_mod.dist_queue_enabled():
        rep = jobs_mod._replica
        fleet = None
        try:
            qs = rep.store if rep is not None else store.get_queue_store()
            fleet = jobs_mod._fleet_infos(qs)
        except Exception:
            fleet = None
        if fleet is not None:
            live, _stale = split_stale(fleet[0], fleet[1])
            for rid in live:
                docs[rid] = dict((fleet[1] or {}).get(rid) or {})
    docs[self_id] = dict(docs.get(self_id) or {}, **jobs_mod.replica_info())
    return docs, self_id


def scalein_preview() -> dict:
    """Victim selection dry-run (the GET surface and the runbook's
    what-if): candidates scored by survivor warm-tier coverage, the
    chosen victim, nothing drained."""
    docs, self_id = _candidates()
    victim, scores = policy.choose_victim(docs)
    return {"victim": victim, "scores": scores, "self": self_id}


def _relay_drain(addr: str) -> dict | None:
    """POST the victim's own drain endpoint (PR 15's checkpoint-drain
    runs there, against its leases). None on any failure — the caller
    answers 502 and nothing was half-drained."""
    import urllib.request

    try:
        req = urllib.request.Request(
            f"http://{addr}/api/admin/drain", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=2.0) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except Exception:
        return None


class ScaleInHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """POST /api/admin/scalein — safe scale-in: pick the victim by
    claim-mix overlap (drain the replica whose hot tiers the survivors
    already have warm) and run the checkpoint-drain against it — zero
    lost jobs, zero burned attempts. Body (optional):
    ``{"replicaId": ..., "graceS": ...}`` forces a victim / sets the
    local drain grace (a relayed victim drains with its own configured
    grace). 202 with the victim + drain state; 409 when no drainable
    victim exists (the last replica is never drained); 502 when the
    victim cannot be reached. GET previews the decision without
    draining anything."""

    def do_POST(self):
        obs.begin_request_obs(self)
        try:
            self._scalein()
        finally:
            obs.end_request_obs(self)

    def _scalein(self):
        content = read_json_body(self)
        if content is None:
            return  # read_json_body already wrote the 400 envelope
        docs, self_id = _candidates()
        victim, scores = policy.choose_victim(docs)
        target = content.get("replicaId")
        if target is not None:
            if target not in docs:
                respond_json(self, 404, {
                    "success": False,
                    "errors": [{
                        "what": "Not found",
                        "reason": f"replica {target!r} is not a live "
                                  "fleet member",
                    }],
                })
                return
            victim = target
        if victim is None:
            respond_json(self, 409, {
                "success": False,
                "errors": [{
                    "what": "Conflict",
                    "reason": "no drainable victim: scale-in never "
                              "drains the last live replica",
                }],
                "scores": scores,
            })
            return
        grace = content.get("graceS")
        with spans.span("fleet.scalein", victim=victim):
            if victim == self_id:
                state = jobs_mod.start_drain(
                    None if grace is None else float(grace)
                )
                result = {"victim": victim, "local": True, "drain": state}
            else:
                addr = (docs.get(victim) or {}).get("addr")
                peer = _relay_drain(addr) if addr else None
                if peer is None:
                    respond_json(self, 502, {
                        "success": False,
                        "errors": [{
                            "what": "Bad gateway",
                            "reason": (
                                f"victim {victim!r} unreachable"
                                if addr
                                else f"victim {victim!r} advertises no "
                                     "address"
                            ),
                        }],
                        "scores": scores,
                    })
                    return
                result = {
                    "victim": victim,
                    "relayed": True,
                    "drain": peer.get("drain"),
                }
        global _last_scalein
        with _lock:
            _last_scalein = dict(result, at=time.time(), scores=scores)
        obs.AUTOSCALE_TOTAL.labels(event="scalein").inc()
        log_event(
            "autoscale.scalein",
            victim=victim,
            local=bool(result.get("local")),
            coverage=(scores.get(victim) or {}).get("coverage"),
        )
        respond_json(self, 202, {
            "success": True, "scalein": result, "scores": scores,
        })

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            preview = scalein_preview()
            with _lock:
                last = dict(_last_scalein) if _last_scalein else None
            payload: dict = {"success": True, "scalein": preview}
            if last is not None:
                payload["last"] = last
            respond_json(self, 200, payload)
        finally:
            obs.end_request_obs(self)


# the desired-replica gauge rides the scrape like every other provider;
# with the switch off it publishes nothing (pre-autoscale /metrics
# unchanged beyond the series registration itself)
obs.set_desired_replicas_provider(
    lambda: controller().desired() if enabled() else None
)
