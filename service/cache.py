"""Content-addressed solution cache: exact-hit serving + near-hit seeding.

Million-user traffic repeats — same city, same depot, overlapping
customer sets — yet every repeat used to pay a full metaheuristic
solve. This module turns the pieces the service already has (tier
padding canonicalizes instance shape, the warm-start machinery seeds
solvers from a prior tour, the store seam persists documents) into a
cache keyed on CONTENT, not on request names:

  * **fingerprint** — `vrpms_tpu.core.tiers.fingerprint(inst)`: a
    SHA-256 of the padded tier tensors. Equal instances hash equal no
    matter how the request spelled them.
  * **exact key** — fingerprint + problem + algorithm + every
    result-relevant option (seed, budgets, weights, polish knobs) +
    the original-id mapping + the auth scope. An exact hit serves the
    cached routes/cost/certificate at store-read latency, bypassing
    the admission queue and the solver entirely (`cacheHit: true`).
  * **family key** — dataset content (full matrix + locations) + fleet
    config + problem + auth scope, WITHOUT the customer subset or
    solver options. One keyed read returns every cached solution over
    the same data, so near hits (small Hamming distance on the
    customer set) and legacy `warmStart` retrieval are the same
    indexed lookup — one warm-start code path, not two.

A near hit repairs the cached giant tour via the separator encoding
(strip dropped customers, greedy-insert new ones at their cheapest
position) and seeds the solver through the existing warm-start
machinery instead of NN construction. For implicit near hits the seed
application is DEFERRED to solo dispatch (solve_prepared): a job that
would merge into a vmapped micro-batch keeps its batch — the batched
launch has no per-job init, and trading a K-way launch for K seeded
solo solves would undo PR 2.

Everything is best-effort behind the `store.base` seam, wrapped by
ResilientDatabase for network backends: a cache outage degrades to
solving (the lookup fails fast under the shared breaker), never to
failing. `VRPMS_CACHE=off` disables the whole module — responses are
then byte-identical to the pre-cache service. `VRPMS_CACHE_NEAR` caps
the Hamming distance an implicit near hit may bridge (default 4;
0 disables near seeding; explicit `warmStart` requests accept the
closest family entry at any distance, like the legacy checkpoint did).
"""

from __future__ import annotations

import copy
import hashlib
import json

import numpy as np

from service import obs
from store.base import cache_enabled
from vrpms_tpu import config
from vrpms_tpu.core import tiers
from vrpms_tpu.core.delta import repair_perm, strip_order  # noqa: F401 (re-exported: service.solve consumes solution_cache.strip_order)
from vrpms_tpu.obs import log_event, spans

#: request options that parameterize the solver program or its result —
#: the exact-hit key must cover everything that can change the response
#: bytes (includeStats/profile are deliberately absent: they only add
#: volatile telemetry, which is stripped from stored entries, so a
#: stats-requesting solve can still warm the cache for plain requests)
_KEY_OPTS = (
    "backend", "seed", "iteration_count", "population_size", "time_limit",
    "makespan_weight", "local_search", "local_search_pool", "ils_rounds",
    "ils_reseed", "islands", "migrate_every", "migrants", "warm_start",
)

#: stored-entry keys stripped before serving comparisons / persistence
_VOLATILE_KEYS = ("stats", "degraded", "cacheHit")


def near_limit() -> int:
    """Max Hamming distance (|A symmetric-difference B| over customer-id
    sets) an implicit near hit may bridge; 0 disables near seeding."""
    return max(0, config.get("VRPMS_CACHE_NEAR"))


def _warm_supported(prep) -> bool:
    """Which (problem, algorithm, opts) combinations consume a warm
    seed — the ONE predicate both the legacy warmStart option and
    near-hit seeding obey (mirrors the historical per-problem rules:
    bf is exact and has no seed hook; TSP islands only wire an initial
    incumbent for ACO)."""
    if prep.problem == "vrp":
        return prep.algorithm != "bf"
    return prep.algorithm == "aco" or (
        prep.algorithm in ("sa", "ga") and not prep.opts.get("islands")
    )


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------


def _family_key(prep, locations, matrix) -> str:
    """Hash of everything that survives a customer-subset change: the
    FULL dataset content, the fleet/start config, the problem kind, and
    the auth scope (tenants must never share entries — the raw token is
    scoped like PR 3's degraded cache keys)."""
    h = hashlib.sha256()
    h.update(b"family:v1:")
    h.update(repr(prep.params.get("auth") or "").encode())
    h.update(prep.problem.encode())
    arr = np.asarray(matrix, dtype=np.float64)
    h.update(repr(arr.shape).encode())
    h.update(arr.tobytes())
    h.update(json.dumps(locations, sort_keys=True, default=str).encode())
    if prep.problem == "vrp":
        cfg = {
            "capacities": prep.params.get("capacities"),
            "startTimes": prep.params.get("start_times"),
        }
    else:
        cfg = {
            "startNode": prep.params.get("start_node"),
            "startTime": prep.params.get("start_time"),
        }
    cfg["timeSliceDuration"] = prep.opts.get("time_slice_duration")
    h.update(json.dumps(cfg, sort_keys=True, default=str).encode())
    return h.hexdigest()


def _ensure_family(prep) -> str:
    """Compute (once) and return the request's family key; the dataset
    refs ride prep.cache until first use."""
    cache = prep.cache
    if "family" not in cache:
        locations, matrix = cache.pop("_family_args")
        cache["family"] = _family_key(prep, locations, matrix)
    return cache["family"]


def _request_key(prep, fingerprint: str) -> str:
    """The exact-hit key. The instance fingerprint covers the padded
    tensor content; the original-id list and anchor must join it
    because two different subsets of duplicate locations can produce
    identical tensors while their responses (tours of ORIGINAL ids)
    differ."""
    opts = {
        k: prep.opts.get(k) for k in _KEY_OPTS
        if prep.opts.get(k) is not None
    }
    ga = {
        k: v for k, v in sorted((prep.ga_params or {}).items())
        if v is not None
    }
    # ids ride the payload as-is: json keeps 3 and "3" distinct (and
    # default=str covers exotic id types), while coercing with int()
    # would both collide those spellings and 400 requests whose stored
    # datasets use non-numeric ids the pre-cache service accepted
    payload = {
        "v": 1,
        "problem": prep.problem,
        "algorithm": prep.algorithm,
        "auth": prep.params.get("auth") or "",
        "fingerprint": fingerprint,
        "ids": list(prep.orig_ids),
        "anchor": prep.anchor_id,
        "opts": opts,
        "ga": ga,
    }
    return hashlib.sha256(
        json.dumps(payload, sort_keys=True, default=str).encode()
    ).hexdigest()


# ---------------------------------------------------------------------------
# Near-hit repair: cached giant tour -> warm permutation for THIS request
# ---------------------------------------------------------------------------


def _repair_perm(prep, routes):
    """Strip-and-insert repair over the separator encoding — the shared
    vrpms_tpu.core.delta.repair_perm, bound to this request's active
    ids and its prepared instance's slice-0 durations (active indexing
    — the padded tensor's real prefix). `routes` hold ORIGINAL location
    ids from the prior solution; the result is the int32 permutation of
    active positions 1..n-1 the warm-start machinery consumes, or None
    when nothing survives to seed from."""
    return repair_perm(
        routes, prep.orig_ids, np.asarray(prep.inst.durations)[0]
    )


def _pick_seed(prep, rows, explicit: bool):
    """Key of the best family entry to seed from: same problem,
    overlapping customer set, ranked by (Hamming distance, cost).
    Implicit near hits respect the VRPMS_CACHE_NEAR distance cap; an
    explicit warmStart request takes the closest entry at any distance
    (the legacy checkpoint semantics). Rows may carry the ranking
    fields nested under 'entry' (memory backend) or flat (the slim
    supabase projection); the caller hydrates the winner by key."""
    current = set(prep.orig_ids[1:])
    limit = None if explicit else near_limit()
    best_rank, best_key = None, None
    for row in rows:
        entry = row.get("entry") or row
        if entry.get("problem") != prep.problem:
            continue
        cached = set(entry.get("customers") or [])
        if not cached & current:
            continue
        dist = len(cached ^ current)
        if limit is not None and dist > limit:
            continue
        try:
            cost = float(entry.get("cost"))
        except (TypeError, ValueError):
            cost = float("inf")
        rank = (dist, cost)
        if row.get("key") is not None and (
            best_rank is None or rank < best_rank
        ):
            best_rank, best_key = rank, row["key"]
    return best_key


# ---------------------------------------------------------------------------
# The request-path hooks
# ---------------------------------------------------------------------------


def _legacy_warm(prep, database) -> None:
    """The pre-cache warmStart retrieval: the (owner, solutionName)
    checkpoint row. Still the fallback when the cache is off or the
    family index is cold (fresh process, evicted entries) — the
    checkpoint table is keep-best and persists independently."""
    from service.solve import _warm_perm

    state = database.get_warmstart(prep.params["name"])
    prep.warm = _warm_perm(state, prep.orig_ids, prep.problem)


#: explicit warm-start spec keys — a request's `warmStart` may be an
#: OBJECT naming its seed source instead of the legacy boolean
_RESOLVE_KEYS = ("tour", "jobId", "fingerprint")


def validate_warm_spec(spec: dict) -> None:
    """Shape-validate an explicit warmStart object; raises ValueError
    with the 400-envelope wording. Exposed so the resolve endpoint can
    reject a malformed spec BEFORE cancelling the predecessor job
    (service.jobs._parse_submit) — _attach_resolve re-runs it at
    prepare time for every other intake path."""
    unknown = [k for k in spec if k not in _RESOLVE_KEYS]
    if unknown:
        raise ValueError(
            f"unknown warmStart key(s) {unknown}; a warmStart object "
            f"takes one of {list(_RESOLVE_KEYS)}"
        )
    if not any(spec.get(k) is not None for k in _RESOLVE_KEYS):
        raise ValueError(
            f"a warmStart object must carry one of {list(_RESOLVE_KEYS)}"
        )
    tour = spec.get("tour")
    if tour is not None and (not isinstance(tour, list) or not tour):
        raise ValueError(
            "warmStart.tour must be a non-empty list (routes of "
            "location ids, or one flat visit order)"
        )
    job_id = spec.get("jobId")
    if job_id is not None and (not isinstance(job_id, str) or not job_id):
        raise ValueError("warmStart.jobId must be a job id string")
    fp = spec.get("fingerprint")
    if fp is not None and (not isinstance(fp, str) or not fp):
        raise ValueError(
            "warmStart.fingerprint must be an instance fingerprint "
            "string (stats.cache.fingerprint of a prior solve)"
        )


def _routes_from_job_record(record, problem: str):
    """Routes (original ids) out of a terminal job record's result
    message, or None when the record cannot seed (not done, wrong
    problem, no tours)."""
    if not isinstance(record, dict) or record.get("status") != "done":
        return None
    rec_problem = record.get("problem")
    if rec_problem is not None and rec_problem != problem:
        return None
    msg = record.get("message")
    if not isinstance(msg, dict):
        return None
    if problem == "vrp":
        vehicles = msg.get("vehicles")
        if not isinstance(vehicles, list):
            return None
        return [
            v["tour"][1:-1]
            for v in vehicles
            if isinstance(v, dict) and isinstance(v.get("tour"), list)
        ]
    tour = msg.get("vehicle")
    if not isinstance(tour, list):
        return None
    return [tour[1:-1]]


def _job_seed_record(job_id: str, database):
    """A prior job's record for seeding: the live in-process registry
    first (a just-cancelled predecessor's result is authoritative there
    the instant its done_event fires, before the terminal store persist
    settles), then the store's record. Best-effort — a miss degrades to
    an unseeded solve."""
    try:
        from service.jobs import get_live_job

        job = get_live_job(job_id)
        if (
            job is not None
            and job.done_event.is_set()
            and isinstance(job.result, dict)
        ):
            return {
                "status": job.status,
                "problem": (job.payload or {}).get("problem"),
                "message": job.result,
            }
    except Exception:
        pass
    return database.get_job_seed(job_id)


def _resolve_seed_routes(prep, spec: dict, database):
    """(routes, seed_source) for an explicit warm-start spec, trying the
    spec's sources in fidelity order: an inline tour needs no store at
    all; a jobId reads the job record (live registry, then store —
    INDEPENDENT of VRPMS_CACHE, job records are not cache entries); a
    fingerprint needs the cache family index and so only resolves with
    the cache on."""
    tour = spec.get("tour")
    if tour is not None:
        routes = tour if isinstance(tour[0], list) else [tour]
        return routes, "tour"
    job_id = spec.get("jobId")
    if job_id is not None:
        if database is not None:
            routes = _routes_from_job_record(
                _job_seed_record(job_id, database), prep.problem
            )
            if routes:
                return routes, "job"
        return None, "miss"
    fp = spec.get("fingerprint")
    if fp is not None:
        if database is not None and cache_enabled():
            rows = database.get_cache_family(_ensure_family(prep))
            for row in rows:
                entry = row.get("entry") or row
                if (
                    entry.get("fingerprint") == fp
                    and entry.get("problem") == prep.problem
                    and row.get("key") is not None
                ):
                    full = (
                        database.get_cached_solution(row["key"]) or {}
                    ).get("entry") or {}
                    if full.get("routes"):
                        return full["routes"], "fingerprint"
        return None, "miss"
    return None, "miss"


def _attach_resolve(prep, spec: dict, locations, matrix, database) -> None:
    """Resolve an EXPLICIT warm-start spec (warmStart as an object) —
    the dynamic re-solve seed path. Runs whether or not the solution
    cache is enabled: an inline tour and a jobId must keep seeding with
    VRPMS_CACHE=off (only the fingerprint source rides the cache's
    family index). Malformed specs raise ValueError, which the prepare
    wrappers turn into the contract's 400 Data-error envelope; a
    well-formed spec that simply fails to resolve degrades to an
    unseeded solve, disclosed in stats.resolve and the
    vrpms_resolve_total{seed_source="miss"} counter."""
    validate_warm_spec(spec)
    if cache_enabled() and database is not None:
        # cache bookkeeping: the outcome is the resolve path's own
        # (never exact — an explicitly seeded request is never SERVED
        # from the index, because its seed content can drift under an
        # unchanged key), but the solved result still WRITES a family
        # entry, so later rolling-horizon requests can near-hit-seed
        # from this horizon's solution without an explicit spec
        fingerprint = tiers.fingerprint(prep.inst)
        prep.cache = {
            "outcome": "resolve",
            "fingerprint": fingerprint,
            "key": _request_key(prep, fingerprint),
            "_family_args": (locations, matrix),
        }
    source = "miss"
    with spans.span("resolve", op="seed") as sp:
        routes = None
        if _warm_supported(prep):
            try:
                routes, source = _resolve_seed_routes(prep, spec, database)
            except ValueError:
                raise
            except Exception as exc:
                # a junk store row or record shape must degrade to an
                # unseeded solve, never fail the request it fronts
                routes, source = None, "miss"
                log_event(
                    "resolve.error",
                    error=f"{type(exc).__name__}: {exc}",
                )
        if routes:
            prep.warm = _repair_perm(prep, routes)
        if prep.warm is None:
            source = "miss"
        if sp is not None:
            sp.set(seedSource=source, seeded=prep.warm is not None)
    prep.resolve = {
        "seedSource": source,
        "seeded": prep.warm is not None,
    }
    if spec.get("jobId") is not None:
        prep.resolve["jobId"] = spec["jobId"]
    obs.RESOLVE.labels(seed_source=source).inc()
    log_event(
        "resolve.seed",
        seedSource=source,
        seeded=prep.warm is not None,
        jobId=spec.get("jobId"),
    )


def attach(prep, locations, matrix, database) -> None:
    """Consult the cache for a prepared request (the one choke point,
    called at the tail of prepare_vrp/prepare_tsp on the HTTP thread).

    Outcomes, in order of preference:
      exact — identical fingerprint + options: `prep.cached` holds the
              servable response; submit paths return it without ever
              enqueueing (and solve_prepared serves it inline when the
              scheduler is off). Requests asking for includeStats or
              profile solve anyway — unseeded, so the result matches a
              plain twin that also solved unseeded bit for bit — with
              the same "exact" outcome disclosed in stats.cache (and
              store_result leaves the existing entry untouched).
      warm  — explicit warmStart: seeded immediately from the closest
              family entry (falling back to the legacy checkpoint row).
      near  — implicit: a small-Hamming-distance family entry rides
              `prep.cache['seed']`, applied only at solo dispatch.
      miss  — nothing usable; the solve proceeds untouched.

    With VRPMS_CACHE=off nothing here runs except the legacy warmStart
    path — responses stay byte-identical to the pre-cache service.
    """
    spec = prep.opts.get("warm_start")
    if isinstance(spec, dict):
        # explicit seed source (dynamic re-solve): its own path, live
        # with or without the cache — an inline tour needs no store at
        # all, so this runs BEFORE the database/None early-out
        _attach_resolve(prep, spec, locations, matrix, database)
        return
    wants_warm = bool(spec) and _warm_supported(prep)
    if database is None:
        return
    if not cache_enabled():
        if wants_warm:
            _legacy_warm(prep, database)
            obs.WARMSTART.labels(
                outcome="hit" if prep.warm is not None else "miss"
            ).inc()
        return
    try:
        outcome = _lookup(prep, locations, matrix, database, wants_warm)
    except Exception as exc:
        # the module contract — a cache problem degrades to solving,
        # never to failing — must hold above the store seam too: a
        # malformed entry document (migration script, truncated jsonb,
        # junk customers list) raises HERE, not in store I/O, and the
        # request it fronts would solve fine without us
        prep.cached = None
        if not isinstance(prep.cache, dict):
            prep.cache = {}
        prep.cache.pop("seed", None)
        prep.cache["outcome"] = outcome = "miss"
        log_event(
            "cache.error", op="lookup",
            error=f"{type(exc).__name__}: {exc}",
        )
        if wants_warm:
            if prep.warm is None:
                try:
                    _legacy_warm(prep, database)
                except Exception:
                    prep.warm = None
            if prep.warm is not None:
                prep.cache["outcome"] = outcome = "warm"
    obs.CACHE_LOOKUPS.labels(outcome=outcome).inc()
    if wants_warm:
        # the checkpoint feature's measurable hit rate, source-agnostic
        obs.WARMSTART.labels(
            outcome="hit" if prep.warm is not None else "miss"
        ).inc()


def _lookup(prep, locations, matrix, database, wants_warm: bool) -> str:
    """The fallible body of attach(): key computation, store reads,
    seed selection. Returns the lookup outcome."""
    with spans.span("store.cache", op="lookup") as sp:
        fingerprint = tiers.fingerprint(prep.inst)
        key = _request_key(prep, fingerprint)
        # the family key hashes the FULL dataset matrix + locations —
        # deliberately lazy (_ensure_family): the exact-hit fast path
        # and seed-less misses never need it, and it would dominate the
        # store-read-latency budget on large instances
        prep.cache = {
            "fingerprint": fingerprint,
            "key": key,
            "outcome": "miss",
            "_family_args": (locations, matrix),
        }
        servable = not (
            prep.opts.get("include_stats")
            or prep.opts.get("profile")
            or prep.opts.get("warm_start")
        )
        # exact lookup first: ONE keyed (primary-key) read — the family
        # scan only runs when a seed could actually be consumed, so the
        # hottest path never transfers a family's worth of documents
        entry = None
        if not wants_warm:
            row = database.get_cached_solution(key)
            entry = (row or {}).get("entry")
        outcome = "miss"
        if entry is not None and entry.get("result") is not None:
            if servable:
                prep.cached = copy.deepcopy(entry["result"])
            # else: includeStats/profile — the solve runs for real
            # telemetry, unseeded so it reproduces the plain solve;
            # the stats disclose the lookup found an exact entry it
            # couldn't serve
            outcome = "exact"
        elif wants_warm or (near_limit() > 0 and _warm_supported(prep)):
            rows = database.get_cache_family(_ensure_family(prep))
            winner = _pick_seed(prep, rows, explicit=wants_warm)
            if sp is not None:
                sp.set(entries=len(rows))
            seed = None
            if winner is not None:
                # hydrate the ONE winning row by key: the family scan
                # returns slim ranking rows (no routes on the network
                # backends), and the keyed read marks the row as USED
                # for the memory tier's LRU — scanned-but-unused rows
                # keep their recency
                full = (database.get_cached_solution(winner) or {}).get(
                    "entry"
                ) or {}
                if full.get("routes"):
                    seed = {
                        "routes": full["routes"],
                        "cost": full.get("cost"),
                    }
            if seed is not None:
                if wants_warm:
                    prep.warm = _repair_perm(prep, seed["routes"])
                    if prep.warm is not None:
                        outcome = "warm"
                else:
                    prep.cache["seed"] = seed
                    outcome = "near"
        if wants_warm and prep.warm is None:
            _legacy_warm(prep, database)
            if prep.warm is not None:
                outcome = "warm"
        prep.cache["outcome"] = outcome
        if sp is not None:
            sp.set(outcome=outcome, fingerprint=fingerprint[:16])
    return outcome


def apply_deferred_seed(prep) -> None:
    """Materialize an implicit near-hit seed at SOLO dispatch time.

    Called by solve_prepared just before the solver runs: only jobs
    that did NOT merge into a micro-batch reach it, so a near hit never
    costs a request its batched launch. The repair happens here (not at
    lookup) for the same reason — no point paying it for a job the
    batcher will absorb."""
    if prep.warm is not None or not prep.cache:
        return
    seed = prep.cache.get("seed")
    if not seed:
        return
    try:
        prep.warm = _repair_perm(prep, seed["routes"])
    except Exception as exc:
        # a junk cached tour must not fail the solve it would have seeded
        prep.warm = None
        log_event(
            "cache.error", op="seed",
            error=f"{type(exc).__name__}: {exc}",
        )


def mark_trivial(prep) -> dict:
    """Contract uniformity for trivial zero-customer responses: they
    short-circuit before attach() runs, but should carry `cacheHit`
    exactly when solved responses would (cache enabled + a store) so
    clients can read the key unconditionally."""
    result = dict(prep.trivial)
    if cache_enabled() and prep.database is not None:
        result["cacheHit"] = False
    return result


def serve_hit(prep) -> dict:
    """Serve an exact hit: a deep copy of the cached response, marked
    `cacheHit: true`, honest about degraded data reads. The solver, the
    admission queue, and the checkpoint write are all bypassed — the
    whole request costs its store reads."""
    # attach() already deep-copied the entry off the store's live row,
    # and prep is per-request, so mutating in place is safe — a second
    # copy would be pure overhead on the store-read-latency hot path
    result = prep.cached
    result["cacheHit"] = True
    obs.CACHE_SOLVES_AVOIDED.inc()
    log_event(
        "cache.hit",
        problem=prep.problem,
        algorithm=prep.algorithm,
        fingerprint=prep.cache["fingerprint"][:16],
    )
    if getattr(prep.database, "degraded", False):
        result["degraded"] = True
    return result


def store_result(prep, result, routes, cost) -> dict:
    """Annotate + persist a solved result (the finish_vrp/finish_tsp
    tail, so solo, batched, sync, and async paths all land here).

    `routes` are the decoded routes in ORIGINAL location ids; `cost` is
    the penalized solver objective (comparable across entries of one
    customer set, like the warm-start checkpoint stores). The persisted
    entry strips volatile keys (stats/degraded/cacheHit) so an exact
    hit can serve any later identical request byte-identically."""
    if result is None or not prep.cache:
        return result
    result["cacheHit"] = False
    stats = result.get("stats")
    if isinstance(stats, dict):
        stats["cache"] = {
            "fingerprint": prep.cache.get("fingerprint"),
            "lookup": prep.cache.get("outcome", "miss"),
            "seeded": bool(
                prep.warm is not None
                and prep.cache.get("outcome") in ("near", "warm", "resolve")
            ),
        }
    if prep.cache.get("outcome") == "exact" or "key" not in prep.cache:
        # exact: the canonical entry already exists (this solve ran only
        # for fresh telemetry) and re-writing could flap the served
        # result if the original solve was seeded and this one
        # deliberately not; no key: the lookup failed before the keys
        # were computed, so there is nothing to index the entry under
        return result
    try:
        entry = {
            "problem": prep.problem,
            "algorithm": prep.algorithm,
            "fingerprint": prep.cache["fingerprint"],
            "customers": sorted(prep.orig_ids[1:], key=repr),
            "routes": routes,
            "cost": float(cost),
            "result": {
                k: v for k, v in result.items() if k not in _VOLATILE_KEYS
            },
        }
        with spans.span("store.cache", op="store"):
            prep.database.put_cached_solution(
                prep.cache["key"], _ensure_family(prep), entry
            )
    except Exception as exc:
        # best-effort persistence: the solved response is already in
        # hand and must ship whether or not the cache accepted the entry
        log_event(
            "cache.error", op="store",
            error=f"{type(exc).__name__}: {exc}",
        )
    return result
