"""Startup warmup: pre-trace the hot solver programs for expected shapes.

XLA compiles are keyed on array shapes and static config: chain batch B,
giant-tour length L (1 + customers + vehicles), eval mode, and block
length. A fresh process pays ~30 s per shape on TPU for the first solve
— far outside the north-star response budget (BASELINE.md config 3:
<10 s). With the persistent compile cache (vrpms_tpu.utils.
enable_compile_cache) plus this warmup, a restarted service answers its
first real request at steady-state latency: the warmup replays the
EXACT service dispatch (service.solve._solve_instance) on synthetic
instances of the declared shapes, so every program a matching request
needs is already in the in-process jit caches (and on disk for the next
restart).

Shape spec grammar (service.app --warmup / $VRPMS_WARMUP):

    "200x36,100x12x1024"   ->   (locations x vehicles [x population])

N is the LOCATION count — the durations-matrix size, depot included
(exactly what a request's matrix row count is) — NOT the customer
count; programs are keyed on L = 1 + (N-1) customers + V vehicles, so
an off-by-one here silently warms the wrong shape. Population defaults
to the service's own default for each algorithm.
Warmed programs per shape: the deadline-blocked SA anneal (512-sweep
blocks — every timeLimit request reuses these), constructive init, the
warm-SEEDED anneal variant (what near-hit and warmStart requests from
the solution cache dispatch — seeded init + cool schedule is its own
trace), the delta-descent polish for pool sizes 1 and 32 (localSearch /
localSearchPool / ilsRounds paths), and the exact final evaluation. A
request with no timeLimit and a novel iterationCount still compiles its
own single-block anneal once.
"""

from __future__ import annotations

import sys
import threading
import time

# tiers this process has actually warmed ("NxV" tokens, in completion
# order) — the readiness probe's `replica.tiersWarmed` surface, so an
# operator can see a replica's owned-and-ready slice of the ladder
_warmed_lock = threading.Lock()
_warmed: list[str] = []


def warmed_tiers() -> list[str]:
    with _warmed_lock:
        return list(_warmed)


def _note_warmed(token: str) -> None:
    with _warmed_lock:
        if token not in _warmed:
            _warmed.append(token)


def _owns_shape(inst, problem: str = "vrp") -> bool:
    """Ring-ownership check for a padded warmup instance: with the
    store-backed distributed queue active, each replica warms ONLY the
    tiers whose ring token hashes into its owned arc — the whole point
    of tier-affinity routing is that nobody pays compiles for tiers
    they will not serve. (Stolen off-arc jobs still compile lazily on
    first contact, exactly like any unwarmed shape.) Local-queue mode
    owns everything."""
    try:
        from service import jobs as jobs_mod

        if not jobs_mod.dist_queue_enabled():
            return True
        from vrpms_tpu.sched import ring as ring_mod

        token = jobs_mod.ring_token(problem, inst)
        if token is None:
            return True
        return jobs_mod.get_replica().owns_slot(ring_mod.slot(token))
    except Exception:
        return True  # warmup must never be blocked by ring plumbing


def _hot_first(prepared: list, problem: str = "vrp") -> list:
    """Arc-weighted warmup order: sort padded warmup instances by the
    replica's observed claim mix (Replica.claim_mix — a decayed counter
    of the ring tokens actually leased here), hottest tier first, so
    background warmup compiles the tiers the ring routes to THIS
    replica before the ladder's cold tail. Stable: unclaimed tiers and
    ties keep ladder order; local-queue mode (no claim mix to observe)
    is untouched."""
    try:
        from service import jobs as jobs_mod

        if not jobs_mod.dist_queue_enabled():
            return prepared
        # PEEK the replica singleton (the _dist_depth_provider pattern):
        # computing a read-only ordering must not lazily construct and
        # START the claim loop — a warmup on a cold process would begin
        # leasing shared-queue jobs before any tier is compiled
        rep = jobs_mod._replica
        if rep is None:
            return prepared
        mix = rep.claim_mix()
        if not mix:
            return prepared

        def heat(item) -> float:
            token = jobs_mod.ring_token(problem, item[-1])
            return mix.get(token, 0.0)

        return sorted(prepared, key=heat, reverse=True)
    except Exception:
        return prepared  # warmup must never be blocked by mix plumbing


def parse_shapes(spec: str) -> list[tuple[int, int, int | None]]:
    """'200x36,100x12x1024' -> [(200, 36, None), (100, 12, 1024)]."""
    shapes = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        dims = [int(x) for x in part.split("x")]
        if len(dims) == 2:
            shapes.append((dims[0], dims[1], None))
        elif len(dims) == 3:
            shapes.append((dims[0], dims[1], dims[2]))
        else:
            raise ValueError(
                f"warmup shape {part!r} is not NxV or NxVxPOP"
            )
    return shapes


def warmup(spec: str, algorithms: tuple[str, ...] = ("sa",), log=True,
           owned_only: bool = False) -> float:
    """Run the warmup for every shape in `spec`; returns seconds spent.
    `owned_only` skips shapes whose tier this replica does not own on
    the distributed-queue ring (the scale-out warmup contract)."""
    from service.solve import _run_solver
    from vrpms_tpu.io.synth import synth_cvrp

    t_start = time.perf_counter()
    # kick the native library builds (bnb + ngroute .so, a one-time g++
    # subprocess of up to ~2 min) here rather than against the first
    # exact request's timeLimit (ADVICE r4)
    from vrpms_tpu.native import load_bnb, load_ngroute

    load_bnb()
    load_ngroute()
    from vrpms_tpu.core import tiers

    # pad through the request path's canonicalization (identity when
    # tiering is off): the warmed traces must be the PADDED ones the
    # prepared requests actually run — padded up front so the claim-mix
    # ordering below can key on the same ring tokens requests route by
    prepared = [
        (n, v, pop, tiers.maybe_pad(synth_cvrp(n, v, seed=0)))
        for n, v, pop in parse_shapes(spec)
    ]
    for n, v, pop, inst in _hot_first(prepared):
        if owned_only and not _owns_shape(inst):
            if log:
                print(f"[warmup] {n}x{v}: tier owned by a peer replica; "
                      "skipped", file=sys.stderr)
            continue
        for algo in algorithms:
            errors: list = []
            # timeLimit 0 -> one 512-sweep deadline block (the program
            # every timeLimit request runs); localSearchPool 32 compiles
            # the pool polish; iterationCount 512 keeps the block full-
            # size. _run_solver is the service's own timed dispatch, so
            # the polish and final-eval programs warm too — and every
            # timed solver (SA, GA, ACO alike) records its measured
            # iteration rate into the shared hint cache
            # (solvers.common.rate_put), so the first real solve of a
            # warmed shape opens with a fitted block instead of the
            # blind probe.
            opts = {
                "seed": 0,
                "population_size": pop,
                "iteration_count": 512,
                "time_limit": 0.0,
                "local_search": True,
                "local_search_pool": 32,
            }
            res, _ = _run_solver(inst, algo, opts, {}, errors, "vrp", None)
            # champion-only polish (localSearch without a pool) is a
            # distinct batch-1 program
            opts2 = {
                "seed": 0,
                "population_size": pop,
                "iteration_count": 512,
                "time_limit": 0.0,
                "local_search": True,
            }
            res2, _ = _run_solver(inst, algo, opts2, {}, errors, "vrp", None)
            # the warm-SEEDED program variant: near-hit/warmStart
            # seeding (service.cache) dispatches seeded init + the cool
            # seeded schedule, a distinct trace from the constructive
            # path — without warming it, the first near hit after the
            # cache fills pays a fresh compile mid-request (visible as
            # the cache_on p99 outlier in benchmarks/records/
            # cache_hit_r11.json)
            import jax.numpy as jnp

            warm_seed = jnp.arange(1, n, dtype=jnp.int32)
            res3, _ = _run_solver(
                inst, algo, opts2, {}, errors, "vrp", warm_seed
            )
            if errors and log:
                print(f"[warmup] {n}x{v} {algo}: {errors}", file=sys.stderr)
            del res, res2, res3
            if algo == "sa":
                # every shrunk deadline-block shape + a persisted
                # sweeps/s per shape, so the FIRST timeLimit request of
                # this (and the next) process opens with a fitted block
                # instead of compiling mid-solve (VERDICT round-3
                # budget-fidelity item). CPU deployments skip it: the
                # delta gate fails there, each block runs the full
                # one-hot evaluation (minutes per block at production
                # chain counts), and startup would balloon (ADVICE r4).
                import jax

                if jax.default_backend() != "cpu":
                    from vrpms_tpu.solvers.sa import warm_anneal_blocks

                    warm_anneal_blocks(inst, pop or 128)
        _note_warmed(f"{n}x{v}")
    elapsed = time.perf_counter() - t_start
    if log:
        print(f"[warmup] {spec} ({','.join(algorithms)}): {elapsed:.1f}s",
              file=sys.stderr)
    return elapsed


def tier_warm_shapes(max_locations: int = 64, vehicles: int = 4) -> str:
    """Default tier-ladder warmup spec: one NxV shape per node tier up
    to `max_locations` (tiers beyond that are rare cold paths whose
    compiles amortize on first contact), at one canonical vehicle tier.
    Within a tier EVERY size shares the warmed programs — that is the
    point of the canonicalization (core.tiers)."""
    from vrpms_tpu.core import tiers

    lad = tiers.ladder()
    if lad is None:
        return ""
    v = tiers.tier_up(vehicles, lad.v) if lad.v else vehicles
    ns = [n for n in lad.n if n <= max_locations] or list(lad.n[:1])
    return ",".join(f"{n}x{v}" for n in ns)


def warmup_tiers(max_locations: int = 64, log=True) -> float:
    """Warm the default-schedule programs for the tier ladder: every
    request whose padded shape lands on a warmed tier then solves at
    steady-state latency from the first hit. Instances are padded
    through the SAME tiers.maybe_pad path requests take, so the warmed
    traces are exactly the ones traffic reuses. With the distributed
    queue active the ladder is arc-weighted (_hot_first): tiers this
    replica's claim mix shows as hot compile before the cold tail."""
    spec = tier_warm_shapes(max_locations)
    if not spec:
        if log:
            print("[warmup] tiering off; nothing to warm", file=sys.stderr)
        return 0.0
    # with the store-backed distributed queue, warm ONLY the arcs this
    # replica owns on the consistent-hash ring — N replicas split the
    # ladder's warmup cost ~N ways instead of each paying all of it
    owned_only = False
    try:
        from service import jobs as jobs_mod

        owned_only = jobs_mod.dist_queue_enabled()
    except Exception:
        pass
    return warmup(spec, log=log, owned_only=owned_only)


def start_background_warmup(fn, *args) -> "object":
    """Run a warmup callable on a daemon thread so the service binds its
    port (and serves /metrics + readiness) while the tier ladder
    precompiles behind it — the VRPMS_WARMUP=tiers startup hook. Solves
    arriving mid-warmup just compile their own shape as before; they
    are never blocked by the thread."""
    import threading

    def run():
        try:
            fn(*args)
        except Exception as e:  # never take the service down
            from vrpms_tpu.obs import log_event

            log_event(
                "warmup.skipped", error=f"{type(e).__name__}: {e}"
            )

    t = threading.Thread(target=run, name="vrpms-warmup", daemon=True)
    t.start()
    return t
