"""Trace debug surface: the operator's window into recent requests.

Two read-only endpoints over the completed-trace ring
(vrpms_tpu.obs.spans):

  GET /api/debug/traces            — newest-first summaries, filterable
                                     by ?minMs= (minimum duration),
                                     ?status= (ok|error), ?limit=
  GET /api/debug/traces/{traceId}  — one trace's full span tree

These answer the question aggregate histograms cannot: WHERE did that
slow request spend its time — queue wait, compile, batch-neighbor
interference, or a store retry storm. The histogram exemplars on
/metrics (`# {trace_id="..."}`) and the `traceId` echoed in every
response envelope are the join keys into this surface.

Header-sampled like the poll/readiness GETs (service.obs
begin_request_obs): debug reads only trace when the caller sends a
valid traceparent, so inspecting the ring doesn't churn it.
"""

from __future__ import annotations

import urllib.parse
from http.server import BaseHTTPRequestHandler

from service import obs
from service.helpers import respond_json
from vrpms_tpu.obs import spans


class TracesHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/debug/traces — the recent-trace ring, filtered."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            self._list()
        finally:
            obs.end_request_obs(self)

    def _list(self):
        query = urllib.parse.parse_qs(self.path.partition("?")[2])
        try:
            min_ms = float(query.get("minMs", ["0"])[0])
            limit = int(query.get("limit", ["50"])[0])
        except (TypeError, ValueError):
            self._obs_errors = ["Bad request"]
            respond_json(self, 400, {
                "success": False,
                "errors": [{
                    "what": "Bad request",
                    "reason": "'minMs' must be a number and 'limit' an "
                    "integer",
                }],
            })
            return
        status = query.get("status", [None])[0]
        if status is not None and status not in ("ok", "error"):
            self._obs_errors = ["Bad request"]
            respond_json(self, 400, {
                "success": False,
                "errors": [{
                    "what": "Bad request",
                    "reason": "'status' must be 'ok' or 'error'",
                }],
            })
            return
        respond_json(self, 200, {
            "success": True,
            "tracing": spans.tracing_enabled(),
            "capacity": spans.ring_capacity(),
            "traces": spans.ring_snapshot(
                min_duration_ms=min_ms, status=status, limit=limit
            ),
        })


class TraceDetailHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/debug/traces/{traceId} — one trace's full span tree."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            self._detail()
        finally:
            obs.end_request_obs(self)

    def _detail(self):
        trace_id = (
            self.path.split("?", 1)[0].rstrip("/").rsplit("/", 1)[-1]
        )
        trace = spans.ring_get(trace_id)
        if trace is None:
            self._obs_errors = ["Not found"]
            respond_json(self, 404, {
                "success": False,
                "errors": [{
                    "what": "Not found",
                    "reason": (
                        f"no completed trace {trace_id!r} in the ring "
                        "(it may not have finished yet, or was evicted "
                        "— see VRPMS_TRACE_RING)"
                    ),
                }],
            })
            return
        respond_json(self, 200, {"success": True, "trace": trace.to_dict()})
