"""Debug surfaces: the operator's window into requests — and the fleet.

Process-local endpoints (PR 5) over the completed-trace ring
(vrpms_tpu.obs.spans):

  GET /api/debug/traces            — newest-first summaries, filterable
                                     by ?minMs= (minimum duration),
                                     ?status= (ok|error), ?limit=;
                                     ?jobId= resolves a job to its
                                     trace (live registry, then the
                                     store record), ?scope=fleet lists
                                     store-backed summaries
  GET /api/debug/traces/{traceId}  — one trace's full span tree

Fleet-aware extensions (durable trace export, VRPMS_TRACE_EXPORT=on):

  * the detail read FEDERATES — local ring spans merge with the trace's
    exported rows from every replica (store.base get_trace_spans), so a
    store-queue job submitted here and solved elsewhere reads as ONE
    waterfall from ANY replica; on span-id conflict the local ring
    wins (it is the live, unserialized truth);
  * store-down degrades to local-only with a `degraded: true` marker,
    never a 500 — trace reads are evidence, not dependencies;
  * GET /api/jobs/{id}/timeline stitches a job's spans plus its
    persisted progress profile into one ordered human-readable event
    list (which replica claimed it, batch size and QoS class, shard
    rollup for decomposed jobs, requeue attempts);
  * GET /api/debug/fleet aggregates the replica heartbeat registry's
    status docs (inflight, claim mix, warmed tiers — sched.replica
    publishes them each beat) with the shared queue's depth into the
    one endpoint an operator or autoscaler polls instead of N
    /api/ready s.

With VRPMS_TRACE_EXPORT=off (the local default) no store read happens
on any pre-existing surface and responses stay byte-identical to the
process-local contract.

Header-sampled like the poll/readiness GETs (service.obs
begin_request_obs): debug reads only trace when the caller sends a
valid traceparent, so inspecting the ring doesn't churn it.
"""

from __future__ import annotations

import time
import urllib.parse
from http.server import BaseHTTPRequestHandler

import store
from service import obs
from service.helpers import respond_json
from vrpms_tpu import config
from vrpms_tpu.obs import analytics
from vrpms_tpu.obs import export as trace_export
from vrpms_tpu.obs import slo
from vrpms_tpu.obs import spans


def _bad_request(handler, reason: str) -> None:
    handler._obs_errors = ["Bad request"]
    respond_json(handler, 400, {
        "success": False,
        "errors": [{"what": "Bad request", "reason": reason}],
    })


def _trace_db():
    return store.get_database("vrp", None)


def _store_trace_rows(trace_id: str | None) -> tuple[list, bool]:
    """(rows, degraded) for a trace's exported rows. Export off — the
    local default — means NO store read at all (rows=[], healthy), so
    the pre-export surfaces cannot gain latency or new failure modes.
    degraded=True means the store could not be read (the caller serves
    local-only and says so)."""
    if not trace_export.enabled() or not trace_id:
        return [], False
    try:
        rows = _trace_db().get_trace_spans(trace_id)
    except Exception:
        rows = None
    if rows is None:
        return [], True
    return rows, False


# ---------------------------------------------------------------------------
# Federated merge: local ring + exported rows -> one span tree
# ---------------------------------------------------------------------------


def merge_trace(trace_id: str, local, rows: list) -> dict | None:
    """One cross-replica span tree from every source that recorded
    part of this trace: the local ring/live Trace (when present) plus
    each replica's exported row. Span offsets are rebased onto the
    EARLIEST source's start clock (replicas must be NTP-sane — the
    lease contract already requires it), spans carry their recording
    replica, and on span-id conflict the LOCAL span wins. None when no
    source holds the trace."""
    sources: list[tuple[dict, bool]] = []
    if local is not None:
        doc = local.to_dict()
        doc.setdefault(
            "replica",
            getattr(local, "export_replica", None)
            or trace_export.replica_identity(),
        )
        sources.append((doc, True))
    for row in rows:
        doc = row.get("doc") or {}
        if not doc.get("spans"):
            continue
        if doc.get("replica") is None:
            doc = dict(doc, replica=row.get("replica"))
        if any(doc.get("replica") == d.get("replica") for d, _ in sources):
            # the local ring supersedes this replica's own exported row
            continue
        sources.append((doc, False))
    if not sources:
        return None
    starts = [
        d.get("startedAt") for d, _ in sources
        if d.get("startedAt") is not None
    ]
    base = min(starts) if starts else 0.0
    by_id: dict = {}
    replicas: list = []
    status, truncated = "ok", False
    for doc, is_local in sources:
        rep = doc.get("replica")
        if rep and rep not in replicas:
            replicas.append(rep)
        if doc.get("status") == "error":
            status = "error"
        truncated = truncated or bool(doc.get("truncated"))
        started = doc.get("startedAt") or base
        shift_ms = (started - base) * 1e3
        for span in doc.get("spans") or []:
            sid = span.get("spanId")
            if sid in by_id and not is_local:
                continue  # local wins; first exported row wins the rest
            span = dict(span)
            span["startMs"] = round(shift_ms + (span.get("startMs") or 0), 3)
            if span.get("events"):
                # event offsets are relative to THEIR trace's start:
                # rebase them onto the merged clock too, or a remote
                # span's lifecycle events would sort seconds early
                span["events"] = [
                    (
                        dict(ev, offsetMs=round(shift_ms + ev["offsetMs"], 3))
                        if ev.get("offsetMs") is not None
                        else dict(ev)
                    )
                    for ev in span["events"]
                ]
            if rep and "replica" not in span:
                span["replica"] = rep
            by_id[sid] = span
    merged = sorted(by_id.values(), key=lambda s: s.get("startMs") or 0)
    end = 0.0
    for span in merged:
        if span.get("durationMs") is not None:
            end = max(end, span["startMs"] + span["durationMs"])
    return {
        "traceId": trace_id,
        "startedAt": base,
        "durationMs": round(end, 3),
        "status": status,
        "truncated": truncated,
        "replicas": replicas,
        "spans": merged,
    }


def _summary_from_rows(trace_id: str, rows: list) -> dict | None:
    """A ring_snapshot-shaped summary for a trace only the store has
    (the ?jobId= jump when the job solved on another replica)."""
    merged = merge_trace(trace_id, None, rows)
    if merged is None:
        return None
    root = merged["spans"][0] if merged["spans"] else None
    return {
        "traceId": trace_id,
        "startedAt": merged["startedAt"],
        "durationMs": merged["durationMs"],
        "status": merged["status"],
        "root": root.get("name") if root else None,
        "spans": len(merged["spans"]),
        "replicas": merged["replicas"],
    }


def _resolve_job_trace(handler, job_id: str):
    """jobId -> (traceId, record, responded): the live registry first
    (a running job's trace is not in any ring yet), then the store
    record. Writes the 404/store-error envelope itself and returns
    responded=True when it did."""
    from service import jobs as jobs_mod

    live = jobs_mod.get_live_job(job_id)
    if live is not None and live.trace is not None:
        return live.trace.trace_id, None, False
    errors: list = []
    try:
        record = _trace_db().get_job(job_id, errors)
    except Exception as e:
        errors.append({"what": "Database error", "reason": str(e)})
        record = None
    if errors:
        handler._obs_errors = [e.get("what", "unknown") for e in errors]
        respond_json(handler, 400, {"success": False, "errors": errors})
        return None, None, True
    if record is None:
        handler._obs_errors = ["Not found"]
        respond_json(handler, 404, {
            "success": False,
            "errors": [{
                "what": "Not found",
                "reason": f"no job with id {job_id!r}",
            }],
        })
        return None, None, True
    return record.get("traceId"), record, False


class TracesHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/debug/traces — recent traces, filtered; ?jobId= jumps
    from a job to its trace; ?scope=fleet lists exported summaries."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            self._list()
        finally:
            obs.end_request_obs(self)

    def _list(self):
        query = urllib.parse.parse_qs(self.path.partition("?")[2])
        try:
            min_ms = float(query.get("minMs", ["0"])[0])
            limit = int(query.get("limit", ["50"])[0])
        except (TypeError, ValueError):
            _bad_request(
                self, "'minMs' must be a number and 'limit' an integer"
            )
            return
        status = query.get("status", [None])[0]
        if status is not None and status not in ("ok", "error"):
            _bad_request(self, "'status' must be 'ok' or 'error'")
            return
        scope = query.get("scope", [None])[0]
        if scope is not None and scope not in ("local", "fleet"):
            _bad_request(self, "'scope' must be 'local' or 'fleet'")
            return
        job_id = query.get("jobId", [None])[0]
        if job_id is not None:
            self._job_traces(job_id)
            return
        if scope == "fleet":
            self._fleet_traces(min_ms, status, limit)
            return
        respond_json(self, 200, {
            "success": True,
            "tracing": spans.tracing_enabled(),
            "capacity": spans.ring_capacity(),
            "traces": spans.ring_snapshot(
                min_duration_ms=min_ms, status=status, limit=limit
            ),
        })

    def _fleet_traces(self, min_ms: float, status, limit: int):
        """Store-backed summaries (every replica's exports merged);
        export off or store down degrades to the local ring, marked."""
        summaries, degraded = None, False
        filtered = status is not None or min_ms > 0
        if trace_export.enabled():
            try:
                # with filters active, scan deeper than the page size:
                # filtering AFTER a newest-`limit` cut would hide any
                # matching trace older than the newest page
                summaries = _trace_db().list_traces(
                    limit=max(limit * 4, 200) if filtered else limit
                )
            except Exception:
                summaries = None
            degraded = summaries is None
        payload: dict = {
            "success": True,
            "tracing": spans.tracing_enabled(),
            "scope": "fleet" if summaries is not None else "local",
        }
        if summaries is not None:
            payload["traces"] = [
                s for s in summaries
                if (status is None or s.get("status") == status)
                and (s.get("durationMs") or 0) >= min_ms
            ][: max(1, limit)]
        else:
            # local fallback keeps the surface useful mid-outage (or
            # with export off, where no fleet view exists to serve)
            payload["capacity"] = spans.ring_capacity()
            payload["traces"] = spans.ring_snapshot(
                min_duration_ms=min_ms, status=status, limit=limit
            )
        if degraded:
            payload["degraded"] = True
        respond_json(self, 200, payload)

    def _job_traces(self, job_id: str):
        """?jobId= — resolve the job to its trace and answer with that
        trace's summary (ring first, exported rows second), so an
        operator jumps from a job to its waterfall without grepping."""
        trace_id, _record, responded = _resolve_job_trace(self, job_id)
        if responded:
            return
        payload: dict = {
            "success": True,
            "tracing": spans.tracing_enabled(),
            "jobId": job_id,
            "resolvedTraceId": trace_id,
            "traces": [],
        }
        if trace_id:
            local = spans.ring_get(trace_id)
            if local is not None:
                payload["traces"] = [local.summary()]
            else:
                rows, degraded = _store_trace_rows(trace_id)
                summary = _summary_from_rows(trace_id, rows)
                if summary is not None:
                    payload["traces"] = [summary]
                if degraded:
                    payload["degraded"] = True
        respond_json(self, 200, payload)


class TraceDetailHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/debug/traces/{traceId} — one trace's full span tree,
    federated across replicas when trace export is on."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            self._detail()
        finally:
            obs.end_request_obs(self)

    def _detail(self):
        trace_id = (
            self.path.split("?", 1)[0].rstrip("/").rsplit("/", 1)[-1]
        )
        local = spans.ring_get(trace_id)
        if not trace_export.enabled():
            # the PR-5 process-local contract, byte-identical: no store
            # read, no merge, no new keys
            if local is None:
                self._not_found(trace_id, degraded=False)
                return
            respond_json(
                self, 200, {"success": True, "trace": local.to_dict()}
            )
            return
        rows, degraded = _store_trace_rows(trace_id)
        merged = merge_trace(trace_id, local, rows)
        if merged is None:
            self._not_found(trace_id, degraded=degraded)
            return
        payload: dict = {"success": True, "trace": merged}
        if degraded:
            # the store could not answer: this is the LOCAL view only,
            # another replica's half may exist
            payload["degraded"] = True
        respond_json(self, 200, payload)

    def _not_found(self, trace_id: str, degraded: bool):
        self._obs_errors = ["Not found"]
        payload: dict = {
            "success": False,
            "errors": [{
                "what": "Not found",
                "reason": (
                    f"no completed trace {trace_id!r} in the ring "
                    "(it may not have finished yet, or was evicted "
                    "— see VRPMS_TRACE_RING)"
                ),
            }],
        }
        if degraded:
            payload["degraded"] = True
        respond_json(self, 404, payload)


# ---------------------------------------------------------------------------
# Per-job timeline
# ---------------------------------------------------------------------------

#: ordered, human-readable event kinds the timeline stitches from spans
_SPAN_EVENT_KINDS = {
    "queue.wait": "waited in queue",
    "dist.claim_batch": "claimed from the shared queue",
    "dist.execute": "executed on replica",
    "solve": "solved",
    "decompose": "decomposed",
    "stitch": "stitched",
    "qos.shed": "shed",
    "ckpt.write": "checkpoint written",
    "ckpt.resume": "resumed from checkpoint",
    "store.persist_job": "record persisted",
}

#: incumbent entries kept verbatim in a timeline before thinning
MAX_TIMELINE_INCUMBENTS = 32


def _span_events(merged: dict | None) -> list:
    events: list = []
    if merged is None:
        return events
    for span in merged["spans"]:
        name = span.get("name")
        if name not in _SPAN_EVENT_KINDS:
            continue
        attrs = span.get("attributes") or {}
        at_ms = span.get("startMs")
        detail = _SPAN_EVENT_KINDS[name]
        ev: dict = {"atMs": at_ms, "event": name}
        rep = span.get("replica")
        if rep:
            ev["replica"] = rep
        # a live (unfinished) span has no duration yet — the
        # human-readable strings must say so, not read "Nonems"
        dur = span.get("durationMs")
        dur_text = "still running" if dur is None else f"{dur}ms"
        if name == "queue.wait":
            detail = f"waited {dur_text} in queue"
            if attrs.get("requeued"):
                detail += " (after a requeue)"
        elif name == "dist.claim_batch":
            size = attrs.get("size") or 1
            detail = (
                f"claimed by replica {rep or '?'} "
                f"({attrs.get('kind') or 'own'} arc, batch of {size}"
            )
            if attrs.get("qos"):
                detail += f", qos {attrs['qos']}"
            detail += ")"
            ev["batchSize"] = size
        elif name == "dist.execute":
            attempt = attrs.get("attempt") or 1
            detail = f"executed on replica {rep or '?'} (attempt {attempt})"
            ev["attempt"] = attempt
        elif name == "solve":
            detail = (
                f"solve ran {dur_text}"
                f" (attempt {attrs.get('attempt') or 1}"
            )
            if (attrs.get("batchSize") or 1) > 1:
                detail += f", micro-batched x{attrs['batchSize']}"
            detail += f") on replica {rep or '?'}"
            ev["attempt"] = attrs.get("attempt") or 1
            # the requeue story: job.* lifecycle events ride the spans
            for sub in span.get("events") or []:
                if str(sub.get("name", "")).startswith("job."):
                    events.append({
                        "atMs": sub.get("offsetMs"),
                        "event": sub["name"],
                        "detail": sub["name"].replace("job.", "job "),
                    })
        elif name == "decompose":
            shards = attrs.get("shards")
            subs = span.get("events") or []
            launches = [e for e in subs if e.get("name") == "launch"]
            detail = (
                f"decomposed into {shards} tier-{attrs.get('tier')} "
                f"shards"
            )
            if launches:
                detail += f", dispatched as {len(launches)} vmapped launches"
            ev["shards"] = shards
            ev["launches"] = len(launches) or None
        elif name == "stitch":
            detail = (
                f"stitched shard routes (boundary band of "
                f"{attrs.get('boundary')} customers)"
            )
        elif name == "qos.shed":
            detail = (
                f"shed ({attrs.get('reason')}, qos {attrs.get('qos')})"
            )
        elif name == "ckpt.write":
            attempt = attrs.get("attempt") or 1
            detail = f"checkpoint written (attempt {attempt}"
            if attrs.get("cost") is not None:
                detail += f", cost {attrs['cost']}"
                ev["cost"] = attrs["cost"]
            if attrs.get("shards"):
                detail += f", {attrs['shards']} shards"
            detail += f") by replica {rep or '?'}"
            ev["attempt"] = attempt
        elif name == "ckpt.resume":
            source = attrs.get("source") or "?"
            if source == "drain":
                # the handoff that PRECEDED this resume: a draining
                # peer flushed its freshest checkpoint and nacked the
                # entry back to the shared queue (no attempt burned)
                events.append({
                    "atMs": at_ms,
                    "event": "drain.nack",
                    "detail": (
                        "a draining replica checkpointed the solve and "
                        "nacked it back to the shared queue for a peer"
                    ),
                })
            detail = (
                f"resumed from checkpoint ({source}"
                + (
                    f", cost {attrs.get('cost')}"
                    if attrs.get("cost") is not None
                    else ""
                )
                + (
                    f", {attrs.get('shards')} shards done"
                    if attrs.get("shards")
                    else ""
                )
                + f") on replica {rep or '?'}"
            )
            ev["source"] = source
            if attrs.get("cost") is not None:
                ev["cost"] = attrs["cost"]
        ev["detail"] = detail
        if span.get("durationMs") is not None:
            ev["durationMs"] = span["durationMs"]
        events.append(ev)
    return events


def _incumbent_events(record: dict, merged: dict | None) -> list:
    """The persisted convergence profile as timeline entries, anchored
    under the solve span's clock when one is known."""
    progress = record.get("progress")
    if isinstance(progress, dict):
        # the persisted sink profile: {"blocks", "improvements": [...]}
        profile = list(progress.get("improvements") or [])
    else:
        profile = list(progress or [])
    if not profile:
        snap = record.get("incumbent")
        profile = [snap] if snap else []
    profile = [s for s in profile if isinstance(s, dict)]
    solve_start = None
    if merged is not None:
        for span in merged["spans"]:
            if span.get("name") == "solve":
                solve_start = span.get("startMs")
                break
    if len(profile) > MAX_TIMELINE_INCUMBENTS:
        # thin evenly, always keeping the first and the final incumbent
        step = (len(profile) - 1) / (MAX_TIMELINE_INCUMBENTS - 1)
        profile = [
            profile[round(i * step)]
            for i in range(MAX_TIMELINE_INCUMBENTS)
        ]
    events = []
    for snap in profile:
        wall = snap.get("wallMs")
        ev = {
            "atMs": (
                None
                if wall is None or solve_start is None
                else round(solve_start + wall, 3)
            ),
            "event": "incumbent",
            "detail": (
                f"incumbent {snap.get('bestCost')}"
                + (
                    f" (gap {snap.get('gap')})"
                    if snap.get("gap") is not None
                    else ""
                )
            ),
            "bestCost": snap.get("bestCost"),
            "gap": snap.get("gap"),
            "block": snap.get("block"),
        }
        events.append(ev)
    return events


def build_timeline(record: dict, merged: dict | None) -> list:
    """One ordered event list for a job: lifecycle from the persisted
    record, execution detail from its (federated) spans, convergence
    from the progress profile. Events carry `atMs` relative to the
    trace start (submit) where the clock is known; unknown-clock events
    sort after their section in emit order."""
    t0 = merged["startedAt"] if merged is not None else None
    submitted = record.get("submittedAt")

    def rel(ts) -> float | None:
        if ts is None:
            return None
        base = t0 if t0 is not None else submitted
        return None if base is None else round((ts - base) * 1e3, 3)

    events: list = [{
        "atMs": 0.0 if submitted is not None else None,
        "event": "submitted",
        "detail": (
            f"{record.get('problem')}/{record.get('algorithm')} job "
            f"submitted"
        ),
    }]
    if record.get("startedAt"):
        events.append({
            "atMs": rel(record["startedAt"]),
            "event": "started",
            "detail": "solve started"
            + (
                f" (queue wait {record.get('queueWaitMs')}ms)"
                if record.get("queueWaitMs") is not None
                else ""
            ),
        })
    events += _span_events(merged)
    events += _incumbent_events(record, merged)
    if int(record.get("attempt") or 1) > 1:
        events.append({
            "atMs": None,
            "event": "requeued",
            "detail": (
                f"attempt {record['attempt']}: the first replica's "
                "lease expired; a peer reclaimed and re-ran the job"
            ),
        })
    if record.get("finishedAt"):
        status = record.get("status")
        events.append({
            "atMs": rel(record["finishedAt"]),
            "event": status or "finished",
            "detail": f"job {status or 'finished'}"
            + (" (cancelled)" if (record.get("message") or {}).get(
                "cancelled") else ""),
        })
    # stable order: known clocks first in time order, unknown clocks
    # keep their emit position at the end of the same millisecond
    return sorted(
        events,
        key=lambda e: (e["atMs"] is None, e["atMs"] or 0.0),
    )


def _lineage_events(record: dict, job_id: str) -> tuple[list, list]:
    """Narrate the `resolvedFrom` chain behind a job — the standing-
    subscription generations (or manual /resolve hops) that seeded it.
    Walks predecessor records back through the shared store (so the
    chain resolves fleet-wide regardless of which replica ran each
    hop), numbering the root as generation 1. Returns (events, hops):
    human-readable timeline entries plus the machine-readable chain."""
    try:
        db = store.get_database(record.get("problem") or "vrp", None)
    except Exception:
        return [], []
    chain: list = []
    seen = {job_id}
    cur = record
    while cur.get("resolvedFrom") and len(chain) < 16:
        pid = cur["resolvedFrom"]
        if pid in seen:
            break  # defensive: a cyclic chain must not spin the walk
        seen.add(pid)
        prev = db.get_job(pid, [])
        cost = None
        if prev is not None:
            cost = (prev.get("incumbent") or {}).get("bestCost")
        chain.append({
            "jobId": pid,
            "cost": cost,
            "status": prev.get("status") if prev is not None else None,
        })
        if prev is None:
            break
        cur = prev
    if not chain:
        return [], []
    # chain[0] is the direct seed; the oldest ancestor is generation 1
    events = []
    root_gen = 1 if not cur.get("resolvedFrom") else None
    for depth, hop in enumerate(reversed(chain)):
        gen = (depth + 1) if root_gen else None
        hop["generation"] = gen
        events.append({
            "atMs": None,
            "event": "lineage",
            "detail": (
                (f"generation {gen}, " if gen else "")
                + f"seeded from job {hop['jobId']}"
                + (
                    f" at cost {hop['cost']}"
                    if hop["cost"] is not None
                    else ""
                )
            ),
        })
    return events, list(reversed(chain))


def _flight_for_job(record: dict, job_id: str) -> dict | None:
    """The job's flight record: the local analytics ring first (this
    replica solved it), then the shared flight table (a peer did).
    Fail-open — a store miss or outage just means no economics event."""
    doc = analytics.recent_for_job(job_id)
    if doc is not None:
        return doc
    try:
        rows = store.get_database(
            record.get("problem") or "vrp", None
        ).get_flight_records(limit=256)
    except Exception:
        rows = None
    for row in rows or []:
        if str(row.get("job_id")) == job_id:
            return dict(row.get("doc") or {}) or None
    return None


def _economics_event(record: dict, job_id: str) -> dict | None:
    """The timeline's closing "solve economics" entry: where the wall
    time went (device vs host, overlap), how full the padded shapes
    were, and what quality came out. None when no flight record exists
    (analytics off for this job, trivial solve, or evicted)."""
    doc = _flight_for_job(record, job_id)
    if not doc:
        return None
    parts: list = []
    if doc.get("deviceS") is not None:
        parts.append(
            f"device {doc['deviceS']}s / host {doc.get('hostS')}s"
        )
    ratio = doc.get("overlapRatio")
    if ratio is not None:
        parts.append(f"overlap {round(ratio * 100, 1)}%")
    occ = (doc.get("occupancy") or {}).get("compute")
    if occ is not None:
        parts.append(
            f"padding occupancy {round(occ * 100, 1)}%"
            + (f" on tier {doc['tier']}" if doc.get("tier") else "")
        )
    batch = doc.get("batch") or {}
    if batch.get("fill") is not None:
        parts.append(
            f"batch fill {batch.get('members')}/{batch.get('padded')}"
        )
    if doc.get("evalsPerSec") is not None:
        parts.append(f"{doc['evalsPerSec']} evals/s")
    if doc.get("cache"):
        parts.append(f"cache {doc['cache']}")
    if doc.get("gap") is not None:
        parts.append(f"gap {doc['gap']}")
    return {
        "atMs": None,
        "event": "solve.economics",
        "detail": "solve economics: " + (", ".join(parts) or "recorded"),
        "flight": doc,
    }


class JobTimelineHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/jobs/{id}/timeline — the job's story as one ordered,
    human-readable event list, resolved across replicas via the trace
    store when export is on. With standing subscriptions on, a job that
    was seeded from a predecessor also narrates its `resolvedFrom`
    lineage ("generation N, seeded from job X at cost C") so a
    subscription's whole chain reads from any one generation."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            self._timeline()
        finally:
            obs.end_request_obs(self)

    def _timeline(self):
        from service import jobs as jobs_mod

        job_id = jobs_mod._job_id_from_path(self.path)
        record = jobs_mod._load_job_record(self, job_id)
        if record is None:
            return
        live = jobs_mod.get_live_job(job_id)
        trace_id = record.get("traceId")
        local = None
        if trace_id:
            local = spans.ring_get(trace_id)
            if local is None and live is not None and live.trace is not None:
                # still running here: the live trace is the local truth
                local = live.trace
        rows, degraded = _store_trace_rows(trace_id)
        merged = (
            merge_trace(trace_id, local, rows) if trace_id else None
        )
        if live is not None and live.sink is not None:
            snap = live.sink.snapshot()
            if snap is not None:
                record = dict(record, incumbent=snap)
        elif (
            jobs_mod._federation_enabled()
            and record.get("status") not in ("done", "failed")
        ):
            # another replica's live solve: the timeline closes on the
            # checkpoint-sourced incumbent (marked, like the status
            # poll); a failed checkpoint read only flags degraded
            snap, ckpt_degraded = jobs_mod._checkpoint_incumbent(job_id)
            if snap is not None:
                record = dict(record, incumbent=snap)
            if ckpt_degraded:
                degraded = True
        payload: dict = {
            "success": True,
            "jobId": job_id,
            "status": record.get("status"),
            "traceId": trace_id,
            "replicas": merged["replicas"] if merged is not None else [],
            "timeline": build_timeline(record, merged),
        }
        if analytics.enabled():
            # analytics-era narration only: with VRPMS_ANALYTICS off
            # the timeline stays byte-identical to the pre-analytics
            # service
            economics = _economics_event(record, job_id)
            if economics is not None:
                payload["timeline"] = payload["timeline"] + [economics]
        if config.enabled("VRPMS_SUBS") and record.get("resolvedFrom"):
            # subscription-era narration only: with VRPMS_SUBS off the
            # timeline stays byte-identical to the pre-subscription
            # service even for manually /resolve-chained jobs
            lin_events, hops = _lineage_events(record, job_id)
            if hops:
                payload["timeline"] = payload["timeline"] + lin_events
                payload["lineage"] = hops
        if degraded or self._job_db_degraded:
            payload["degraded"] = True
        respond_json(self, 200, payload)


# ---------------------------------------------------------------------------
# Fleet rollup
# ---------------------------------------------------------------------------


class FleetHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/debug/fleet — every replica's heartbeat status doc plus
    the shared queue's depth, from any replica: the autoscaler's one
    poll. Store-down (or VRPMS_QUEUE=local) serves the local replica's
    view only, marked accordingly — never a 500."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            self._fleet()
        finally:
            obs.end_request_obs(self)

    def _fleet(self):
        from service import jobs as jobs_mod

        dist = jobs_mod.dist_queue_enabled()
        self_id = jobs_mod.replica_id()
        fleet: dict = {
            "queue": "store" if dist else "local",
            "generatedBy": self_id,
            "generatedAt": time.time(),
        }
        degraded = False
        replicas: dict = {}
        if dist:
            rep = jobs_mod._replica  # peek — polling must not build one
            qs = None
            try:
                qs = rep.store if rep is not None else store.get_queue_store()
            except Exception:
                degraded = True
            if qs is not None:
                try:
                    members = qs.replicas()
                except Exception:
                    members, degraded = [], True
                infos = None
                try:
                    infos = qs.replica_infos()
                except Exception:
                    degraded = True
                for rid in members:
                    replicas[rid] = dict(
                        (infos or {}).get(rid) or {}, replicaId=rid
                    )
                depth = jobs_mod._shared_depth(qs)
                if depth is not None:
                    fleet["sharedDepth"] = depth
                classes = jobs_mod._shared_class_depths(qs)
                if classes is not None:
                    fleet["sharedQueuedByClass"] = classes
        # this process answers with its LIVE state (fresher than its
        # last heartbeat doc), so a fleet of one still tells the story
        replicas[self_id] = dict(
            replicas.get(self_id) or {},
            **jobs_mod.replica_info(),
            replicaId=self_id,
            self=True,
        )
        from service import autoscale as autoscale_mod

        if autoscale_mod.enabled():
            if dist:
                # heartbeat-registry hygiene: a crashed replica's last
                # doc lingers until its row TTLs out — mark it stale
                # (updatedAt older than the lease window) and keep it
                # OUT of the live-member count instead of silently
                # counting it
                live, stale = autoscale_mod.split_stale(
                    list(replicas.keys()), replicas
                )
                for rid in stale:
                    replicas[rid]["stale"] = True
                fleet["members"] = {"live": len(live), "stale": len(stale)}
            # the controller's recommendation (inputs, decision,
            # cooldown state) — the block an HPA/external autoscaler
            # polls; fail-open, degraded-marked under a store outage
            fleet["autoscale"] = autoscale_mod.fleet_block()
        if analytics.enabled():
            # per-QoS-class deadline-met burn rates (fast/slow windows)
            # — the alerting view next to the capacity view it explains
            fleet["slo"] = slo.fleet_block()
        fleet["replicas"] = replicas
        drain = jobs_mod.drain_info()
        if drain is not None:
            # the answering replica is draining: surfaced at the top
            # level too (a fully-drained replica has deregistered its
            # heartbeat, so the members list alone would hide it)
            fleet["draining"] = drain
        payload: dict = {"success": True, "fleet": fleet}
        if degraded:
            payload["degraded"] = True
        respond_json(self, 200, payload)


# ---------------------------------------------------------------------------
# Solve analytics rollup
# ---------------------------------------------------------------------------

#: padding waste above this fraction earns a tier-ladder tuning hint
WASTE_HINT_THRESHOLD = 0.35
#: mean batch fill below this fraction earns a gather-window hint
FILL_HINT_THRESHOLD = 0.5
#: mean overlap ratio at or above this reads as a healthy pipeline
OVERLAP_HEALTHY = 0.5
#: flight rows scanned per store read (newest first)
FLIGHT_SCAN_LIMIT = 512


def _mean(values: list) -> float | None:
    vals = [float(v) for v in values if v is not None]
    return round(sum(vals) / len(vals), 4) if vals else None


def _merged_flight_docs() -> tuple[list, bool]:
    """Every known flight record, fleet-wide: the shared flight table
    (each replica's exported rows) overlaid with this replica's local
    ring — on (jobId, replica) conflict the LOCAL doc wins (it is the
    live, untruncated truth). degraded=True means the store could not
    be read and the rollup is local-only."""
    by_key: dict = {}
    try:
        rows = _trace_db().get_flight_records(limit=FLIGHT_SCAN_LIMIT)
    except Exception:
        rows = None
    degraded = rows is None
    for row in rows or []:
        doc = row.get("doc") or {}
        if doc:
            by_key[(str(row.get("job_id")), str(row.get("replica")))] = doc
    for doc in analytics.recent_records():
        by_key[(str(doc.get("jobId")), str(doc.get("replica")))] = doc
    docs = sorted(
        by_key.values(),
        key=lambda d: d.get("finishedAt") or 0.0,
        reverse=True,
    )
    return docs, degraded


def analytics_rollup(docs: list) -> dict:
    """Per-tier and per-algorithm hardware-efficiency aggregates over a
    set of flight records, with tuning hints where a knob would help:
    padding waste ranked worst-first -> tier-ladder hints, mean batch
    fill -> gather-window hint, mean overlap -> pipeline health."""
    tiers_map: dict = {}
    algos: dict = {}
    fills: list = []
    overlaps: list = []
    replicas: list = []
    for doc in docs:
        rep = doc.get("replica")
        if rep and rep not in replicas:
            replicas.append(rep)
        tier = doc.get("tier")
        if tier:
            t = tiers_map.setdefault(
                str(tier), {"occ": [], "gaps": [], "count": 0}
            )
            t["count"] += 1
            t["occ"].append((doc.get("occupancy") or {}).get("compute"))
            t["gaps"].append(doc.get("gap"))
        algo = doc.get("algorithm")
        if algo:
            a = algos.setdefault(
                str(algo),
                {"gaps": [], "eps": [], "pis": [], "count": 0},
            )
            a["count"] += 1
            a["gaps"].append(doc.get("gap"))
            a["eps"].append(doc.get("evalsPerSec"))
            a["pis"].append(doc.get("primalIntegral"))
        fills.append((doc.get("batch") or {}).get("fill"))
        overlaps.append(doc.get("overlapRatio"))
    tier_rows = []
    for tier, t in tiers_map.items():
        occ = _mean(t["occ"])
        row: dict = {
            "tier": tier,
            "solves": t["count"],
            "meanOccupancy": occ,
            "paddingWaste": (
                None if occ is None else round(1.0 - occ, 4)
            ),
            "meanGap": _mean(t["gaps"]),
        }
        if row["paddingWaste"] is not None and (
            row["paddingWaste"] > WASTE_HINT_THRESHOLD
        ):
            row["hint"] = (
                f"{round(row['paddingWaste'] * 100, 1)}% of this "
                "tier's padded compute is waste — consider an "
                "intermediate ladder step below it "
                "(vrpms_tpu.core.tiers)"
            )
        tier_rows.append(row)
    # worst waste first: the tier an operator should re-ladder first
    tier_rows.sort(key=lambda r: -(r["paddingWaste"] or 0.0))
    algo_rows = [
        {
            "algorithm": algo,
            "solves": a["count"],
            "meanGap": _mean(a["gaps"]),
            "meanEvalsPerSec": _mean(a["eps"]),
            "meanPrimalIntegral": _mean(a["pis"]),
        }
        for algo, a in sorted(algos.items())
    ]
    mean_fill = _mean(fills)
    batch: dict = {
        "launches": sum(1 for f in fills if f is not None),
        "meanFill": mean_fill,
    }
    if mean_fill is not None and mean_fill < FILL_HINT_THRESHOLD:
        batch["hint"] = (
            f"vmapped launches run {round(mean_fill * 100, 1)}% full "
            "on average — widen VRPMS_SCHED_WINDOW_MS (or lower "
            "VRPMS_SCHED_MAX_BATCH) so gather windows fill"
        )
    mean_overlap = _mean(overlaps)
    pipeline: dict = {
        "solves": sum(1 for r in overlaps if r is not None),
        "meanOverlapRatio": mean_overlap,
        "health": (
            "unknown"
            if mean_overlap is None
            else ("good" if mean_overlap >= OVERLAP_HEALTHY else "poor")
        ),
    }
    if pipeline["health"] == "poor":
        pipeline["hint"] = (
            "host bookkeeping rarely overlaps device compute — check "
            "VRPMS_PIPELINE and per-block host costs"
        )
    return {
        "records": len(docs),
        "replicas": replicas,
        "tiers": tier_rows,
        "algorithms": algo_rows,
        "batch": batch,
        "pipeline": pipeline,
    }


class AnalyticsHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/debug/analytics — hardware-efficiency rollups over the
    fleet's flight records: padding waste ranked by tier (tier-ladder
    tuning), batch fill (gather-window tuning), pipeline overlap
    health, per-algorithm quality, the regression sentinel's state, and
    the SLO burn rates. Store-down degrades to this replica's local
    ring, marked — never a 500."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            self._rollup()
        finally:
            obs.end_request_obs(self)

    def _rollup(self):
        query = urllib.parse.parse_qs(self.path.partition("?")[2])
        try:
            limit = int(query.get("limit", [str(FLIGHT_SCAN_LIMIT)])[0])
        except (TypeError, ValueError):
            _bad_request(self, "'limit' must be an integer")
            return
        docs, degraded = _merged_flight_docs()
        docs = docs[: max(1, limit)]
        payload: dict = {
            "success": True,
            "analytics": analytics_rollup(docs),
            "sentinel": analytics.get_sentinel().snapshot(),
            "slo": slo.fleet_block(),
            "queueDepth": analytics.queue_depth(),
        }
        if degraded:
            # the store could not answer: this replica's ring only,
            # other replicas' records may exist
            payload["degraded"] = True
        respond_json(self, 200, payload)
