"""Standing subscriptions — durable re-solve-on-change jobs.

A subscription is a store-persisted standing request: POST
/api/subscriptions binds a dataset (the same request body POST
/api/jobs takes), and the subscription then re-solves ITSELF — when a
delta is posted to it (POST /api/subscriptions/{id}/deltas, the same
{add, drop, demands, timeWindows} schema requests carry inline) or on
an optional wall-clock cadence (`resolveEvery` seconds). Each re-solve
is one GENERATION: a normal async job launched through the jobs.py
submit seam (service.jobs.submit_headless), seeded from the previous
generation's incumbent via the existing `warmStart: {jobId}`
continuation path, with `resolvedFrom` lineage in the record and the
trace root — so a subscription's history reads as one chain through
GET /api/jobs/{id}/timeline and the `sub.generation` trace spans.

The control-plane rules:

  * **debounce/coalesce** — a burst of deltas inside one
    VRPMS_SUB_DEBOUNCE_MS window composes into ONE pending delta and
    launches ONE generation (every delta beyond the first counts in
    vrpms_sub_coalesced_total);
  * **no-op dedupe** — a pending delta whose post-application instance
    carries the SAME tier fingerprint as the previous generation (adds
    cancelled by drops, attributes rewritten to their current values)
    is absorbed without any solver launch;
  * **first-class queue citizenship** — generations ride the normal
    submit pipeline, so QoS class, tenant quota accounting, the
    distributed store queue, and the PR-15 checkpoint/drain marker all
    apply with zero subscription-specific scheduling;
  * **fleet durability** — the subscription doc (base content,
    cumulative delta, pending delta, lineage tail) is store-persisted
    at every mutation; the replica heartbeat tick adopts docs whose
    owner left the ring (drain, crash), firing adopted pending state
    as a trigger="resume" generation;
  * **streaming** — GET /api/subscriptions/{id}/stream replays
    terminal generations (Last-Event-ID aware, ids are
    "{generation}:{block}") then follows the live one through the
    owner's progress sink or, federated, the PR-16 relay/checkpoint
    ladder.

VRPMS_SUBS=off removes the routes (the router 404s them) and disables
the manager, keeping every pre-subscription response byte-identical.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler

import store
from service import jobs as jobs_mod
from service import obs
from service.helpers import read_json_body, respond_json, send_static_headers
from service.solve import prepare_request
from vrpms_tpu import config
from vrpms_tpu.core import tiers
from vrpms_tpu.core.delta import _DELTA_KEYS, _attr_map, _id_list
from vrpms_tpu.obs import log_event, spans
from vrpms_tpu.sched import DONE, FAILED
from vrpms_tpu.sched import qos as qos_mod


def enabled() -> bool:
    return config.enabled("VRPMS_SUBS")


def debounce_s() -> float:
    return max(0.0, float(config.get("VRPMS_SUB_DEBOUNCE_MS"))) / 1e3


def max_per_tenant() -> int:
    return max(0, int(config.get("VRPMS_SUB_MAX_PER_TENANT")))


def _db():
    # subscription docs are problem-agnostic control-plane rows; any
    # Database instance carries the seam (the jobs-record convention)
    return store.get_database("vrp", None)


#: lineage entries kept on the doc — enough chain for the timeline and
#: stream replay without the doc growing with subscription lifetime
LINEAGE_TAIL = 64

#: create-body keys that configure the SUBSCRIPTION rather than the
#: solve request it wraps
_SUB_KEYS = ("resolveEvery",)

#: non-owner stream poll cadence: a federated watcher cannot park on
#: the owner's generation condition, so it re-reads the store at this
#: bounded interval instead of spinning on unthrottled lookups
_REMOTE_POLL_S = 0.5

#: minimum spacing between keep-alive frames on an idle stream
_KEEPALIVE_S = 2.0


def _compose_delta(cum: dict, new, errors: list) -> dict | None:
    """Compose a newly-posted delta onto an accumulated one (the
    coalescing step, and the fold of fired deltas into the cumulative
    base-relative delta). Shape rules match core.delta's strict apply:
    unknown keys, malformed lists/maps, and duplicate adds/drops are
    contract violations (400), while an add that cancels an
    accumulated drop (or vice versa) nets out — that is exactly the
    no-op a burst is allowed to collapse to."""
    if not isinstance(new, dict):
        errors += [{"what": "Data error", "reason": "'delta' must be an object"}]
        return None
    unknown = [k for k in new if k not in _DELTA_KEYS]
    if unknown:
        errors += [{
            "what": "Data error",
            "reason": f"unknown delta key(s) {unknown}; expected one of "
            f"{list(_DELTA_KEYS)}",
        }]
        return None
    add = _id_list(new, "add", errors)
    drop = _id_list(new, "drop", errors)
    demands = _attr_map(new, "demands", errors)
    windows = _attr_map(new, "timeWindows", errors)
    if add is None or drop is None or demands is None or windows is None:
        return None
    both = [c for c in add if c in drop]
    if both:
        errors += [{
            "what": "Data error",
            "reason": f"delta adds and drops the same id(s) {both}",
        }]
        return None
    out_add = list(cum.get("add") or [])
    out_drop = list(cum.get("drop") or [])
    for cid in add:
        if repr(cid) in {repr(c) for c in out_drop}:
            out_drop = [c for c in out_drop if repr(c) != repr(cid)]
        elif repr(cid) in {repr(c) for c in out_add}:
            errors += [{
                "what": "Data error",
                "reason": f"duplicate add: id {cid!r} is already pending",
            }]
            return None
        else:
            out_add.append(cid)
    for cid in drop:
        if repr(cid) in {repr(c) for c in out_add}:
            out_add = [c for c in out_add if repr(c) != repr(cid)]
        elif repr(cid) in {repr(c) for c in out_drop}:
            errors += [{
                "what": "Data error",
                "reason": f"duplicate drop: id {cid!r} is already pending",
            }]
            return None
        else:
            out_drop.append(cid)
    out_dem = dict(cum.get("demands") or {})
    out_dem.update(demands)
    out_win = dict(cum.get("timeWindows") or {})
    out_win.update(windows)
    out: dict = {}
    if out_add:
        out["add"] = out_add
    if out_drop:
        out["drop"] = out_drop
    if out_dem:
        out["demands"] = out_dem
    if out_win:
        out["timeWindows"] = out_win
    return out


def _merge_bursts(older: dict, newer: dict) -> dict:
    """Fold a claimed-but-unlaunched firing burst back UNDER deltas
    posted while the launch was in flight (the requeue path). Unlike
    `_compose_delta` this merge is lenient about cross-burst repeats:
    the newer burst was validated against an EMPTY pending slot, so a
    re-add of an id the firing burst already adds is idempotent (one
    add), not a contract violation — while add/drop pairs still net
    out and newer attribute rewrites win."""
    out_add = list(older.get("add") or [])
    out_drop = list(older.get("drop") or [])
    for cid in newer.get("add") or []:
        if repr(cid) in {repr(c) for c in out_drop}:
            out_drop = [c for c in out_drop if repr(c) != repr(cid)]
        elif repr(cid) not in {repr(c) for c in out_add}:
            out_add.append(cid)
    for cid in newer.get("drop") or []:
        if repr(cid) in {repr(c) for c in out_add}:
            out_add = [c for c in out_add if repr(c) != repr(cid)]
        elif repr(cid) not in {repr(c) for c in out_drop}:
            out_drop.append(cid)
    out_dem = dict(older.get("demands") or {})
    out_dem.update(newer.get("demands") or {})
    out_win = dict(older.get("timeWindows") or {})
    out_win.update(newer.get("timeWindows") or {})
    out: dict = {}
    if out_add:
        out["add"] = out_add
    if out_drop:
        out["drop"] = out_drop
    if out_dem:
        out["demands"] = out_dem
    if out_win:
        out["timeWindows"] = out_win
    return out


def _prep_fingerprint(prep) -> str | None:
    """The tier-fingerprint cache key content of a prepared request —
    the no-op-delta dedupe identity. The cache attach already computed
    it on the warm-start path; otherwise hash the instance directly.
    Decomposed giants have no fingerprint (by design: materializing the
    padded tensors is what decomposition avoids) — they never dedupe."""
    cache = getattr(prep, "cache", None)
    if isinstance(cache, dict) and cache.get("fingerprint"):
        return cache["fingerprint"]
    inst = getattr(prep, "inst", None)
    if inst is None or getattr(prep, "decomp", None) is not None:
        return None
    try:
        return tiers.fingerprint(inst)
    except Exception:
        return None


class _Sub:
    """In-process runtime state for one subscription: the doc (the
    durable truth, persisted on every mutation) plus the monotonic
    timer deadlines that must not survive a process anyway."""

    __slots__ = ("doc", "fire_at", "cadence_at", "resume_pending")

    def __init__(self, doc: dict):
        self.doc = doc
        self.fire_at: float | None = None  # debounce deadline (mono)
        self.cadence_at: float | None = None  # next cadence fire (mono)
        self.resume_pending = False  # adopted pending → trigger=resume


class SubscriptionManager:
    """The process-wide standing-subscription registry + scheduler.

    One background worker thread serves every subscription's debounce
    and cadence timers (started lazily at the first armed timer); the
    replica heartbeat additionally calls tick() so cadences fire and
    orphaned docs are adopted in fleet mode even when this process
    never sees subscription HTTP traffic."""

    def __init__(self):
        self._lock = threading.RLock()
        self._subs: dict[str, _Sub] = {}  # guarded-by: _lock
        self._gen = threading.Condition(self._lock)  # stream waiters
        self._wake = threading.Event()
        self._halt = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def stop(self) -> None:
        self._halt.set()
        self._wake.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)
        with self._lock:
            self._subs.clear()
            self._gen.notify_all()

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            if self._halt.is_set():
                return
            self._thread = threading.Thread(
                target=self._worker, name="vrpms-subs", daemon=True
            )
            self._thread.start()

    def _worker(self) -> None:
        while not self._halt.is_set():
            self.run_due()
            timeout = 0.5
            with self._lock:
                now = time.monotonic()
                deadlines = [
                    t
                    for sub in self._subs.values()
                    for t in (sub.fire_at, sub.cadence_at)
                    if t is not None
                ]
                if deadlines:
                    timeout = min(0.5, max(0.005, min(deadlines) - now))
            self._wake.wait(timeout)
            self._wake.clear()

    # -- control-plane API (the handlers call these) -----------------------

    def create(self, content: dict) -> tuple[int, dict]:
        resolve_every = content.get("resolveEvery")
        if resolve_every is not None:
            try:
                resolve_every = float(resolve_every)
                if resolve_every <= 0:
                    raise ValueError
            except (TypeError, ValueError):
                return 400, {"success": False, "errors": [{
                    "what": "Data error",
                    "reason": "'resolveEvery' must be a positive number "
                    "of seconds",
                }]}
        if content.get("delta") is not None:
            return 400, {"success": False, "errors": [{
                "what": "Data error",
                "reason": "a subscription's create body takes no 'delta' "
                "— post deltas to /api/subscriptions/{id}/deltas",
            }]}
        base = {k: v for k, v in content.items() if k not in _SUB_KEYS}
        errors: list = []
        ctx = jobs_mod._parse_content(dict(base), errors)
        if ctx is None:
            return 400, {"success": False, "errors": errors}
        tenant = qos_mod.tenant_id(ctx["params"].get("auth"))
        limit = max_per_tenant()
        if limit > 0 and tenant is not None:
            held = self._tenant_count(tenant)
            if held is not None and held >= limit:
                return 429, {"success": False, "errors": [{
                    "what": "Too busy",
                    "reason": "per-tenant standing-subscription quota "
                    "exceeded; delete one or raise "
                    "VRPMS_SUB_MAX_PER_TENANT",
                }]}
        now = time.time()
        doc = {
            "id": uuid.uuid4().hex,
            "content": base,
            "problem": ctx["problem"],
            "algorithm": ctx["algorithm"],
            "resolveEvery": resolve_every,
            "tenant": tenant,
            "qos": jobs_mod.job_qos_class(ctx["opts"]),
            "generation": 0,
            "lastJobId": None,
            "lastFingerprint": None,
            "delta": None,
            "pending": None,
            "pendingCount": 0,
            "pendingAt": None,
            "firing": None,
            "firingCount": 0,
            "lineage": [],
            "status": "active",
            "replicaId": jobs_mod.replica_id(),
            "createdAt": now,
            "updatedAt": now,
        }
        sub = _Sub(doc)
        with self._lock:
            self._subs[doc["id"]] = sub
            if resolve_every is not None:
                sub.cadence_at = time.monotonic() + resolve_every
        _db().put_subscription(doc["id"], doc)
        if resolve_every is not None:
            self._ensure_thread()
            self._wake.set()
        log_event(
            "sub.created",
            subscriptionId=doc["id"],
            problem=doc["problem"],
            algorithm=doc["algorithm"],
            resolveEvery=resolve_every,
        )
        return 201, {
            "success": True,
            "subscriptionId": doc["id"],
            "status": "active",
            "resolveEvery": resolve_every,
        }

    def post_delta(self, sub_id: str, delta) -> tuple[int, dict]:
        with self._lock:
            sub = self._subs.get(sub_id)
        if sub is None:
            sub = self._adopt_from_store(sub_id)
        if sub is None:
            return 404, _not_found(sub_id)
        errors: list = []
        with self._lock:
            if self._subs.get(sub_id) is not sub:
                # deleted (or superseded) between the registry read
                # above and here: composing into the stale doc would
                # persist a row the delete just dropped
                return 404, _not_found(sub_id)
            doc = sub.doc
            pending = _compose_delta(doc.get("pending") or {}, delta, errors)
            if pending is None:
                return 400, {"success": False, "errors": errors}
            first = doc.get("pending") is None
            if not first:
                # every delta beyond the first in this debounce window
                # is one launch the coalescer saved
                obs.SUB_COALESCED.inc()
            doc["pending"] = pending
            doc["pendingCount"] = int(doc.get("pendingCount") or 0) + 1
            doc["pendingAt"] = time.time()
            doc["updatedAt"] = time.time()
            if first:
                # leading-edge debounce: the window opens at the FIRST
                # delta of a burst and is not extended by later ones, so
                # a continuous stream still fires every window
                sub.fire_at = time.monotonic() + debounce_s()
            count = doc["pendingCount"]
            # persist under the lock: a concurrent DELETE must not see
            # this write resurrect the row it just dropped
            _db().put_subscription(sub_id, doc)
        self._ensure_thread()
        self._wake.set()
        log_event(
            "sub.delta", subscriptionId=sub_id, pendingDeltas=count
        )
        return 202, {
            "success": True,
            "subscriptionId": sub_id,
            "pendingDeltas": count,
            "debounceMs": float(config.get("VRPMS_SUB_DEBOUNCE_MS")),
        }

    def lookup(self, sub_id: str) -> dict | None:
        """The doc, live copy preferred (it has the freshest pending
        state); falls back to the store so any replica answers."""
        with self._lock:
            sub = self._subs.get(sub_id)
            if sub is not None:
                return dict(sub.doc)
        doc = _db().get_subscription(sub_id)
        if doc is not None and doc.get("status") == "deleted":
            return None  # tombstone of a delete the store couldn't drop
        return doc

    def delete(self, sub_id: str) -> tuple[int, dict]:
        with self._lock:
            sub = self._subs.pop(sub_id, None)
            if sub is not None:
                # mark the live doc too: an in-flight holder of this
                # reference (post_delta between its lock blocks) must
                # not persist the row back after the store drop below
                sub.doc["status"] = "deleted"
            self._gen.notify_all()  # stream waiters re-check existence
        doc = sub.doc if sub is not None else _db().get_subscription(sub_id)
        if doc is None or (sub is None and doc.get("status") == "deleted"):
            return 404, _not_found(sub_id)
        # cooperative cancel of an in-flight generation (the PR-7
        # cancel flag): the job runs to its cancelled terminal record,
        # so the lineage chain stays intact, the tenant-quota slot is
        # released by the terminal transition, and no queue entry is
        # orphaned — the pending debounce timer died with the registry
        # entry above, so nothing NEW can launch either
        cancel_requested = False
        job_id = doc.get("lastJobId")
        if job_id:
            live = jobs_mod.get_live_job(job_id)
            if (
                live is not None
                and live.status not in (DONE, FAILED)
                and live.sink is not None
            ):
                live.sink.cancel()
                cancel_requested = True
                log_event(
                    "job.cancel_requested", jobId=job_id, via="subscription"
                )
        degraded = False
        if not _db().delete_subscription(sub_id):
            # the row survived a failed store delete — and the sub is
            # already out of the local registry, so without a marker
            # any replica's adoption sweep would resurrect it. Write a
            # status tombstone (every read/adopt path skips those and
            # the sweep retries the hard delete); if even that write
            # fails, tell the client the delete may not stick
            # fleet-wide.
            tomb = dict(
                doc,
                status="deleted",
                pending=None,
                pendingCount=0,
                firing=None,
                firingCount=0,
                updatedAt=time.time(),
            )
            degraded = not _db().put_subscription(sub_id, tomb)
        log_event(
            "sub.deleted",
            subscriptionId=sub_id,
            cancelRequested=cancel_requested,
            generation=doc.get("generation"),
            degraded=degraded,
        )
        body = {
            "success": True,
            "subscriptionId": sub_id,
            "status": "deleted",
            "cancelRequested": cancel_requested,
        }
        if degraded:
            body["degraded"] = True
        return 200, body

    def list(self) -> tuple[int, dict]:
        rows = _db().list_subscriptions()
        degraded = rows is None
        if degraded:
            with self._lock:
                rows = [dict(s.doc) for s in self._subs.values()]
        body = {
            "success": True,
            "subscriptions": sorted(
                (
                    public_view(d)
                    for d in rows
                    if d.get("status") != "deleted"
                ),
                key=lambda v: v.get("createdAt") or 0,
            ),
        }
        if degraded:
            body["degraded"] = True
        return 200, body

    # -- scheduling --------------------------------------------------------

    def tick(self) -> None:
        """The replica-heartbeat (and worker-loop) due-work pass: adopt
        orphaned store docs, then fire due timers."""
        if self._halt.is_set():
            return
        self._adopt_orphans()
        self.run_due()

    def run_due(self) -> None:
        now = time.monotonic()
        due: list[tuple[str, str]] = []
        with self._lock:
            for sub_id, sub in self._subs.items():
                # claim the deadline while the lock is held: run_due is
                # entered concurrently (worker thread + replica
                # heartbeat), and a deadline left armed here would let
                # both collectors launch two generations from one burst
                if sub.fire_at is not None and now >= sub.fire_at:
                    sub.fire_at = None
                    due.append((
                        sub_id, "resume" if sub.resume_pending else "delta"
                    ))
                elif sub.cadence_at is not None and now >= sub.cadence_at:
                    sub.cadence_at = None
                    due.append((sub_id, "cadence"))
        for sub_id, trigger in due:
            if self._halt.is_set():
                return
            try:
                self._fire(sub_id, trigger)
            except Exception as e:
                log_event(
                    "sub.fire_error",
                    subscriptionId=sub_id,
                    error=f"{type(e).__name__}: {e}",
                )
                with self._lock:
                    sub = self._subs.get(sub_id)
                    if sub is None:
                        continue
                    # the claimed deadline must not die with the
                    # exception: put the burst back and re-arm
                    self._requeue(sub)
                    if (
                        trigger == "cadence"
                        and sub.cadence_at is None
                        and sub.doc.get("resolveEvery")
                    ):
                        sub.cadence_at = (
                            time.monotonic()
                            + float(sub.doc["resolveEvery"])
                        )

    def wait_generation(self, sub_id: str, seen_gen: int,
                        timeout: float) -> dict | None:
        """Park until the subscription's generation advances past
        `seen_gen` (or the wait times out / the sub is deleted); returns
        the current doc copy, or None when the sub is gone."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                sub = self._subs.get(sub_id)
                if sub is None:
                    return None
                if int(sub.doc.get("generation") or 0) > seen_gen:
                    return dict(sub.doc)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return dict(sub.doc)
                self._gen.wait(timeout=min(remaining, 1.0))

    def stats(self) -> dict:
        """The fleet-debug block: this replica's standing load."""
        with self._lock:
            count = len(self._subs)
            backlog = sum(
                int(s.doc.get("pendingCount") or 0)
                + int(s.doc.get("firingCount") or 0)
                for s in self._subs.values()
            )
            newest = None
            for s in self._subs.values():
                for hop in s.doc.get("lineage") or []:
                    at = hop.get("at")
                    if at is not None and (newest is None or at > newest):
                        newest = at
        age = None if newest is None else round((time.time() - newest) * 1e3)
        return {
            "count": count,
            "coalescedBacklog": backlog,
            "lastGenerationAgeMs": age,
        }

    # -- internals ---------------------------------------------------------

    def _tenant_count(self, tenant: str) -> int | None:
        rows = _db().list_subscriptions()
        if rows is None:
            # unreadable store fails OPEN (the tenant-quota rule):
            # count what this process knows instead
            with self._lock:
                rows = [s.doc for s in self._subs.values()]
        return sum(
            1
            for d in rows
            if d.get("tenant") == tenant and d.get("status") != "deleted"
        )

    def _adopt_from_store(self, sub_id: str) -> _Sub | None:
        """Adopt one doc on touch (delta posted to a replica that has
        never seen it — restart, or fleet routing): the toucher becomes
        the owner, re-arming cadence from now."""
        doc = _db().get_subscription(sub_id)
        if doc is None or doc.get("status") == "deleted":
            return None
        return self._adopt(doc)

    def _adopt(self, doc: dict) -> _Sub:
        with self._lock:
            sub = self._subs.get(doc["id"])
            if sub is not None:
                return sub
            if doc.get("firing"):
                # the previous owner died between claiming a burst into
                # the firing slot and completing the launch: fold the
                # claim back under any later-posted pending so the
                # resume generation still carries it
                doc["pending"] = _merge_bursts(
                    doc["firing"], doc.get("pending") or {}
                )
                doc["pendingCount"] = (
                    int(doc.get("firingCount") or 0)
                    + int(doc.get("pendingCount") or 0)
                )
                doc["firing"] = None
                doc["firingCount"] = 0
            sub = _Sub(doc)
            self._subs[doc["id"]] = sub
            if doc.get("resolveEvery"):
                sub.cadence_at = time.monotonic() + float(doc["resolveEvery"])
            if doc.get("pending") is not None:
                # pending state from a drained/crashed owner fires as a
                # resume generation at once — the burst already waited
                # its debounce window somewhere else
                sub.resume_pending = True
                sub.fire_at = time.monotonic()
        doc["replicaId"] = jobs_mod.replica_id()
        _db().put_subscription(doc["id"], doc)
        self._ensure_thread()
        self._wake.set()
        log_event("sub.adopted", subscriptionId=doc["id"])
        return sub

    def _adopt_orphans(self) -> None:
        """Fleet sweep: take over docs whose owning replica left the
        membership ring (drain/crash). Single-process (local-queue)
        mode adopts everything — there is no other owner."""
        rows = _db().list_subscriptions()
        if rows is None:
            return
        mine = jobs_mod.replica_id()
        members = None
        if jobs_mod.dist_queue_enabled():
            rep = jobs_mod._replica
            ring = rep.ring() if rep is not None else None
            if ring is not None:
                members = set(ring.members)
        for doc in rows:
            if doc.get("status") == "deleted":
                # tombstone of a delete whose hard drop failed: never
                # resurrect it — retry the drop as sweep hygiene
                _db().delete_subscription(doc.get("id"))
                continue
            with self._lock:
                if doc.get("id") in self._subs:
                    continue
            owner = doc.get("replicaId")
            if jobs_mod.dist_queue_enabled():
                if members is None:
                    # no membership view yet: only reclaim our own docs
                    if owner != mine:
                        continue
                elif owner in members and owner != mine:
                    continue  # the owner is alive — not ours to take
            self._adopt(doc)

    def _fire(self, sub_id: str, trigger: str) -> None:
        """Launch one generation (or absorb a no-op burst). Runs on the
        worker/tick thread; the manager lock is held only around doc
        mutation, never across the parse/prepare/submit work."""
        with self._lock:
            sub = self._subs.get(sub_id)
            if sub is None:
                return
            doc = sub.doc
            if jobs_mod.is_draining():
                # fire nothing into a draining replica: the doc (with
                # its pending delta) is already durable — stop the
                # timers so a peer's adoption sweep takes over
                self._requeue(sub, persist=False)
                sub.fire_at = None
                sub.cadence_at = None
                return
            if doc.get("firing"):
                # leftover claim from a fire that died mid-launch:
                # fold it back before claiming the current burst
                self._requeue(sub, persist=False)
            if doc.get("pending") is None and trigger != "cadence":
                # spurious wake: the burst was consumed or requeued by
                # a competing path already — nothing to fire
                sub.resume_pending = False
                return
            # claim the burst into the firing slot: doc['pending'] is
            # free again, so a delta posted while this launch is in
            # flight opens a NEW debounce window (post_delta sees it
            # as the first of a burst and arms fire_at) instead of
            # composing into state the completion path clears
            firing = doc.get("pending")
            firing_count = int(doc.get("pendingCount") or 0)
            doc["firing"] = firing
            doc["firingCount"] = firing_count
            doc["pending"] = None
            doc["pendingCount"] = 0
            doc["pendingAt"] = None
            errors: list = []
            effective = _compose_delta(
                doc.get("delta") or {}, firing or {}, errors
            )
            if effective is None:
                # the pending burst conflicts with the accumulated
                # delta (e.g. re-adding an id a fired generation
                # already added): poison — drop it, keep the sub alive
                self._absorb(sub, doc.get("delta"), errors=errors)
                return
            last_id = doc.get("lastJobId")
            generation = int(doc.get("generation") or 0)
            pending_count = firing_count
            sub.fire_at = None
            sub.resume_pending = False
            if trigger == "cadence" and doc.get("resolveEvery"):
                sub.cadence_at = (
                    time.monotonic() + float(doc["resolveEvery"])
                )
        # predecessor still solving? Deltas cancel-and-resolve (the
        # /resolve semantic: the successor seeds from the cancelled
        # run's final incumbent); cadences just wait their turn.
        live = jobs_mod.get_live_job(last_id) if last_id else None
        if live is not None and live.status not in (DONE, FAILED):
            if trigger == "cadence":
                with self._lock:
                    if sub_id in self._subs:
                        self._requeue(sub)
                        sub.cadence_at = time.monotonic() + 0.25
                return
            if live.sink is not None:
                live.sink.cancel()
            live.wait(timeout=float(config.get("VRPMS_RESOLVE_WAIT_S")))
            if not live.done_event.is_set():
                with self._lock:
                    if sub_id in self._subs:
                        self._requeue(sub)
                        sub.fire_at = (
                            time.monotonic() + max(debounce_s(), 0.25)
                        )
                return
        content = dict(doc["content"])
        if effective:
            content["delta"] = effective
        if last_id:
            content["warmStart"] = {"jobId": last_id}
        errors = []
        ctx = jobs_mod._parse_content(content, errors)
        prep = None
        if ctx is not None:
            prep = prepare_request(
                ctx["problem"], ctx["algorithm"], ctx["params"],
                ctx["opts"], ctx["algo_params"], ctx["locations"],
                ctx["durations"], errors, ctx["database"],
            )
        if prep is None or errors:
            # dataset drift / poison delta: the generation cannot be
            # built — record why, drop the pending burst (keeping it
            # would wedge the subscription forever), keep the sub alive
            with self._lock:
                if sub_id in self._subs:
                    self._absorb(sub, doc.get("delta"), errors=errors)
            log_event(
                "sub.generation_rejected",
                subscriptionId=sub_id,
                errors=[e.get("reason") for e in errors][:4],
            )
            return
        fingerprint = _prep_fingerprint(prep)
        if (
            trigger != "cadence"
            and fingerprint is not None
            and fingerprint == doc.get("lastFingerprint")
        ):
            # no-op burst: the post-delta instance IS the previous
            # generation's instance (tier-fingerprint identity) — fold
            # the delta in, launch nothing
            obs.SUB_COALESCED.inc()
            with self._lock:
                if sub_id in self._subs:
                    self._absorb(sub, effective or None)
            log_event(
                "sub.noop_delta",
                subscriptionId=sub_id,
                generation=generation,
                coalesced=pending_count,
            )
            return
        trace = spans.start_trace(None)
        root = None
        tokens = None
        if trace is not None:
            root = trace.span("sub.generation")
            root.set(
                subscriptionId=sub_id,
                generation=generation + 1,
                trigger=trigger,
            )
            tokens = spans.activate(trace, root)
        code, body = 0, {}
        try:
            code, body = jobs_mod.submit_headless(
                ctx,
                resolve_from=last_id,
                prepared=prep,
                request_id=obs.new_request_id(),
                trace=trace,
                trace_root=root,
            )
        finally:
            if trace is not None:
                status = None if code and code < 400 else "error"
                root.end(status=status)
                spans.deactivate(tokens)
                if not trace.deferred:
                    trace.finish(
                        status="ok" if code and code < 400 else "error"
                    )
        job_id = body.get("jobId")
        if code in (200, 201, 202) and job_id:
            obs.SUB_GENERATIONS.labels(trigger=trigger).inc()
            log_event(
                "sub.generation",
                subscriptionId=sub_id,
                generation=generation + 1,
                jobId=job_id,
                trigger=trigger,
                resolvedFrom=last_id,
                coalesced=max(0, pending_count - 1),
            )
            with self._lock:
                if sub_id not in self._subs:
                    return  # deleted mid-launch: the job runs terminal
                doc["generation"] = generation + 1
                doc["lastJobId"] = job_id
                doc["lastFingerprint"] = fingerprint
                doc["delta"] = effective or None
                # only the CLAIMED burst is consumed: doc['pending']
                # may hold deltas posted mid-launch whose debounce
                # timer is already armed — they fire the next
                # generation, never silently cleared here
                doc["firing"] = None
                doc["firingCount"] = 0
                doc["lastError"] = None
                doc["updatedAt"] = time.time()
                lineage = list(doc.get("lineage") or [])
                lineage.append({
                    "generation": generation + 1,
                    "jobId": job_id,
                    "trigger": trigger,
                    "resolvedFrom": last_id,
                    "at": time.time(),
                })
                doc["lineage"] = lineage[-LINEAGE_TAIL:]
                self._gen.notify_all()
                # persist under the lock (the _absorb idiom): a DELETE
                # landing after the membership check above must not see
                # its store row resurrected by this write
                _db().put_subscription(sub_id, doc)
        elif code in (429, 503):
            # backpressure: the claimed burst goes back to pending and
            # retries after another debounce window — never dropped,
            # never doubled
            with self._lock:
                if sub_id in self._subs:
                    doc["lastError"] = body.get("errors")
                    self._requeue(sub)
                    if trigger == "cadence" and doc.get("resolveEvery"):
                        sub.cadence_at = min(
                            sub.cadence_at or float("inf"),
                            time.monotonic() + max(debounce_s(), 0.25),
                        )
                    else:
                        sub.fire_at = (
                            time.monotonic() + max(debounce_s(), 0.25)
                        )
            self._wake.set()
        else:
            with self._lock:
                if sub_id in self._subs:
                    self._absorb(
                        sub, doc.get("delta"), errors=body.get("errors")
                    )
            log_event(
                "sub.generation_rejected",
                subscriptionId=sub_id,
                code=code,
            )

    def _absorb(self, sub: _Sub, delta, errors=None) -> None:
        """Finish a CLAIMED burst without a launch (poison, no-op
        dedupe, hard submit rejection): fold `delta` in as the new
        cumulative and drop the firing slot. doc['pending'] is not
        touched — it may hold deltas posted while the claim was in
        flight, and their debounce timer is already armed. Caller
        holds the lock."""
        doc = sub.doc
        doc["delta"] = delta
        doc["firing"] = None
        doc["firingCount"] = 0
        if errors:
            doc["lastError"] = errors
        doc["updatedAt"] = time.time()
        _db().put_subscription(doc["id"], doc)

    def _requeue(self, sub: _Sub, persist: bool = True) -> None:
        """Fold a claimed-but-unlaunched firing burst back into
        doc['pending'] — UNDER any deltas posted while the launch was
        in flight — and re-arm its debounce timer, so the retry and
        crash-recovery paths never drop a claimed burst. Caller holds
        the lock."""
        doc = sub.doc
        firing = doc.get("firing")
        if firing is None and not doc.get("firingCount"):
            return
        if firing is not None:
            doc["pending"] = _merge_bursts(
                firing, doc.get("pending") or {}
            )
            doc["pendingCount"] = (
                int(doc.get("firingCount") or 0)
                + int(doc.get("pendingCount") or 0)
            )
            doc["pendingAt"] = doc.get("pendingAt") or time.time()
        doc["firing"] = None
        doc["firingCount"] = 0
        doc["updatedAt"] = time.time()
        if doc.get("pending") is not None and sub.fire_at is None:
            sub.fire_at = time.monotonic() + max(debounce_s(), 0.25)
        if persist:
            _db().put_subscription(doc["id"], doc)


def public_view(doc: dict) -> dict:
    """The response shape of a subscription doc: everything a client
    steers by, minus the (possibly large) base content and the internal
    fingerprint/replica fields."""
    view = {
        "subscriptionId": doc.get("id"),
        "problem": doc.get("problem"),
        "algorithm": doc.get("algorithm"),
        "resolveEvery": doc.get("resolveEvery"),
        "generation": int(doc.get("generation") or 0),
        "lastJobId": doc.get("lastJobId"),
        "pendingDeltas": int(doc.get("pendingCount") or 0),
        "lineage": list(doc.get("lineage") or []),
        "status": doc.get("status") or "active",
        "createdAt": doc.get("createdAt"),
        "updatedAt": doc.get("updatedAt"),
    }
    if doc.get("lastError"):
        view["lastError"] = doc["lastError"]
    return view


def _not_found(sub_id: str) -> dict:
    return {
        "success": False,
        "errors": [{
            "what": "Not found",
            "reason": f"no subscription with id '{sub_id}'",
        }],
    }


_mgr: SubscriptionManager | None = None
_mgr_lock = threading.Lock()


def manager() -> SubscriptionManager:
    global _mgr
    with _mgr_lock:
        if _mgr is None:
            _mgr = SubscriptionManager()
        return _mgr


def reset() -> None:
    """Park and forget the manager (tests, scheduler shutdown): timers
    stop, in-memory registry clears; the store docs — the durable truth
    — are untouched and re-adopted on the next touch/tick."""
    global _mgr
    with _mgr_lock:
        m, _mgr = _mgr, None
    if m is not None:
        m.stop()


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------


def _sub_id_from_path(path: str) -> str:
    """The {id} segment of /api/subscriptions/{id}[/deltas|/stream]."""
    parts = [p for p in path.split("?", 1)[0].split("/") if p]
    if parts and parts[-1] in ("deltas", "stream"):
        parts = parts[:-1]
    return parts[-1] if parts else ""


def _answer(handler, code: int, body: dict) -> None:
    """Envelope responder with the repo's error-accounting convention:
    contract rejections (400) and sheds (429) count in ERROR_KINDS like
    fail()/too_busy() would; 404s only mark the access-log line."""
    if code >= 400:
        kinds = [
            e.get("what", "unknown") for e in body.get("errors") or []
        ] or ["error"]
        handler._obs_errors = sorted(set(kinds))
        if code != 404:
            for what in kinds:
                obs.ERROR_KINDS.labels(what=what).inc()
    respond_json(handler, code, body)


class SubscriptionsHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """POST /api/subscriptions — create a standing subscription;
    GET — list the fleet's standing subscriptions."""

    def do_POST(self):
        obs.begin_request_obs(self)
        try:
            content = read_json_body(self)
            if content is None:
                return
            code, body = manager().create(content)
            _answer(self, code, body)
        finally:
            obs.end_request_obs(self)

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            code, body = manager().list()
            _answer(self, code, body)
        finally:
            obs.end_request_obs(self)


class SubscriptionDetailHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/subscriptions/{id} — the doc view (any replica);
    DELETE — cancel the in-flight generation cooperatively and remove
    the subscription (terminal records + lineage survive)."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            sub_id = _sub_id_from_path(self.path)
            doc = manager().lookup(sub_id)
            if doc is None:
                _answer(self, 404, _not_found(sub_id))
                return
            _answer(self, 200, {
                "success": True, "subscription": public_view(doc),
            })
        finally:
            obs.end_request_obs(self)

    def do_DELETE(self):
        obs.begin_request_obs(self)
        try:
            code, body = manager().delete(_sub_id_from_path(self.path))
            _answer(self, code, body)
        finally:
            obs.end_request_obs(self)


class SubscriptionDeltasHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """POST /api/subscriptions/{id}/deltas — feed a dataset change; the
    debounced/coalesced burst becomes one re-solve generation."""

    def do_POST(self):
        obs.begin_request_obs(self)
        try:
            content = read_json_body(self)
            if content is None:
                return
            delta = content.get("delta", content)
            code, body = manager().post_delta(
                _sub_id_from_path(self.path), delta
            )
            _answer(self, code, body)
        finally:
            obs.end_request_obs(self)


class SubscriptionStreamHandler(obs.RequestObsMixin, BaseHTTPRequestHandler):
    """GET /api/subscriptions/{id}/stream — every generation's
    incumbents as Server-Sent Events, across generations and replicas.

    Event ids are "{generation}:{block}" ("{generation}:end" for a
    generation's terminal frame), so Last-Event-ID replay resumes the
    CHAIN, not just one job: terminal generations the client missed
    replay from their records, then the live generation follows through
    the local progress sink or — non-owner, federation on — the PR-16
    relay/checkpoint ladder."""

    def do_GET(self):
        obs.begin_request_obs(self, sample="header")
        try:
            self._stream()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-stream: nothing to answer
        finally:
            obs.end_request_obs(self)

    def _emit(self, name: str, payload: dict, event_id=None) -> None:
        frame = f"event: {name}\n"
        if event_id is not None:
            frame += f"id: {event_id}\n"
        frame += f"data: {json.dumps(payload)}\n\n"
        self.wfile.write(frame.encode("utf-8"))
        try:
            self.wfile.flush()
        except Exception:
            pass

    @staticmethod
    def _parse_last(header) -> int:
        """Last-Event-ID "{gen}:{block}" -> the last FULLY-streamed
        generation (a mid-generation id replays that generation's
        terminal again — the != dedupe rule: duplicates beat gaps)."""
        if not header:
            return 0
        try:
            gen_s, _, block_s = str(header).partition(":")
            gen = int(gen_s)
            return gen if block_s == "end" else gen - 1
        except (TypeError, ValueError):
            return 0

    def _snap(self, job_id: str):
        """The freshest incumbent view of one generation job: the local
        sink when this replica owns it, else the federated ladder."""
        live = jobs_mod.get_live_job(job_id)
        if live is not None and live.sink is not None:
            return live.sink.snapshot(), live.status
        if jobs_mod._federation_enabled():
            snap = jobs_mod._relay_snap(job_id)
            if snap is not None:
                obs.FEDERATED_READS.labels(source="relay").inc()
                return snap, None
            snap, degraded = jobs_mod._checkpoint_incumbent(job_id)
            if degraded:
                obs.FEDERATED_READS.labels(source="degraded").inc()
            elif snap is not None:
                obs.FEDERATED_READS.labels(source="checkpoint").inc()
                return snap, None
        return None, None

    def _stream(self):
        sub_id = _sub_id_from_path(self.path)
        mgr = manager()
        doc = mgr.lookup(sub_id)
        if doc is None:
            _answer(self, 404, _not_found(sub_id))
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        send_static_headers(self)
        self.end_headers()
        last_gen = self._parse_last(self.headers.get("Last-Event-ID"))
        self._emit("subscription", {
            "subscriptionId": sub_id,
            "generation": int(doc.get("generation") or 0),
            "resolveEvery": doc.get("resolveEvery"),
        })
        deadline = (
            time.monotonic() + float(config.get("VRPMS_STREAM_TIMEOUT_S"))
        )
        db = _db()
        seen_gen = last_gen
        last_block = -1
        last_beat = time.monotonic()
        while time.monotonic() < deadline:
            if doc is None:
                self._emit("deleted", {"subscriptionId": sub_id})
                return
            cur_gen = int(doc.get("generation") or 0)
            # replay every terminal generation the client has not seen
            # (all but the newest are terminal by construction: a new
            # generation only launches once its predecessor ended)
            for hop in doc.get("lineage") or []:
                gen = int(hop.get("generation") or 0)
                if gen <= seen_gen or gen >= cur_gen:
                    continue
                self._emit_terminal(db, gen, hop)
                seen_gen = gen
                last_block = -1
            if cur_gen > seen_gen:
                # the newest generation: follow it live
                job_id = doc.get("lastJobId")
                snap, status = (None, None)
                if job_id:
                    snap, status = self._snap(job_id)
                if snap is not None and snap.get("block") != last_block:
                    last_block = snap.get("block")
                    self._emit(
                        "progress",
                        dict(snap, generation=cur_gen, jobId=job_id),
                        event_id=f"{cur_gen}:{last_block}",
                    )
                if status in (DONE, FAILED) or (
                    job_id and jobs_mod.get_live_job(job_id) is None
                ):
                    hop = (doc.get("lineage") or [{}])[-1]
                    self._emit_terminal(db, cur_gen, hop)
                    seen_gen = cur_gen
                    last_block = -1
            fresh = mgr.wait_generation(
                sub_id, seen_gen,
                timeout=min(2.0, max(0.05, deadline - time.monotonic())),
            )
            if fresh is None:
                # deleted while parked — or simply not registered on
                # this replica. wait_generation cannot park on a sub
                # this replica does not own, so sleep a bounded
                # interval before re-reading the store: a federated
                # watcher polls at _REMOTE_POLL_S, never spins
                time.sleep(min(
                    _REMOTE_POLL_S,
                    max(0.0, deadline - time.monotonic()),
                ))
                fresh = mgr.lookup(sub_id)
            doc = fresh
            if doc is not None and int(doc.get("generation") or 0) <= seen_gen:
                now = time.monotonic()
                if now - last_beat >= _KEEPALIVE_S:
                    self._emit("keep-alive", {"generation": seen_gen})
                    last_beat = now
        self._emit("timeout", {
            "subscriptionId": sub_id, "generation": seen_gen,
        })

    def _emit_terminal(self, db, gen: int, hop: dict) -> None:
        job_id = hop.get("jobId")
        errors: list = []
        record = db.get_job(job_id, errors) if job_id else None
        payload = {
            "generation": gen,
            "jobId": job_id,
            "trigger": hop.get("trigger"),
            "resolvedFrom": hop.get("resolvedFrom"),
        }
        if record is not None:
            payload["status"] = record.get("status")
            if record.get("incumbent"):
                payload["incumbent"] = record["incumbent"]
            if record.get("resolvedFrom"):
                payload["resolvedFrom"] = record["resolvedFrom"]
        self._emit("generation", payload, event_id=f"{gen}:end")
