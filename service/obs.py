"""Service observability: concrete instruments, /metrics, request logs.

The generic primitives live in vrpms_tpu.obs (registry/logging/trace);
this module owns everything service-shaped:

  * the process REGISTRY and every instrument the request path records
    (requests by route/algorithm/outcome, error-envelope kinds,
    warm-start hit/miss, solve/polish latency, evals, body sizes);
  * scrape-time gauges (uptime, attached devices, backend + compile
    cache info) refreshed on each GET /metrics, never on the hot path;
  * MetricsHandler — the GET /metrics route (Prometheus text format);
  * RequestObsMixin — the one log_request/log_error hook shared by the
    router and every endpoint handler, replacing the old silenced
    log_message overrides with a structured JSON access line + the
    request counter.

Instrumentation stays out of the solve hot path: counters/histograms
are lock-guarded floats recorded once per request, and nothing here
runs unless a request arrives or /metrics is scraped.
"""

from __future__ import annotations

import time
from http.server import BaseHTTPRequestHandler

from vrpms_tpu.obs import (
    Registry,
    log_event,
    new_request_id,
    reset_request_id,
    set_request_id,
    spans,
)

REGISTRY = Registry()

REQUESTS = REGISTRY.counter(
    "vrpms_requests_total",
    "HTTP requests by route, algorithm, and outcome (ok|error)",
    labels=("route", "algorithm", "outcome"),
)
ERROR_KINDS = REGISTRY.counter(
    "vrpms_error_envelope_total",
    "Error entries returned in 400 envelopes, by kind ('what')",
    labels=("what",),
)
WARMSTART = REGISTRY.counter(
    "vrpms_warmstart_lookups_total",
    "Warm-start checkpoint lookups by outcome (hit|miss)",
    labels=("outcome",),
)
SOLVE_SECONDS = REGISTRY.histogram(
    "vrpms_solve_seconds",
    "End-to-end solve wall time (dispatch + anneal + polish), seconds",
    labels=("problem", "algorithm"),
)
POLISH_SECONDS = REGISTRY.histogram(
    "vrpms_polish_seconds",
    "localSearch delta-descent polish wall time, seconds",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
)
SOLVE_EVALS = REGISTRY.histogram(
    "vrpms_solve_evals",
    "Candidate evaluations performed per solve",
    buckets=(1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, 1e10),
)
BODY_BYTES = REGISTRY.histogram(
    "vrpms_request_body_bytes",
    "POST request body size, bytes",
    buckets=(256, 1024, 4096, 16384, 65536, 262144, 1048576, 8388608),
)
SCHED_QUEUE_DEPTH = REGISTRY.gauge(
    "vrpms_sched_queue_depth",
    "Jobs waiting in the scheduler admission queue, by backend",
    labels=("backend",),
)
SCHED_QUEUE_WAIT = REGISTRY.histogram(
    "vrpms_sched_queue_wait_seconds",
    "Time jobs spent queued before their solve started, seconds",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0),
)
SCHED_BATCH_SIZE = REGISTRY.histogram(
    "vrpms_sched_batch_size",
    "Jobs merged into one scheduler launch (1 = solo)",
    buckets=(1, 2, 4, 8, 16, 32, 64),
)
DECOMP_SHARDS = REGISTRY.histogram(
    "vrpms_decomp_shards",
    "Shards one giant-instance decomposed solve was partitioned into "
    "(core.decompose; recorded once per decomposed request)",
    buckets=(2, 4, 8, 16, 32, 64, 128),
)
DECOMP_LAUNCHES = REGISTRY.histogram(
    "vrpms_decomp_launches",
    "Vmapped batched launches one decomposed solve dispatched its "
    "shards as (ceil(shards / VRPMS_SCHED_MAX_BATCH) when healthy — "
    "a value near the shard count means batching degraded to solo "
    "solves)",
    buckets=(1, 2, 4, 8, 16, 32),
)
DECOMP_BOUNDARY = REGISTRY.histogram(
    "vrpms_decomp_boundary_customers",
    "Customers in the cross-shard boundary band repaired by the "
    "stitch pass (re-opt solve or capacity-aware reinsertion)",
    buckets=(0, 8, 16, 32, 64, 128, 256, 512),
)
QOS_QUEUE_WAIT = REGISTRY.histogram(
    "vrpms_qos_queue_wait_seconds",
    "Time jobs spent queued before their solve started, by QoS class "
    "(the per-class view of vrpms_sched_queue_wait_seconds — under "
    "overload interactive should stay in the low buckets while batch "
    "absorbs the wait)",
    labels=("qos",),
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0),
)
SHED_TOTAL = REGISTRY.counter(
    "vrpms_jobs_shed_total",
    "Requests shed without solving, by reason (queue_full = admission "
    "bound / class shed fraction reached, tenant_quota = per-tenant "
    "fairness quota, deadline_exhausted = the deadline budget was "
    "already fully spent in queue wait) and QoS class",
    labels=("reason", "qos"),
)
SCHED_REJECTS = REGISTRY.counter(
    "vrpms_sched_rejected_total",
    "Jobs the scheduler refused or failed without solving, by reason "
    "(queue_full|deadline_spent|shutdown|tenant_quota)",
    labels=("reason",),
)
JOBS_TOTAL = REGISTRY.counter(
    "vrpms_jobs_total",
    "Scheduler jobs reaching a terminal state, by outcome (done|failed)",
    labels=("outcome",),
)
JOBS_RUNNING = REGISTRY.gauge(
    "vrpms_jobs_running",
    "Async jobs currently executing on a device worker (live in-process "
    "view); refreshed per scrape",
)
INCUMBENT_GAP = REGISTRY.gauge(
    "vrpms_incumbent_gap",
    "Last published incumbent gap vs the instance's quick lower bound "
    "(io.bounds.quick_lower_bound), per job class — the live answer to "
    "'how good are the solutions we are currently shipping'",
    labels=("problem", "algorithm"),
)
PROGRESS_EVENTS = REGISTRY.counter(
    "vrpms_progress_events_total",
    "Incumbent progress snapshots published by running solves "
    "(improving block boundaries; the SSE stream's event source)",
)
RESOLVE = REGISTRY.counter(
    "vrpms_resolve_total",
    "Dynamic re-solve warm-seed resolutions by seed source (tour = "
    "inline giant tour, job = a prior job's result record, fingerprint "
    "= cache family index entry, miss = no usable seed — solved cold)",
    labels=("seed_source",),
)
JOBS_FAILED = REGISTRY.counter(
    "vrpms_jobs_failed_total",
    "Job failures by cause (runner = runner exception, crash = worker "
    "crashed twice on the job)",
    labels=("reason",),
)
DIST_CLAIMS = REGISTRY.counter(
    "vrpms_dist_claims_total",
    "Distributed-queue claims by this replica, by kind (own = the job's "
    "tier hashed into this replica's ring arc — the compile-affinity "
    "path; steal = off-arc work taken because the own arc was empty) "
    "and batch (multi = leased as part of a claim-K batch, solo = a "
    "single-entry claim)",
    labels=("kind", "batch"),
)
DIST_CLAIM_BATCH = REGISTRY.histogram(
    "vrpms_dist_claim_batch_size",
    "Entries leased per store claim (claim-K micro-batching; 1 = the "
    "shared queue held no same-token batch-mate)",
    buckets=(1, 2, 4, 8, 16, 32),
)
DIST_CLAIM_CONFLICTS = REGISTRY.counter(
    "vrpms_dist_claim_conflicts_total",
    "Conditional claim/reclaim updates that lost the race to another "
    "replica (the exactly-once arbitration firing, not an error)",
)
DIST_LEASES = REGISTRY.counter(
    "vrpms_dist_lease_events_total",
    "Lease lifecycle events (renewed | reclaimed = an expired peer "
    "lease re-queued | expired_dead = reclaimed past the attempt "
    "ceiling, failed clean | lost = this replica's lease was taken — "
    "its result is discarded | nack = entry returned, local admission "
    "full | ack_lost = terminal ack refused, record not published | "
    "drain_requeued = checkpoint-and-nacked to a peer by a graceful "
    "drain)",
    labels=("event",),
)
DIST_QUEUE_DEPTH = REGISTRY.gauge(
    "vrpms_dist_queue_depth",
    "Unleased jobs waiting in the SHARED store-backed queue (the "
    "cross-replica backpressure signal); refreshed per scrape",
)
FLEET_DESIRED = REGISTRY.gauge(
    "vrpms_fleet_desired_replicas",
    "The elastic-fleet controller's desired replica count (backlog "
    "work-seconds vs deadline headroom, hysteresis + cooldown damped "
    "— the external-metric a k8s HPA should track); refreshed per "
    "scrape, frozen at the last-known value while the store is "
    "unreadable",
)
AUTOSCALE_TOTAL = REGISTRY.counter(
    "vrpms_autoscale_total",
    "Elastic-fleet controller events (up|down = the recommendation "
    "changed, frozen = one degraded observation — store unreadable, "
    "last-known value served, churn_warm = a ring membership change "
    "triggered inherited-tier pre-warm, scalein = a scale-in victim "
    "was chosen and drained)",
    labels=("event",),
)
WORKER_RESTARTS = REGISTRY.counter(
    "vrpms_sched_worker_restarts_total",
    "Watchdog worker restarts, by backend and reason (died|wedged)",
    labels=("backend", "reason"),
)
CKPT_TOTAL = REGISTRY.counter(
    "vrpms_ckpt_total",
    "Durable solve-checkpoint events (written = one checkpoint row "
    "persisted, resumed = a reclaimed/requeued/drained attempt seeded "
    "from a checkpoint, dropped = a capture or write failed — "
    "fail-open, the solve is unaffected)",
    labels=("outcome",),
)
READ_CACHE = REGISTRY.counter(
    "vrpms_read_cache_total",
    "Job-read cache lookups on the distributed queue (hit = served "
    "from a fresh memo, miss = no memo — store read, stale = memo "
    "past VRPMS_READ_TTL_MS — refetched); local-queue mode and "
    "TTL=0 never touch the cache",
    labels=("outcome",),
)
SUB_GENERATIONS = REGISTRY.counter(
    "vrpms_sub_generations_total",
    "Standing-subscription re-solve generations launched, by trigger "
    "(delta = a coalesced delta burst, cadence = the resolveEvery "
    "timer, resume = a drain/crash adoption re-armed the schedule)",
    labels=("trigger",),
)
SUB_COALESCED = REGISTRY.counter(
    "vrpms_sub_coalesced_total",
    "Deltas absorbed into an already-pending generation (every delta "
    "beyond the first in one VRPMS_SUB_DEBOUNCE_MS window) plus no-op "
    "bursts deduped by tier fingerprint before any solver launch",
)
FEDERATED_READS = REGISTRY.counter(
    "vrpms_federated_reads_total",
    "Job reads answered fleet-wide, by incumbent source (live = this "
    "replica owns the solve, checkpoint = overlay from the durable "
    "checkpoint row, relay = live progress fetched from the owning "
    "replica, degraded = store/owner unreachable — marked, never a "
    "500)",
    labels=("source",),
)
SCHED_REQUEUES = REGISTRY.counter(
    "vrpms_sched_requeues_total",
    "In-flight jobs re-admitted after a worker crash (once per job max)",
)
STORE_FAILURES = REGISTRY.counter(
    "vrpms_store_call_failures_total",
    "Backend store call failures, by backend kind and reason "
    "(error|timeout)",
    labels=("kind", "reason"),
)
STORE_RETRIES = REGISTRY.counter(
    "vrpms_store_retries_total",
    "Store read retries after a failed attempt, by backend kind",
    labels=("kind",),
)
STORE_FALLBACKS = REGISTRY.counter(
    "vrpms_store_fallbacks_total",
    "Degraded-mode serves, by backend kind and source (cache = read "
    "from last-known rows, journal = write spooled for replay)",
    labels=("kind", "source"),
)
STORE_REPLAYS = REGISTRY.counter(
    "vrpms_store_journal_replayed_total",
    "Spooled writes replayed into the recovered backend, by kind",
    labels=("kind",),
)
AUTH_FAILURES = REGISTRY.counter(
    "vrpms_store_auth_failures_total",
    "JWT set_session failures swallowed at store construction "
    "(requests likely doomed to row-level-security errors)",
)
STORE_CIRCUIT_STATE = REGISTRY.gauge(
    "vrpms_store_circuit_state",
    "Circuit breaker state per backend kind (0=closed, 1=half-open, "
    "2=open); refreshed per scrape",
    labels=("kind",),
)
STORE_JOURNAL_DEPTH = REGISTRY.gauge(
    "vrpms_store_journal_depth",
    "Writes spooled in the in-memory journal awaiting replay, by kind",
    labels=("kind",),
)
COMPILE_TOTAL = REGISTRY.counter(
    "vrpms_compile_total",
    "XLA backend compiles performed by this process (cache hits emit "
    "nothing — with shape tiering + the persistent cache this should "
    "flatline after warmup)",
)
COMPILE_SECONDS = REGISTRY.histogram(
    "vrpms_compile_seconds",
    "XLA backend compile durations",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
)
TIER_CACHE = REGISTRY.counter(
    "vrpms_tier_cache_total",
    "Shape-tier canonicalization outcomes: hit = this padded shape "
    "signature was already seen by the process (compiled programs "
    "available), miss = first sighting (the solve may pay compiles)",
    labels=("outcome",),
)
CACHE_LOOKUPS = REGISTRY.counter(
    "vrpms_cache_lookups_total",
    "Content-addressed solution-cache LOOKUP outcomes (exact = "
    "identical entry found — served without solving unless the request "
    "demanded fresh telemetry; near = a similar cached tour was found "
    "to seed from, applied only if the job dispatches solo — "
    "stats.cache.seeded tells per request; warm = explicit warmStart "
    "retrieval via the family index; miss = solved cold)",
    labels=("outcome",),
)
CACHE_SOLVES_AVOIDED = REGISTRY.counter(
    "vrpms_cache_solves_avoided_total",
    "Requests served entirely from the solution cache (exact hits): "
    "each one cost a store read instead of a metaheuristic solve",
)
CACHE_EVICTIONS = REGISTRY.counter(
    "vrpms_cache_evictions_total",
    "Entries LRU-evicted from the in-memory solution-cache tier "
    "(bounded by the VRPMS_CACHE entry cap)",
)
BUILD_INFO = REGISTRY.gauge(
    "vrpms_build_info",
    "Constant 1, labeled with the package version, jax version, "
    "backend platform, and this process's replica identity — correlate "
    "deploys (and fleet members) with behavior shifts",
    labels=("version", "jaxVersion", "platform", "replicaId"),
)
TRACE_RING_SIZE = REGISTRY.gauge(
    "vrpms_trace_ring_size",
    "Completed traces currently retained in the debug ring "
    "(GET /api/debug/traces); refreshed per scrape",
)
TRACE_EXPORT = REGISTRY.counter(
    "vrpms_trace_export_total",
    "Spans offered to the durable trace exporter, by outcome (ok = "
    "batch-written to the store's trace_spans seam, dropped = export "
    "queue overflow or an oversized trace document, failed = the store "
    "write failed — single-attempt, fail-open). Every offered span is "
    "accounted exactly once, so ok/(ok+dropped+failed) is the export "
    "delivery rate",
    labels=("outcome",),
)
TRACE_EXPORT_QUEUE = REGISTRY.gauge(
    "vrpms_trace_export_queue_depth",
    "Completed traces waiting in the bounded export queue for the "
    "background flusher (VRPMS_TRACE_EXPORT_QUEUE caps it; sustained "
    "depth near the cap precedes drops); refreshed per scrape",
)
_OCCUPANCY_BUCKETS = (
    0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 1.0,
)
PADDING_OCCUPANCY = REGISTRY.histogram(
    "vrpms_padding_occupancy",
    "Per-solve compute occupancy of the padded tier shape (real work "
    "over padded work, 1.0 = no padding waste), labeled by tier; the "
    "retained exemplar points at the worst-waste trace seen. Low "
    "buckets dominating for a tier = the ladder rung above it is too "
    "far — add an intermediate tier (VRPMS_TIERS)",
    labels=("tier",),
    buckets=_OCCUPANCY_BUCKETS,
)
BATCH_FILL = REGISTRY.histogram(
    "vrpms_batch_fill",
    "Micro-batch fill of vmapped launches (member jobs over the "
    "power-of-two padded batch, 1.0 = no phantom members). Sustained "
    "low fill = widen the gather window (VRPMS_SCHED_WINDOW_MS) or "
    "lower VRPMS_SCHED_MAX_BATCH",
    buckets=_OCCUPANCY_BUCKETS,
)
PIPELINE_OVERLAP = REGISTRY.histogram(
    "vrpms_pipeline_overlap_ratio",
    "Fraction of per-solve host bookkeeping hidden behind in-flight "
    "device blocks (the VRPMS_PIPELINE driver; 0 = fully serial "
    "boundaries). A drop after a deploy = pipeline health regression",
    buckets=_OCCUPANCY_BUCKETS,
)
SLO_BURN = REGISTRY.gauge(
    "vrpms_slo_burn_rate",
    "Deadline-met SLO burn rate per QoS class and window (fast = 5 min, "
    "slow = 1 h): observed miss fraction over the window divided by the "
    "allowed miss budget (1 - VRPMS_SLO_TARGET). 1.0 = consuming "
    "exactly the error budget; refreshed per scrape",
    labels=("qos", "window"),
)
ANALYTICS_TOTAL = REGISTRY.counter(
    "vrpms_analytics_total",
    "Flight records offered to the durable analytics exporter, by "
    "outcome (ok = batch-written to the store's flight_records seam, "
    "dropped = queue overflow or an oversized document, failed = the "
    "store write failed — single-attempt, fail-open). Every offered "
    "record is accounted exactly once",
    labels=("outcome",),
)
ANALYTICS_QUEUE = REGISTRY.gauge(
    "vrpms_analytics_queue_depth",
    "Flight records waiting in the bounded analytics export queue "
    "(VRPMS_ANALYTICS_QUEUE caps it; sustained depth near the cap "
    "precedes drops); refreshed per scrape",
)
ANALYTICS_REGRESSIONS = REGISTRY.counter(
    "vrpms_analytics_regressions_total",
    "Flight records whose rolling per-(tier, algorithm) quality or "
    "efficiency EWMA sits past the committed baseline's tolerance "
    "(benchmarks/records/analytics_baseline.json), by drifted metric",
    labels=("metric",),
)
UPTIME = REGISTRY.gauge(
    "vrpms_uptime_seconds", "Seconds since service process start"
)
DEVICES = REGISTRY.gauge(
    "vrpms_devices", "Accelerator devices attached to the process"
)
BACKEND_INFO = REGISTRY.gauge(
    "vrpms_backend_info",
    "Constant 1, labeled with the jax backend and compile-cache state",
    labels=("backend", "compileCache"),
)

_START = time.time()
_compile_cache = "off"

# populated by service.app from its route table; request-counter label
# values come from here so an arbitrary 404 path can never mint a new
# label series (unbounded cardinality)
KNOWN_ROUTES: set = set()


def set_compile_cache(cache_dir) -> None:
    """Record the compile-cache state app startup resolved (label of
    vrpms_backend_info)."""
    global _compile_cache
    _compile_cache = "on" if cache_dir else "off"


_queue_depths = None
_jobs_running = None
_dist_depth = None
_desired_replicas = None


def set_dist_depth_provider(fn) -> None:
    """Register a callable returning the shared queue's depth (the
    replica layer provides it once a queue store exists); refreshed per
    scrape like the local queue-depth provider."""
    global _dist_depth
    _dist_depth = fn


def set_desired_replicas_provider(fn) -> None:
    """Register a callable returning the elastic-fleet controller's
    desired replica count, or None to publish nothing (the autoscale
    switch is off); refreshed per scrape (service.autoscale)."""
    global _desired_replicas
    _desired_replicas = fn


def set_queue_depth_provider(fn) -> None:
    """Register a callable returning {backend: depth} — the scheduler
    (service.jobs) provides it once constructed; refreshed per scrape."""
    global _queue_depths
    _queue_depths = fn


def set_jobs_running_provider(fn) -> None:
    """Register a callable returning the count of live RUNNING jobs
    (service.jobs' in-process registry); refreshed per scrape."""
    global _jobs_running
    _jobs_running = fn


def refresh_gauges() -> None:
    """Scrape-time gauge values. jax is imported lazily and guarded:
    /metrics must answer even if the backend is broken."""
    UPTIME.set(time.time() - _START)
    if _queue_depths is not None:
        try:
            for backend, depth in _queue_depths().items():
                SCHED_QUEUE_DEPTH.labels(backend=backend).set(depth)
        except Exception:
            pass
    if _jobs_running is not None:
        try:
            JOBS_RUNNING.set(_jobs_running())
        except Exception:
            pass
    if _dist_depth is not None:
        try:
            DIST_QUEUE_DEPTH.set(_dist_depth())
        except Exception:
            pass
    if _desired_replicas is not None:
        try:
            desired = _desired_replicas()
            if desired is not None:
                FLEET_DESIRED.set(desired)
        except Exception:
            pass
    try:
        from store import resilient

        for kind, state in resilient.circuit_states().items():
            STORE_CIRCUIT_STATE.labels(kind=kind).set(
                resilient.STATE_VALUE.get(state, -1)
            )
        for kind, depth in resilient.journal_depths().items():
            STORE_JOURNAL_DEPTH.labels(kind=kind).set(depth)
    except Exception:
        pass
    TRACE_RING_SIZE.set(spans.ring_size())
    try:
        from vrpms_tpu.obs import export as trace_export

        TRACE_EXPORT_QUEUE.set(trace_export.queue_depth())
    except Exception:
        pass
    try:
        from vrpms_tpu.obs import analytics, slo

        ANALYTICS_QUEUE.set(analytics.queue_depth())
        for cls, windows in slo.burn_rates().items():
            for window, stats in windows.items():
                SLO_BURN.labels(qos=cls, window=window).set(
                    stats["burnRate"]
                )
    except Exception:
        pass
    jax_version = "unavailable"
    try:
        import jax

        DEVICES.set(len(jax.devices()))
        backend = jax.default_backend()
        jax_version = jax.__version__
    except Exception:
        DEVICES.set(0)
        backend = "unavailable"
    BACKEND_INFO.labels(backend=backend, compileCache=_compile_cache).set(1)
    try:
        from vrpms_tpu import __version__ as pkg_version
    except Exception:  # pragma: no cover - version attr always present
        pkg_version = "unknown"
    BUILD_INFO.labels(
        version=pkg_version, jaxVersion=jax_version, platform=backend,
        replicaId=_replica_label(),
    ).set(1)


_replica_label_cached: str | None = None


def _replica_label() -> str:
    """This process's replica identity for metric labels and trace-root
    attribution (lazy: service.jobs imports this module at its top, so
    the reverse import must wait until request/scrape time). Resolved
    ONCE per process: label values must stay stable or every
    scheduler rebuild would mint a fresh vrpms_build_info series
    (label-set children are never retired)."""
    global _replica_label_cached
    if _replica_label_cached is None:
        try:
            from service.jobs import replica_id

            _replica_label_cached = replica_id()
        except Exception:  # pragma: no cover - jobs always importable
            return ""
    return _replica_label_cached


def route_label(path: str) -> str:
    if path.startswith("/api/jobs/"):
        # per-id status polls / streams must not mint a series per job
        if path.endswith("/stream"):
            return "/api/jobs/{id}/stream"
        if path.endswith("/resolve"):
            return "/api/jobs/{id}/resolve"
        if path.endswith("/timeline"):
            return "/api/jobs/{id}/timeline"
        return "/api/jobs/{id}"
    if path.startswith("/api/debug/traces/"):
        # same rule for per-trace detail reads
        return "/api/debug/traces/{traceId}"
    return path if path in KNOWN_ROUTES else "<unmatched>"


# ---------------------------------------------------------------------------
# Per-request context: id + trace, opened/closed around every handler body
# ---------------------------------------------------------------------------


def begin_request_obs(handler, sample: str = "always") -> None:
    """Open the request's observability context on the HTTP thread:
    clock, request id (contextvar-bound), and — tracing on — a Trace
    adopted from the W3C `traceparent` header (fresh ids when absent or
    malformed) with a root span named after the route. Every handler
    body runs between begin/end so each log line, metric exemplar, and
    span of the request correlates.

    `sample="header"` traces only when the client sent a VALID
    traceparent — the cheap high-frequency surfaces (job status polls,
    readiness probes, debug reads) must not evict real solve traces
    from the debug ring, and a malformed header minting a fresh trace
    per poll would defeat exactly that."""
    handler._obs_t0 = time.perf_counter()
    handler._request_id = new_request_id()
    handler._rid_token = set_request_id(handler._request_id)
    header = handler.headers.get("traceparent")
    if sample == "header" and spans.parse_traceparent(header)[0] is None:
        trace = None
    else:
        trace = spans.start_trace(header)
    handler._trace = trace
    handler._trace_id = trace.trace_id if trace is not None else None
    handler._trace_root = None
    handler._span_tokens = None
    if trace is not None:
        path = (
            (getattr(handler, "path", "") or "").split("?", 1)[0].rstrip("/")
            or "/"
        )
        root = trace.span(
            f"{getattr(handler, 'command', 'HTTP')} {route_label(path)}"
        )
        # the root names the process that recorded it: exported spans
        # and cross-replica waterfalls stay attributable
        root.set(requestId=handler._request_id, replica=_replica_label())
        handler._trace_root = root
        handler._span_tokens = spans.activate(trace, root)


def end_request_obs(handler) -> None:
    """Close the context: end the root span, drop the activation, and
    finish the trace (ring + slow-capture) — unless the trace was
    DEFERRED to the scheduler worker (async jobs: the 202 left long
    before the solve will end; the worker finishes it at the job's
    terminal transition)."""
    trace = getattr(handler, "_trace", None)
    if trace is not None:
        status = "error" if getattr(handler, "_obs_errors", None) else None
        root = handler._trace_root
        if root is not None:
            root.end(status=status)
        if handler._span_tokens is not None:
            spans.deactivate(handler._span_tokens)
        if not trace.deferred:
            trace.finish(status=status)
    token = getattr(handler, "_rid_token", None)
    if token is not None:
        reset_request_id(token)


def trace_response_headers(handler) -> list[tuple[str, str]]:
    """The outgoing `traceparent` header (parent = this request's root
    span) — emitted by every envelope writer so downstream hops and
    clients join the same trace."""
    trace = getattr(handler, "_trace", None)
    if trace is None:
        return []
    root = getattr(handler, "_trace_root", None)
    span_id = root.span_id if root is not None else spans.new_span_id()
    return [("traceparent", spans.format_traceparent(trace.trace_id, span_id))]


class RequestObsMixin:
    """Structured access logging + request counting for every handler.

    BaseHTTPRequestHandler calls log_request from send_response, so one
    response means exactly one access line and one counter bump — for
    GET banners, POST solves, OPTIONS preflights, and router 404s
    alike. Handlers that time their work stash _obs_t0 / _request_id /
    _obs_errors on the instance; the hook picks up whatever is there.
    """

    def log_request(self, code="-", size="-"):  # noqa: A002
        try:
            status = int(code)
        except (TypeError, ValueError):
            status = 0
        # parse_request send_error()s malformed request lines BEFORE
        # assigning self.path/self.command — the hook still fires
        raw_path = getattr(self, "path", "") or ""
        path = raw_path.split("?", 1)[0].rstrip("/") or "/"
        route = route_label(path)
        outcome = "ok" if status < 400 else "error"
        REQUESTS.labels(
            route=route,
            algorithm=getattr(self, "algorithm", ""),
            outcome=outcome,
        ).inc()
        t0 = getattr(self, "_obs_t0", None)
        errors = getattr(self, "_obs_errors", None)
        log_event(
            "http.request",
            requestId=getattr(self, "_request_id", None),
            method=getattr(self, "command", None),
            path=path,
            status=status,
            durationMs=(
                round((time.perf_counter() - t0) * 1e3, 2)
                if t0 is not None
                else None
            ),
            algorithm=getattr(self, "algorithm", None),
            problem=getattr(self, "problem", None),
            bodyBytes=getattr(self, "_obs_body_bytes", None),
            errors=errors or None,
        )

    def log_error(self, format, *args):  # noqa: A002
        log_event("http.error", message=format % args)

    def log_message(self, format, *args):  # noqa: A002
        # stray stdlib messages (malformed request lines, ...) also
        # arrive as structured lines instead of bare stderr text
        log_event("http.log", message=format % args)


class MetricsHandler(RequestObsMixin, BaseHTTPRequestHandler):
    """GET /metrics — Prometheus exposition of the REGISTRY.

    Content-negotiated: scrapers advertising OpenMetrics in Accept
    (modern Prometheus does by default) get the OpenMetrics exposition
    WITH trace-id exemplars and the `# EOF` terminator; everyone else
    gets the classic 0.0.4 text format without exemplars — a classic
    parser errors on the exemplar `#` and fails the whole scrape.
    """

    def do_GET(self):
        refresh_gauges()
        accept = self.headers.get("Accept", "")
        openmetrics = "application/openmetrics-text" in accept
        body = REGISTRY.render(openmetrics=openmetrics).encode("utf-8")
        self.send_response(200)
        self.send_header(
            "Content-Type",
            "application/openmetrics-text; version=1.0.0; charset=utf-8"
            if openmetrics
            else "text/plain; version=0.0.4; charset=utf-8",
        )
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


# ---------------------------------------------------------------------------
# Compile + tier-cache wiring (PR 4): the jax-facing aggregation lives in
# vrpms_tpu.obs.compile / vrpms_tpu.core.tiers (no service imports there);
# this module, imported by every entry point, points their observer seams
# at the Prometheus instruments above.
# ---------------------------------------------------------------------------


def _record_compile(duration_s: float) -> None:
    COMPILE_TOTAL.inc()
    COMPILE_SECONDS.observe(duration_s)


def _record_tier(outcome: str, _key) -> None:
    TIER_CACHE.labels(outcome=outcome).inc()


def _wire_compile_obs() -> None:
    try:
        from vrpms_tpu.obs import compile as compile_obs

        compile_obs.on_compile(_record_compile)
    except Exception:
        pass
    try:
        from vrpms_tpu.core import tiers

        tiers.set_tier_observer(_record_tier)
    except Exception:
        pass
    try:
        from store import base as store_base

        store_base.set_cache_observer(lambda n: CACHE_EVICTIONS.inc(n))
        store_base.set_queue_observer(
            lambda event, n=1: DIST_CLAIM_CONFLICTS.inc(n)
            if event == "claim_conflict"
            else None
        )
    except Exception:
        pass
    try:
        from vrpms_tpu.obs import progress

        progress.set_observer(_record_progress)
    except Exception:
        pass
    try:
        from vrpms_tpu.obs import export as trace_export

        trace_export.set_observer(
            lambda outcome, n: TRACE_EXPORT.labels(outcome=outcome).inc(n)
        )
    except Exception:
        pass
    try:
        from vrpms_tpu.obs import analytics

        analytics.set_observer(
            lambda outcome, n: ANALYTICS_TOTAL.labels(outcome=outcome).inc(n)
        )
        analytics.set_record_observer(_record_flight)
        analytics.set_regression_observer(
            lambda metric: ANALYTICS_REGRESSIONS.labels(metric=metric).inc()
        )
    except Exception:
        pass


_worst_occupancy = 2.0  # sentinel above any real occupancy


def _record_flight(doc: dict) -> None:
    """Flight-record observer (vrpms_tpu.obs.analytics
    .set_record_observer): one histogram observation per efficiency
    signal the record carries. The occupancy exemplar attaches only
    when the record sets a new worst waste, so the retained exemplar
    always points at the worst-waste trace."""
    global _worst_occupancy
    occ = (doc.get("occupancy") or {}).get("compute")
    tier = doc.get("tier")
    if occ is not None and tier:
        tid = None
        if float(occ) <= _worst_occupancy:
            _worst_occupancy = float(occ)
            tid = doc.get("traceId")
        PADDING_OCCUPANCY.labels(tier=str(tier)).observe(
            float(occ), trace_id=tid
        )
    fill = (doc.get("batch") or {}).get("fill")
    if fill is not None:
        BATCH_FILL.observe(float(fill))
    ratio = doc.get("overlapRatio")
    if ratio is not None:
        PIPELINE_OVERLAP.observe(float(ratio))


def _record_progress(sink, snap: dict) -> None:
    """Progress-sink observer (vrpms_tpu.obs.progress.set_observer):
    one counter bump per published snapshot, and the per-class
    last-value gap gauge when the snapshot carries one."""
    PROGRESS_EVENTS.inc()
    gap = snap.get("gap")
    if gap is not None:
        INCUMBENT_GAP.labels(
            problem=sink.problem or "", algorithm=sink.algorithm or ""
        ).set(gap)


_wire_compile_obs()
