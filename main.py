"""Local smoke driver — the reference's manual test path, made real.

The reference blesses `python main.py` as the local-testing procedure
(reference README.md:47-51; reference main.py:1-13 calls its duration
stub, its random solve stub, and the date helper, then prints). This
driver exercises the same three capabilities against the actual
framework: a point-to-point duration query with time-of-day slicing, a
real VRP solve on a synthetic instance, and the dated solve summary.

    python main.py [--customers N] [--vehicles V] [--algorithm sa|ga|aco|bf]
"""

from __future__ import annotations

import argparse


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--customers", type=int, default=12)
    ap.add_argument("--vehicles", type=int, default=3)
    ap.add_argument("--algorithm", default="sa", choices=["sa", "ga", "aco", "bf"])
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    from vrpms_tpu.core import travel_duration
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.solvers import (
        ACOParams,
        GAParams,
        SAParams,
        solve_aco,
        solve_ga,
        solve_sa,
        solve_vrp_bf,
        solve_info,
    )
    from vrpms_tpu.utils import current_date

    # synth_cvrp counts nodes (depot included); +1 turns customers into nodes
    inst = synth_cvrp(args.customers + 1, args.vehicles, seed=args.seed)
    print(f"instance: {inst.n_customers} customers, {inst.n_vehicles} vehicles")
    print(
        "duration 1 -> 2 departing t=0:   ",
        float(travel_duration(inst, 1, 2, 0.0)),
    )
    print(
        "duration 1 -> 2 departing t=90:  ",
        float(travel_duration(inst, 1, 2, 90.0)),
    )

    if args.algorithm == "sa":
        res = solve_sa(inst, key=args.seed, params=SAParams(n_chains=128, n_iters=2000))
    elif args.algorithm == "ga":
        res = solve_ga(inst, key=args.seed, params=GAParams(population=64, generations=200))
    elif args.algorithm == "aco":
        res = solve_aco(inst, key=args.seed, params=ACOParams(n_ants=32, n_iters=100))
    else:
        res = solve_vrp_bf(inst)

    info = solve_info(res)
    print(f"{args.algorithm} solve: cost={float(res.cost):.1f}")
    print("tour:        ", info["tour"])
    print("total_time:  ", round(info["total_time"], 1))
    print("unvisited:   ", info["unvisited"])
    print("date:        ", info["date"])
    print("current date:", current_date())


if __name__ == "__main__":
    main()
