"""Mixed-size cold sweep: time-to-first-result and distinct compiles,
seed (exact shapes) vs tiered (shape-tier canonicalization, core.tiers).

The ISSUE-4 acceptance gate. A FRESH worker process per mode (so every
jit cache starts empty; the persistent disk cache is disabled for the
measurement — it composes with tiering but would mask the ratio) solves
a stream of requests whose customer counts are drawn from 10-40,
through the service's own dispatch (service.solve._run_solver). Per
request we record its latency (= that request's time-to-first-result)
and the XLA backend-compile count/time around it (vrpms_tpu.obs.
compile — cache hits emit nothing, so the counter IS the distinct-
compile count).

  exact  — VRPMS_TIERS=off: every distinct size specializes its own
           programs; a realistic mix compiles almost per request.
  tiered — the default ladder: sizes collapse onto a handful of padded
           tiers; after each tier's first sighting every request in it
           is compile-free.

Gate: tiered total time-to-first-result >= 3x lower, distinct compiles
>= 4x fewer.

    JAX_PLATFORMS=cpu python -m benchmarks.compile_amortization \
        [--requests 40] [--iters 128] [--pop 32] [--out records/...json]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _worker(mode: str, requests: int, iters: int, pop: int) -> None:
    os.environ["VRPMS_TIERS"] = "off" if mode == "exact" else ""
    import numpy as np

    from service.solve import _run_solver
    from vrpms_tpu.core import tiers
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.obs import compile as compile_obs

    compile_obs.install()
    rng = np.random.default_rng(0)
    sizes = rng.integers(10, 41, size=requests).tolist()
    out = {"mode": mode, "sizes": sizes, "requests": []}
    for n in sizes:
        inst = tiers.maybe_pad(synth_cvrp(int(n), 3, seed=int(n)))
        opts = {
            "seed": 1, "population_size": pop, "iteration_count": iters,
        }
        errors: list = []
        c0, s0 = compile_obs.snapshot()
        t0 = time.perf_counter()
        res, _ = _run_solver(inst, "sa", opts, {}, errors, "vrp", None)
        ttfr = time.perf_counter() - t0
        c1, s1 = compile_obs.snapshot()
        assert res is not None and not errors, errors
        out["requests"].append(
            {
                "n": int(n),
                "ttfr_s": round(ttfr, 4),
                "compiles": c1 - c0,
                "compile_s": round(s1 - s0, 4),
            }
        )
    total_c, total_s = compile_obs.snapshot()
    out["distinct_compiles"] = total_c
    out["compile_seconds"] = round(total_s, 3)
    out["total_ttfr_s"] = round(sum(r["ttfr_s"] for r in out["requests"]), 3)
    out["first_ttfr_s"] = out["requests"][0]["ttfr_s"]
    print("RESULT " + json.dumps(out))


def _spawn(mode: str, args) -> dict:
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        VRPMS_COMPILE_CACHE="off",  # honest cold start for BOTH modes
        VRPMS_RATE_CACHE="/dev/null",
    )
    cmd = [
        sys.executable, "-m", "benchmarks.compile_amortization",
        "--worker", mode,
        "--requests", str(args.requests),
        "--iters", str(args.iters),
        "--pop", str(args.pop),
    ]
    t0 = time.perf_counter()
    proc = subprocess.run(
        cmd, capture_output=True, text=True, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    wall = time.perf_counter() - t0
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout + proc.stderr)
        raise SystemExit(f"{mode} worker failed ({proc.returncode})")
    line = next(
        ln for ln in proc.stdout.splitlines() if ln.startswith("RESULT ")
    )
    rec = json.loads(line[len("RESULT "):])
    rec["process_wall_s"] = round(wall, 2)
    return rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", choices=["exact", "tiered"])
    ap.add_argument("--requests", type=int, default=40)
    ap.add_argument("--iters", type=int, default=128)
    ap.add_argument("--pop", type=int, default=32)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.worker:
        _worker(args.worker, args.requests, args.iters, args.pop)
        return

    exact = _spawn("exact", args)
    tiered = _spawn("tiered", args)
    ratio_ttfr = exact["total_ttfr_s"] / max(tiered["total_ttfr_s"], 1e-9)
    ratio_comp = exact["distinct_compiles"] / max(
        tiered["distinct_compiles"], 1
    )
    record = {
        "benchmark": "compile_amortization",
        "backend": "cpu",
        "requests": args.requests,
        "iters": args.iters,
        "pop": args.pop,
        "exact": exact,
        "tiered": tiered,
        "ttfr_ratio": round(ratio_ttfr, 2),
        "compile_ratio": round(ratio_comp, 2),
        "gate": {
            "ttfr_3x": ratio_ttfr >= 3.0,
            "compiles_4x": ratio_comp >= 4.0,
        },
    }
    print(
        f"exact:  total TTFR {exact['total_ttfr_s']:8.2f}s  "
        f"compiles {exact['distinct_compiles']:4d}  "
        f"({exact['compile_seconds']}s compiling)"
    )
    print(
        f"tiered: total TTFR {tiered['total_ttfr_s']:8.2f}s  "
        f"compiles {tiered['distinct_compiles']:4d}  "
        f"({tiered['compile_seconds']}s compiling)"
    )
    print(
        f"ratios: TTFR {ratio_ttfr:.2f}x lower, "
        f"compiles {ratio_comp:.2f}x fewer "
        f"(gate: >=3x / >=4x -> "
        f"{'PASS' if ratio_ttfr >= 3 and ratio_comp >= 4 else 'FAIL'})"
    )
    if args.out:
        path = args.out
        if not os.path.isabs(path):
            path = os.path.join(os.path.dirname(__file__), path)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(record, f, indent=2)
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
