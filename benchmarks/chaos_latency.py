"""Chaos benchmark: tail latency + availability with the circuit
breaker on vs. off, against a misbehaving store.

Starts the service in-process on the fault-injecting store
(`VRPMS_STORE=faulty:<plan>`) and drives it with N closed-loop clients
through three store conditions:

  healthy — empty plan (baseline);
  flaky   — per-call latency + jitter + error rate: without the
            breaker every request pays the latency tax and a slice of
            requests 400; with it, failures trip the circuit and reads
            serve from the last-known-rows cache — fast and degraded;
  down    — every store call fails: without the breaker every request
            is an error; with it the service keeps answering degraded.

Each condition runs twice: `VRPMS_RESILIENCE=off` (raw store, the
pre-ISSUE-3 behavior) and `on`. Reported per phase: solves/sec,
p50/p99 latency, and the outcome mix (ok / degraded / shed = 4xx-5xx) —
the acceptance contrast is the down row: off sheds ~100%, on serves
~100% degraded at cache speed.

    JAX_PLATFORMS=cpu python -m benchmarks.chaos_latency \
        [--clients 4] [--duration 6] [--warmup 3] [--n 8] \
        [--iters 200] [--pop 8] [--out records/chaos_latency_r8.json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import threading
import time
import urllib.error
import urllib.request

FLAKY_PLAN = "latency=0.05;jitter=0.05;rate=0.3;seed=5"
DOWN_PLAN = "down"


def _post(base: str, path: str, body: dict) -> tuple[int, dict]:
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _seed_store(n: int) -> None:
    import numpy as np

    import store.memory as mem

    mem.reset()
    rng = np.random.default_rng(29)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        "chaos", [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations("chaos", d.tolist())


def _body(n: int, iters: int, pop: int, seed: int) -> dict:
    return {
        "solutionName": "chaos-bench",
        "solutionDescription": "chaos_latency",
        "locationsKey": "chaos",
        "durationsKey": "chaos",
        "capacities": [3 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": iters,
        "populationSize": pop,
    }


def run_phase(base, clients, duration_s, warmup_s, n, iters, pop) -> dict:
    stop = threading.Event()
    measuring = threading.Event()
    lock = threading.Lock()
    lat_ok: list[float] = []
    outcomes = {"ok": 0, "degraded": 0, "shed": 0}

    def client(i: int) -> None:
        seed = 1000 * i
        while not stop.is_set():
            seed += 1
            t0 = time.perf_counter()
            status, resp = _post(base, "/api/vrp/sa", _body(n, iters, pop, seed))
            dt = time.perf_counter() - t0
            if not measuring.is_set():
                continue
            with lock:
                if status == 200:
                    lat_ok.append(dt)
                    key = (
                        "degraded"
                        if resp.get("message", {}).get("degraded")
                        else "ok"
                    )
                    outcomes[key] += 1
                else:
                    outcomes["shed"] += 1

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    time.sleep(warmup_s)
    measuring.set()
    t_meas = time.perf_counter()
    time.sleep(duration_s)
    measured_s = time.perf_counter() - t_meas
    stop.set()
    for t in threads:
        t.join(timeout=120)
    lat_ms = sorted(1e3 * x for x in lat_ok)

    def pct(p: float):
        if not lat_ms:
            return None
        k = min(len(lat_ms) - 1, int(round(p / 100 * (len(lat_ms) - 1))))
        return round(lat_ms[k], 1)

    total = sum(outcomes.values())
    return {
        "requests": total,
        "solvesPerSec": round(len(lat_ms) / measured_s, 2),
        "p50Ms": pct(50),
        "p99Ms": pct(99),
        "meanMs": round(statistics.mean(lat_ms), 1) if lat_ms else None,
        "okPct": round(100 * outcomes["ok"] / total, 1) if total else None,
        "degradedPct": (
            round(100 * outcomes["degraded"] / total, 1) if total else None
        ),
        "shedPct": round(100 * outcomes["shed"] / total, 1) if total else None,
        "measuredSeconds": round(measured_s, 2),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--duration", type=float, default=6.0)
    ap.add_argument("--warmup", type=float, default=3.0)
    ap.add_argument("--n", type=int, default=8, help="locations per instance")
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--pop", type=int, default=8)
    ap.add_argument("--out", default=None, help="record JSON path")
    ap.add_argument("--note", default=None)
    args = ap.parse_args()

    # fast-trip resilience policy so short phases reach steady state
    os.environ.setdefault("VRPMS_STORE_DEADLINE_S", "0.5")
    os.environ.setdefault("VRPMS_STORE_RETRIES", "1")
    os.environ.setdefault("VRPMS_STORE_BACKOFF_S", "0.01")
    os.environ.setdefault("VRPMS_CB_FAILURES", "5")
    os.environ.setdefault("VRPMS_CB_RESET_S", "1.0")
    _seed_store(args.n)

    from service import jobs as jobs_mod
    from service.app import serve
    from store.faulty import reset_faults
    from store.resilient import reset_resilience

    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    import jax

    record = {
        "benchmark": "chaos_latency",
        "backend": jax.default_backend(),
        "clients": args.clients,
        "locations": args.n,
        "iterationCount": args.iters,
        "populationSize": args.pop,
        "durationSeconds": args.duration,
        "plans": {"flaky": FLAKY_PLAN, "down": DOWN_PLAN},
        "policy": {
            k: os.environ[k]
            for k in (
                "VRPMS_STORE_DEADLINE_S", "VRPMS_STORE_RETRIES",
                "VRPMS_STORE_BACKOFF_S", "VRPMS_CB_FAILURES",
                "VRPMS_CB_RESET_S",
            )
        },
        "note": args.note,
    }
    for mode in ("off", "on"):
        os.environ["VRPMS_RESILIENCE"] = mode
        record[f"breaker_{mode}"] = {}
        for name, plan in (("healthy", ""), ("flaky", FLAKY_PLAN),
                           ("down", DOWN_PLAN)):
            reset_faults()
            reset_resilience()
            os.environ["VRPMS_STORE"] = "faulty:"
            if mode == "on" and name != "healthy":
                # one clean request warms the read-through cache — the
                # real-world precondition for degraded serving (a store
                # that was never up has nothing cached to fall back on)
                _post(base, "/api/vrp/sa", _body(args.n, args.iters,
                                                 args.pop, 7))
            os.environ["VRPMS_STORE"] = f"faulty:{plan}" if plan else "faulty:"
            print(f"== breaker={mode} store={name}: {args.clients} clients, "
                  f"{args.duration:.0f}s measure")
            record[f"breaker_{mode}"][name] = run_phase(
                base, args.clients, args.duration, args.warmup,
                args.n, args.iters, args.pop,
            )
            print(json.dumps(record[f"breaker_{mode}"][name], indent=2))
            jobs_mod.shutdown_scheduler()
    os.environ.pop("VRPMS_RESILIENCE", None)

    down_off = record["breaker_off"]["down"]
    down_on = record["breaker_on"]["down"]
    record["availabilityUnderDown"] = {
        "breakerOffServedPct": (down_off["okPct"] or 0)
        + (down_off["degradedPct"] or 0),
        "breakerOnServedPct": (down_on["okPct"] or 0)
        + (down_on["degradedPct"] or 0),
    }
    print(json.dumps(record["availabilityUnderDown"], indent=2))

    srv.shutdown()
    if args.out:
        out = args.out if os.path.isabs(args.out) else os.path.join(
            os.path.dirname(__file__), args.out
        )
        with open(out, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(f"record -> {out}")


if __name__ == "__main__":
    main()
