"""Standing-subscription benchmark: coalesced re-solves vs ad-hoc.

A dispatch feed posts dataset changes in bursts (a traffic update
lands together with the customer it delays; three orders arrive in
one webhook). This bench replays such a trace two ways against the
in-process service and measures what the subscription subsystem
(ISSUE 21) buys over the client-driven alternative:

  * AD-HOC — the pre-subscription client: every arriving delta
    triggers its own POST /api/jobs re-solve (cumulative delta +
    warmStart jobId chain, the ISSUE 8 path), so a burst of B deltas
    costs B solver launches and a no-op pair still costs two;
  * SUBSCRIPTION — the same deltas POSTed to
    /api/subscriptions/{id}/deltas: the debounce window coalesces each
    burst into ONE generation seeded from the previous incumbent, and
    a net no-op burst is fingerprint-deduped into ZERO launches.

Both modes solve the same per-launch budget (iterationCount, chains,
seed), so "equal budget" means equal work per launch — the claim under
test is that the coalesced chain reaches the ad-hoc chain's cost while
launching strictly fewer solves. Cache OFF throughout (VRPMS_CACHE=off):
the point is the subscription machinery, not the solution cache.

Gates (ISSUE 21 acceptance):
  * per burst, the subscription generation's cost matches the ad-hoc
    chain's post-burst cost (relative gap <= costRelTolMax);
  * subscription launches < ad-hoc launches, strictly.

    JAX_PLATFORMS=cpu python -m benchmarks.subscriptions \
        [--n 14] [--bursts 2] [--burst-size 3] [--iters 600] \
        [--chains 16] [--out records/subscriptions_r21.json]
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
import urllib.error
import urllib.request

GATE_COST_REL_TOL = 5e-3
WAIT_S = 300.0


def _request(base: str, method: str, path: str, body: dict | None = None):
    req = urllib.request.Request(
        base + path,
        data=None if body is None else json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _seed_store(n: int) -> None:
    import numpy as np

    import store.memory as mem

    mem.reset()
    rng = np.random.default_rng(47)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        "subbench",
        [{"id": i, "demand": 2 if i else 0} for i in range(n)],
    )
    mem.seed_durations("subbench", d.tolist())


def _content(n: int, iters: int, chains: int, ignored: list) -> dict:
    return {
        "problem": "vrp",
        "algorithm": "sa",
        "solutionName": "sub-bench",
        "solutionDescription": "subscriptions",
        "locationsKey": "subbench",
        "durationsKey": "subbench",
        "capacities": [3 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": list(ignored),
        "completedCustomers": [],
        "seed": 1,
        "iterationCount": iters,
        "populationSize": chains,
    }


def _build_trace(n: int, bursts: int, burst_size: int, horizon: int):
    """(initial_ignored, burst list). Burst 0 is the single cold-start
    delta both modes begin from; bursts 1..B are `burst_size` deltas
    each (drop an active customer, admit an arrival, tweak a demand);
    the final burst is a net no-op pair (add y then drop y)."""
    customers = list(range(1, n))
    ignored = customers[-horizon:]
    active = [c for c in customers if c not in ignored]
    arrivals = list(ignored)
    trace = [[{"add": [arrivals.pop(0)], "drop": [active.pop(0)]}]]
    for _ in range(bursts):
        burst = [{"drop": [active.pop(0)]}, {"add": [arrivals.pop(0)]}]
        # demand tweak on a customer no burst ever drops (customer n-
        # horizon-... keep it simple: the last remaining active one)
        burst.append({"demands": {str(active[-1]): 3}})
        trace.append(burst[:burst_size])
    trace.append([{"add": [arrivals[0]]}, {"drop": [arrivals[0]]}])
    return ignored, trace


def _accumulate(cum: dict, delta: dict) -> dict:
    """The ad-hoc client's cumulative delta (same algebra the
    subscription applies server-side, spelled by hand: the trace only
    ever cancels an add with its own drop)."""
    out = {
        "add": list(cum.get("add") or []),
        "drop": list(cum.get("drop") or []),
        "demands": dict(cum.get("demands") or {}),
    }
    for cid in delta.get("add") or []:
        if cid in out["drop"]:
            out["drop"].remove(cid)
        else:
            out["add"].append(cid)
    for cid in delta.get("drop") or []:
        if cid in out["add"]:
            out["add"].remove(cid)
        else:
            out["drop"].append(cid)
    out["demands"].update(delta.get("demands") or {})
    return {k: v for k, v in out.items() if v}


def _job_cost(base: str, job_id: str) -> float:
    deadline = time.monotonic() + WAIT_S
    while time.monotonic() < deadline:
        status, resp = _request(base, "GET", f"/api/jobs/{job_id}")
        assert status == 200, resp
        job = resp["job"]
        if job["status"] == "done":
            msg = job.get("message") or {}
            if msg.get("durationSum") is not None:
                return float(msg["durationSum"])
            return float(job["incumbent"]["bestCost"])
        assert job["status"] != "failed", job
        time.sleep(0.05)
    raise AssertionError(f"job {job_id} never finished")


def run_adhoc(base, content, trace) -> dict:
    """One POST /api/jobs per arriving delta, chained on warmStart."""
    cum: dict = {}
    launches = 0
    prev = None
    costs = []  # post-burst cost, one per trace burst
    for burst in trace:
        for delta in burst:
            cum = _accumulate(cum, delta)
            body = dict(content)
            if cum:
                body["delta"] = cum
            if prev is not None:
                body["warmStart"] = {"jobId": prev}
            status, resp = _request(base, "POST", "/api/jobs", body)
            assert status == 202, resp
            prev = resp["jobId"]
            launches += 1
            cost = _job_cost(base, prev)
        costs.append(cost)
    return {"launches": launches, "costs": costs, "lastJobId": prev}


def run_subscription(base, content, trace) -> dict:
    """The same deltas through /api/subscriptions: one burst -> at most
    one generation (zero for the trailing no-op burst)."""
    status, resp = _request(base, "POST", "/api/subscriptions", content)
    assert status == 201, resp
    sid = resp["subscriptionId"]
    generation = 0
    costs = []
    for burst in trace:
        net_noop = not _burst_is_change(burst)
        for delta in burst:
            status, resp = _request(
                base, "POST", f"/api/subscriptions/{sid}/deltas", delta
            )
            assert status == 202, resp
        if net_noop:
            # deduped: wait for the pending burst to drain (absorbed
            # without a launch), then re-read the unchanged generation
            _wait_sub(base, sid, lambda d: d["pendingDeltas"] == 0)
            doc = _sub_doc(base, sid)
            assert doc["generation"] == generation, doc
        else:
            generation += 1
            doc = _wait_sub(
                base, sid,
                lambda d, g=generation: d["generation"] >= g
                and d["lastJobId"],
            )
            costs.append(_job_cost(base, doc["lastJobId"]))
    status, _ = _request(base, "DELETE", f"/api/subscriptions/{sid}")
    assert status == 200
    return {
        "launches": generation,
        "costs": costs,
        "subscriptionId": sid,
        "lineage": doc["lineage"],
    }


def _burst_is_change(burst) -> bool:
    cum: dict = {}
    for d in burst:
        cum = _accumulate(cum, d)
    return bool(cum)


def _sub_doc(base, sid) -> dict:
    status, resp = _request(base, "GET", f"/api/subscriptions/{sid}")
    assert status == 200, resp
    return resp["subscription"]


def _wait_sub(base, sid, ready) -> dict:
    deadline = time.monotonic() + WAIT_S
    while time.monotonic() < deadline:
        doc = _sub_doc(base, sid)
        if ready(doc):
            return doc
        time.sleep(0.05)
    raise AssertionError(f"subscription {sid} never became ready")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=14,
                    help="locations incl. depot")
    ap.add_argument("--bursts", type=int, default=2,
                    help="multi-delta bursts after the cold-start step")
    ap.add_argument("--burst-size", type=int, default=3)
    ap.add_argument("--iters", type=int, default=600)
    ap.add_argument("--chains", type=int, default=16)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    os.environ["VRPMS_STORE"] = "memory"
    os.environ["VRPMS_CACHE"] = "off"
    os.environ["VRPMS_SUB_DEBOUNCE_MS"] = "400"
    horizon = args.bursts + 2
    _seed_store(args.n)
    from service.app import serve

    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    ignored, trace = _build_trace(
        args.n, args.bursts, args.burst_size, horizon
    )
    content = _content(args.n, args.iters, args.chains, ignored)
    try:
        adhoc = run_adhoc(base, content, trace)
        sub = run_subscription(base, content, trace)
    finally:
        srv.shutdown()
        from service.jobs import shutdown_scheduler

        shutdown_scheduler()

    from service import obs as service_obs  # committed-metric color

    coalesced = 0.0
    for line in service_obs.REGISTRY.render().splitlines():
        if line.startswith("vrpms_sub_coalesced_total "):
            coalesced = float(line.rsplit(" ", 1)[1])
    # one cost per instance-changing burst in both modes (the trailing
    # no-op burst adds an ad-hoc cost for an instance the subscription
    # already solved — compare it against the last generation)
    gaps = []
    sub_costs = list(sub["costs"])
    for i, a in enumerate(adhoc["costs"]):
        s = sub_costs[i] if i < len(sub_costs) else sub_costs[-1]
        gaps.append(round((s - a) / a, 6))
    import jax

    record = {
        "bench": "subscriptions",
        "config": {
            "n": args.n, "bursts": args.bursts,
            "burstSize": args.burst_size, "iters": args.iters,
            "chains": args.chains, "backend": jax.default_backend(),
            "cache": "off", "debounceMs": 400,
        },
        "trace": trace,
        "adhoc": adhoc,
        "subscription": {k: v for k, v in sub.items() if k != "lineage"},
        "lineage": sub["lineage"],
        "summary": {
            "adhocLaunches": adhoc["launches"],
            "subLaunches": sub["launches"],
            "launchesSaved": adhoc["launches"] - sub["launches"],
            "coalescedTotal": coalesced,
            "costRelGaps": gaps,
            "costRelGapMax": max(gaps),
        },
        "gate": {
            "costRelTolMax": GATE_COST_REL_TOL,
            "costRelGapMax": max(gaps),
            "launchesStrictlyFewer": sub["launches"] < adhoc["launches"],
            "pass": bool(
                sub["launches"] < adhoc["launches"]
                and max(gaps) <= GATE_COST_REL_TOL
            ),
        },
    }
    out = json.dumps(record, indent=2)
    print(out)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0 if record["gate"]["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
