"""Crash-recovery benchmark: resume-from-checkpoint vs restart-from-zero.

A replica dies at ~80% of a solve's budget. Before ISSUE 15 the reclaim
re-ran the job FROM ZERO at attempt=2 — every eval the first attempt
paid was thrown away. This bench measures what the durable checkpoint
buys, CPU-honestly (iteration-bound solves, fixed seeds — the pattern
of resolve_delta_r13):

  * **attempt 1 @ 80%** runs through the REAL capture machinery: an
    async job (progress sink + checkpoint handle) at 80% of the full
    iteration budget, VRPMS_CKPT_MS=0 so every improving block
    captures; the bench polls the checkpoint STORE row during the solve
    and keeps the freshest copy — exactly what a reclaiming peer would
    read after a kill (terminal hygiene deletes the row once the job
    completes, like a real ack does).
  * **restart attempt 2** (the pre-ISSUE behavior) solves the instance
    cold at the full budget I — its final cost is the reference and its
    evals are the attempt-2 work being paid today.
  * **resumed attempt 2** seeds from the checkpoint's routes through
    the same continuation path the reclaim uses
    (`warmStart: {"tour": ...}` -> repair -> SA continuation
    temperature) at shrinking budgets (I, I/2, ... I/16): the smallest
    budget whose cost still matches the restart's final cost gives
    evals-to-match.
  * **overhead**: a paired trace of identical fixed-seed async jobs
    with VRPMS_CKPT on vs off at a realistic cadence (VRPMS_CKPT_MS=
    250 on solves long enough to capture several times) — the
    checkpointer must cost <1% wall clock. Rounds alternate off/on so
    machine drift cancels.

Gates (ISSUE 15 acceptance):
  * resumed attempt-2 matches the restart's final cost with >= 2x
    fewer evals (restartEvals / resumeEvalsAtMatch >= 2);
  * checkpointer overhead < 1% on the paired on/off trace.

    JAX_PLATFORMS=cpu python -m benchmarks.checkpoint_recovery \
        [--n 14] [--iters 600] [--chains 16] [--trace-jobs 8] \
        [--out records/checkpoint_recovery_r19.json]
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
import urllib.error
import urllib.request

GATE_EVALS_RATIO = 2.0
GATE_OVERHEAD_PCT = 1.0
REL_EPS = 1e-6


def _post(base: str, path: str, body: dict) -> tuple[int, dict]:
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base: str, path: str) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(base + path, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _seed_store(n: int) -> None:
    import numpy as np

    import store.memory as mem

    mem.reset()
    rng = np.random.default_rng(47)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        "ckptbench",
        [{"id": i, "demand": 2 if i else 0} for i in range(n)],
    )
    mem.seed_durations("ckptbench", d.tolist())


def _body(n: int, iters: int, chains: int, seed: int, **over) -> dict:
    b = {
        "solutionName": "ckpt-bench",
        "solutionDescription": "checkpoint_recovery",
        "locationsKey": "ckptbench",
        "durationsKey": "ckptbench",
        "capacities": [3 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": iters,
        "populationSize": chains,
    }
    b.update(over)
    return b


def _solve_sync(base, body):
    body = dict(body, includeStats=True)
    status, resp = _post(base, "/api/vrp/sa", body)
    assert status == 200, resp
    msg = resp["message"]
    return {
        "cost": float(msg["durationSum"]),
        "evals": int(msg["stats"]["evals"]),
        "routes": [v["tour"][1:-1] for v in msg["vehicles"]],
        "stats": msg["stats"],
    }


def _checkpointed_attempt1(base, n, iters, chains):
    """Run attempt 1 through the REAL async capture machinery and
    return the freshest checkpoint row a reclaiming peer could read."""
    import store

    status, resp = _post(
        base, "/api/jobs",
        dict(_body(n, iters, chains, seed=1), problem="vrp",
             algorithm="sa"),
    )
    assert status == 202, resp
    jid = resp["jobId"]
    db = store.get_database("vrp", None)
    seen = None
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        row = db.get_checkpoint(jid)
        if row is not None and row["state"].get("routes"):
            seen = row["state"]
        status, poll = _get(base, f"/api/jobs/{jid}")
        if poll["job"]["status"] in ("done", "failed"):
            break
        time.sleep(0.005)
    assert seen is not None, "attempt 1 never wrote a checkpoint"
    return jid, seen


def _run_async_trace(base, n, iters, chains, jobs, seed0) -> float:
    """Total wall seconds for `jobs` sequential async solves (submit +
    wait each) — the paired-overhead workload."""
    t0 = time.perf_counter()
    for i in range(jobs):
        status, resp = _post(
            base, "/api/jobs",
            dict(
                _body(n, iters, chains, seed=seed0 + i, timeLimit=120.0),
                problem="vrp", algorithm="sa",
            ),
        )
        assert status == 202, resp
        jid = resp["jobId"]
        while True:
            _, poll = _get(base, f"/api/jobs/{jid}")
            if poll["job"]["status"] in ("done", "failed"):
                assert poll["job"]["status"] == "done", poll
                break
            time.sleep(0.002)
    return time.perf_counter() - t0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=14)
    ap.add_argument("--iters", type=int, default=600)
    ap.add_argument("--chains", type=int, default=16)
    ap.add_argument("--kill-frac", type=float, default=0.8)
    ap.add_argument("--trace-jobs", type=int, default=4)
    ap.add_argument("--trace-iters", type=int, default=4000,
                    help="iterations per overhead-trace job (long "
                    "enough for several cadence-bounded captures)")
    ap.add_argument("--trace-rounds", type=int, default=3)
    ap.add_argument("--trace-ckpt-ms", type=float, default=250.0)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    os.environ["VRPMS_STORE"] = "memory"
    os.environ["VRPMS_CACHE"] = "off"  # the continuation machinery
    # itself is under test; exact hits would fake the evals story
    os.environ["VRPMS_CKPT_MS"] = "0"  # capture every improving block
    # (the worst case the <1% overhead gate must hold at)
    _seed_store(args.n)
    from service.app import serve

    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        # -- recovery: resume vs restart at the kill point ---------------
        kill_iters = max(1, int(args.iters * args.kill_frac))
        jid, ckpt = _checkpointed_attempt1(
            base, args.n, kill_iters, args.chains
        )
        restart = _solve_sync(
            base, _body(args.n, args.iters, args.chains, seed=2)
        )
        budgets = []
        b = args.iters
        while b >= max(1, args.iters // 16):
            budgets.append(b)
            b //= 2
        resume_runs = {}
        for budget in budgets:
            body = _body(args.n, budget, args.chains, seed=2)
            body["warmStart"] = {"tour": ckpt["routes"]}
            resume_runs[budget] = _solve_sync(base, body)
        match_budget = None
        for budget in sorted(budgets):
            if (
                resume_runs[budget]["cost"]
                <= restart["cost"] * (1 + REL_EPS)
            ):
                match_budget = budget
                break
        full_resume = resume_runs[args.iters]
        evals_ratio = (
            None
            if match_budget is None
            else round(
                restart["evals"]
                / max(1, resume_runs[match_budget]["evals"]),
                2,
            )
        )

        # -- overhead: paired on/off async trace -------------------------
        # realistic capture cadence for the trace (the recovery phase
        # above deliberately ran the capture-every-block worst case)
        os.environ["VRPMS_CKPT_MS"] = str(args.trace_ckpt_ms)
        # one warmup pass compiles every program both sides use
        _run_async_trace(
            base, args.n, args.trace_iters, args.chains, 2, 100
        )
        t_off = t_on = 0.0
        for rnd in range(args.trace_rounds):
            seed0 = 200 + 10 * rnd
            os.environ["VRPMS_CKPT"] = "off"
            t_off += _run_async_trace(
                base, args.n, args.trace_iters, args.chains,
                args.trace_jobs, seed0,
            )
            os.environ["VRPMS_CKPT"] = "on"
            t_on += _run_async_trace(
                base, args.n, args.trace_iters, args.chains,
                args.trace_jobs, seed0,
            )
        overhead_pct = 100.0 * (t_on - t_off) / t_off
    finally:
        srv.shutdown()
        from service.jobs import shutdown_scheduler

        shutdown_scheduler()

    import jax

    record = {
        "bench": "checkpoint_recovery",
        "config": {
            "n": args.n,
            "iters": args.iters,
            "chains": args.chains,
            "killFrac": args.kill_frac,
            "traceJobs": args.trace_jobs,
            "traceIters": args.trace_iters,
            "traceRounds": args.trace_rounds,
            "traceCkptMs": args.trace_ckpt_ms,
            "backend": jax.default_backend(),
            "cache": "off",
            "recoveryCkptMs": 0,
        },
        "recovery": {
            "attempt1Iters": kill_iters,
            "checkpointCost": ckpt["cost"],
            "restartCost": restart["cost"],
            "restartEvals": restart["evals"],
            "resumeFullCost": full_resume["cost"],
            "resumeFullEvals": full_resume["evals"],
            "matchBudget": match_budget,
            "resumeEvalsAtMatch": (
                None
                if match_budget is None
                else resume_runs[match_budget]["evals"]
            ),
            "evalsRatio": evals_ratio,
            "seeded": full_resume["stats"]["resolve"]["seeded"],
            "continuation": full_resume["stats"]["resolve"][
                "continuation"
            ],
        },
        "overhead": {
            "traceOffS": round(t_off, 3),
            "traceOnS": round(t_on, 3),
            "overheadPct": round(overhead_pct, 3),
        },
        "gate": {
            "evalsRatioMin": GATE_EVALS_RATIO,
            "evalsRatio": evals_ratio,
            "overheadMax": GATE_OVERHEAD_PCT,
            "overheadPct": round(overhead_pct, 3),
            "pass": bool(
                evals_ratio is not None
                and evals_ratio >= GATE_EVALS_RATIO
                and overhead_pct < GATE_OVERHEAD_PCT
            ),
        },
    }
    out = json.dumps(record, indent=2)
    print(out)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0 if record["gate"]["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
