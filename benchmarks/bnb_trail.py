"""Branch-and-bound proof trail (VERDICT r4 item 8): log honest attempts
with the ng-route bound in-tree — root bound, nodes walked, outcome —
comparable round over round.

Round-3 baseline trail (BASELINE.md): A-n32-k5 PROVEN optimal at 784 in
3.34e9 nodes / 412 s (8.1M nodes/s, single core) with the 2-cycle
q-path bound only. Since round 4 the completion tables are the
elementwise MAX of the 2-cycle and ng-route tables (io/bounds.py), and
since round 5 the root ascent warm-starts from persisted multipliers
and max-merges ng evaluations over ascent snapshots — both strictly
tighten the root and the per-node prune, so the NODE count is the
honest progress metric on a one-core host (wall-clock wins need the
parallel engine plus cores that are not here).

A-n36-k5 / A-n45-k6 (named by the verdict) have published BKS entries
in io/metrics.py but NO verified fixture data: their coordinates are
not reliably transcribable from memory, and the one hand transcription
attempted at even n=33 was CONVICTED by this same proof machinery
(A-n33-k5: proven 690 != published 661). Attempting them would log
node counts against instances that may not be the published ones —
noise, not evidence. The trail therefore runs the verified fixtures.

Usage: python -m benchmarks.bnb_trail [--limit SECONDS] [--names A,B]
"""

from __future__ import annotations

import argparse
import json
import time


def attempt(name: str, time_limit_s: float):
    import numpy as np

    from vrpms_tpu.io import bounds
    from vrpms_tpu.io.fixtures import load_fixture
    from vrpms_tpu.solvers.exact import solve_cvrp_bnb
    from vrpms_tpu.solvers import ILSParams, SAParams, solve_ils

    inst, meta = load_fixture(name)
    # root certificate (long ascent + ng snapshots + persisted warm
    # start — the same artifact the in-tree pruner reuses)
    t0 = time.perf_counter()
    asc = bounds.cmt_qroute_ascent(inst, iters=1500, ub=meta["bks"])
    root = None if asc is None else round(asc["bound"], 2)
    asc_s = time.perf_counter() - t0
    # incumbent for pruning: a short ILS (the BKS value itself is NOT
    # handed in — the proof must stand on in-repo work)
    res = solve_ils(
        inst, key=0,
        params=ILSParams(rounds=3, sa=SAParams(n_chains=512, n_iters=4000)),
        deadline_s=30.0,
    )
    routes = []
    import jax.numpy as jnp  # noqa: F401

    from vrpms_tpu.core.encoding import routes_from_giant

    routes = [r for r in routes_from_giant(res.giant) if r]
    t0 = time.perf_counter()
    sol, proven, stats = solve_cvrp_bnb(
        inst,
        time_limit_s=time_limit_s,
        incumbent_routes=routes,
        incumbent_cost=float(res.cost),
    )
    wall = time.perf_counter() - t0
    line = {
        "instance": name,
        "bks": meta["bks"],
        "root_bound": root,
        "root_gap_pct": (
            None if root is None
            else round(100 * (meta["bks"] - root) / meta["bks"], 2)
        ),
        "ascent_seconds": round(asc_s, 1),
        "incumbent": round(float(res.cost), 2),
        "nodes": int(stats.get("nodes", -1)),
        "outcome": (
            f"PROVEN optimal at {float(sol.cost):.0f}"
            if proven
            else f"timeout at incumbent {float(sol.cost):.0f}"
        ),
        "proven_matches_bks": bool(
            proven and abs(float(sol.cost) - meta["bks"]) < 1e-6
        ),
        "wall_seconds": round(wall, 1),
        "nodes_per_sec": round(int(stats.get("nodes", 0)) / max(wall, 1e-9)),
    }
    print(json.dumps(line))
    return line


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--limit", type=float, default=900.0)
    ap.add_argument("--names", default="E-n22-k4,A-n32-k5")
    args = ap.parse_args()
    for name in args.names.split(","):
        attempt(name.strip(), args.limit)


if __name__ == "__main__":
    main()
