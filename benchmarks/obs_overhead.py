"""Observability-overhead micro-check: metrics on vs no-op registry.

    python -m benchmarks.obs_overhead [--reps 7] [--iters 1000]
                                      [--customers 100] [--chains 64]

The observability layer's acceptance bar (ISSUE 1): on a 100-customer
SA solve, the per-request instrumentation (request/solve counters +
histograms recorded in service.solve._run_solver) must cost < 1% of
solve wall time. Measured by driving the REAL request path —
service.solve.run_vrp on a synthetic euclidean instance — alternating
the process registry between enabled and disabled (the disabled
registry short-circuits every record call, i.e. the no-op baseline),
with structured logging forced off so only the metrics delta is
measured. includeStats stays absent, matching the hot production path
(no trace collector installed).

Prints one JSON line on stdout (bench.py convention); diagnostics to
stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time


def build_request(n_customers: int, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    n = n_customers + 1
    pts = rng.uniform(0, 100, size=(n, 2))
    matrix = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1).tolist()
    locations = [
        {"id": i, "demand": 2 if i else 0} for i in range(n)
    ]
    n_vehicles = max(2, n_customers // 10)
    cap = 2.0 * n_customers / n_vehicles * 1.3
    params = {
        "name": "obs-overhead",
        "description": "bench",
        "auth": None,
        "ignored_customers": [],
        "completed_customers": [],
        "capacities": [cap] * n_vehicles,
        "start_times": [0.0] * n_vehicles,
    }
    return params, locations, matrix


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--reps", type=int, default=7,
                        help="measured solve pairs (one per registry state)")
    parser.add_argument("--iters", type=int, default=1000)
    parser.add_argument("--customers", type=int, default=100)
    parser.add_argument("--chains", type=int, default=64)
    args = parser.parse_args()

    os.environ["VRPMS_LOG"] = "off"  # isolate the metrics delta
    from service import obs
    from service.solve import run_vrp

    params, locations, matrix = build_request(args.customers)
    opts = {
        "seed": 1,
        "iteration_count": args.iters,
        "population_size": args.chains,
    }

    def one_solve(seed: int):
        errors: list = []
        t0 = time.perf_counter()
        result = run_vrp(
            "sa", params, dict(opts, seed=seed), {}, locations, matrix,
            errors, database=None,
        )
        elapsed = (time.perf_counter() - t0) * 1e3
        assert result is not None and not errors, errors
        return elapsed

    print(
        f"[obs_overhead] warmup solve ({args.customers} customers, "
        f"{args.chains}x{args.iters})",
        file=sys.stderr,
    )
    one_solve(0)  # compile

    on_ms, off_ms = [], []
    # paired design: each rep runs the SAME seed (same compiled program,
    # same search trajectory) once per registry state, flipping the
    # within-pair order each rep so drift (thermal, GC, cache) cancels.
    # The estimator is the median of per-pair relative deltas — solve
    # wall time wobbles several percent rep-to-rep on a shared host,
    # which unpaired medians read as fake overhead.
    for rep in range(args.reps):
        pair = ((True, on_ms), (False, off_ms))
        if rep % 2:
            pair = pair[::-1]
        for enabled, sink in pair:
            obs.REGISTRY.enabled = enabled
            sink.append(one_solve(rep + 1))
    obs.REGISTRY.enabled = True

    overhead_pct = 100.0 * statistics.median(
        (on - off) / off for on, off in zip(on_ms, off_ms)
    )
    line = {
        "bench": "obs_overhead",
        "customers": args.customers,
        "chains": args.chains,
        "iters": args.iters,
        "reps": args.reps,
        "solve_ms_metrics_on": round(statistics.median(on_ms), 2),
        "solve_ms_metrics_off": round(statistics.median(off_ms), 2),
        "overhead_pct": round(overhead_pct, 3),
        # negative deltas are timing noise; the bar is one-sided
        "pass": overhead_pct < 1.0,
    }
    print(json.dumps(line))
    return 0 if line["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
