"""Round-5 fixture verification gauntlet (run BEFORE registering fixtures).

Adjudicates the two new hand-embedded transcriptions against published
anchors, per the methodology proven in round 3 (which certified A-n32-k5
and convicted A-n33-k5):

  E-n51-k5 (Christofides-Eilon, eil51 coordinate set):
    - demand sum 777 <= 5*160, bin-packing minimum fleet = 5
    - TSP on the same 51 coords (nint) has published optimum 426 (TSPLIB
      eil51): solver must land >= 426, ideally == (never below)
    - CVRP optimum 521 (nint rounding): solver >= 521, ideally ==
    - CMT1 (same data, real-valued distances, cap 160): BKS 524.61

  R101 (full 100-customer Solomon):
    - rows 1..25 must EXACTLY match the in-repo R101_25.txt whose
      transcription was certified in round 3 (exact optimum 617.1 hit)
    - first-50 sub-instance = R101.50, exact optimum 1044.0 (Kohl et
      al.): solver >= 1044, ideally ==
    - full instance: distance-minimizing optimum 1637.7; solver >= and
      within a few percent

Usage: python benchmarks/verify_r5.py [--budget S]
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")

from vrpms_tpu.io.cvrplib import load_cvrplib, load_solomon, parse_solomon
from vrpms_tpu.io import bounds
from vrpms_tpu.solvers import ILSParams, SAParams, solve_ils

FIXDIR = "vrpms_tpu/io/fixtures"


def solomon_subset_text(path: str, k: int) -> str:
    """Header + depot + first k customer rows of a Solomon file."""
    out = []
    ncust = 0
    for ln in open(path):
        s = ln.split()
        if s and s[0].isdigit() and len(s) >= 7:
            if int(s[0]) > 0:
                ncust += 1
                if ncust > k:
                    continue
        out.append(ln)
    return "".join(out)


def report(tag, cost, anchor):
    gap = 100.0 * (cost - anchor) / anchor
    flag = "OK" if cost >= anchor - 1e-4 else "!!! BELOW PUBLISHED — BAD DATA"
    print(f"[{tag}] cost={cost:.1f} anchor={anchor} gap={gap:+.2f}%  {flag}")
    return cost >= anchor - 1e-4


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=30.0)
    ap.add_argument("--only", default="")
    args = ap.parse_args()
    t0 = time.time()
    ok = True

    # ---- prefix check: R101 rows 0..25 vs certified R101_25.txt ----
    if not args.only or args.only == "prefix":
        i25, _ = load_solomon(f"{FIXDIR}/R101_25.txt", n_vehicles=8)
        i25b, _ = parse_solomon(solomon_subset_text(f"{FIXDIR}/R101.txt", 25),
                                n_vehicles=8)
        for field in ("demands", "ready", "due", "service"):
            a = np.asarray(getattr(i25, field))
            b = np.asarray(getattr(i25b, field))
            assert np.allclose(a, b), f"prefix mismatch in {field}"
        da = np.asarray(i25.durations[0])
        db = np.asarray(i25b.durations[0])
        assert np.allclose(da, db), "prefix mismatch in distances (coords)"
        print("[prefix] R101 rows 0..25 EXACTLY match certified R101_25.txt")

    # ---- E-n51-k5 ----
    if not args.only or args.only == "e51":
        inst, meta = load_cvrplib(f"{FIXDIR}/E-n51-k5.vrp", round_nint=True)
        dem = np.asarray(inst.demands)
        assert dem.sum() == 777, f"demand sum {dem.sum()} != 777"
        assert inst.n_vehicles == 5
        lb = bounds.lower_bound(inst)
        print(f"[e51] demand sum 777 OK, fleet 5, lower bound {lb:.1f} "
              f"(must be <= 521): {'OK' if lb <= 521 else 'VIOLATED'}")
        ok &= lb <= 521 + 1e-6

        # TSP anchor: same coordinates, single vehicle -> eil51, opt 426
        tsp, _ = load_cvrplib(f"{FIXDIR}/E-n51-k5.vrp", round_nint=True,
                              n_vehicles=1)
        # lift capacity so the single route is feasible
        import dataclasses
        tsp = dataclasses.replace(
            tsp, capacities=tsp.capacities * 0 + float(dem.sum()))
        res = solve_ils(tsp, key=0, params=ILSParams(
            rounds=6, sa=SAParams(n_chains=1024, n_iters=8000), pool=32,
            polish_sweeps=128), deadline_s=args.budget)
        ok &= report("e51/tsp eil51", float(res.cost), 426.0)

        res = solve_ils(inst, key=0, params=ILSParams(
            rounds=6, sa=SAParams(n_chains=1024, n_iters=8000), pool=32,
            polish_sweeps=128), deadline_s=args.budget)
        ok &= report("e51/cvrp", float(res.cost), 521.0)

        # CMT1 anchor: real-valued euclidean distances, BKS 524.61
        instf, _ = load_cvrplib(f"{FIXDIR}/E-n51-k5.vrp", round_nint=False)
        res = solve_ils(instf, key=0, params=ILSParams(
            rounds=6, sa=SAParams(n_chains=1024, n_iters=8000), pool=32,
            polish_sweeps=128), deadline_s=args.budget)
        ok &= report("e51/cmt1 float", float(res.cost), 524.61)

    # ---- R101.50 ----
    if not args.only or args.only == "r50":
        inst, _ = parse_solomon(solomon_subset_text(f"{FIXDIR}/R101.txt", 50),
                                n_vehicles=12)
        res = solve_ils(inst, key=0, params=ILSParams(
            rounds=6, sa=SAParams(n_chains=1024, n_iters=8000), pool=32,
            polish_sweeps=128), deadline_s=args.budget * 2)
        ok &= report("r101.50", float(res.cost), 1044.0)

    # ---- R101 full ----
    if not args.only or args.only == "r100":
        inst, _ = load_solomon(f"{FIXDIR}/R101.txt", n_vehicles=20)
        res = solve_ils(inst, key=0, params=ILSParams(
            rounds=8, sa=SAParams(n_chains=1024, n_iters=8000), pool=32,
            polish_sweeps=128), deadline_s=args.budget * 3)
        ok &= report("r101 full", float(res.cost), 1637.7)

    print(f"[done] {'ALL CHECKS PASSED' if ok else 'FAILURES — see above'} "
          f"({time.time() - t0:.1f}s)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
