"""The BASELINE.md benchmark ladder, runnable end-to-end.

    python -m benchmarks.ladder [--quick] [--configs 1,2,3] [--cpu]

Five configs (BASELINE.md table):
  1  TSP-50 NN+2-opt through the api/tsp -> solver boundary (contract+core)
  2  CVRP A-n32-k5-shaped, single-population SA
  3  CVRP X-n200-k36-shaped, vmap population-parallel SA
  4  CVRP GA island model over the device mesh
  5  VRPTW Solomon-R101-shaped, TW penalty in the batched cost kernel

CVRPLIB/Solomon files are welcome where available (pass --vrp/--solomon
paths); the zero-egress default uses vrpms_tpu.io.synth stand-ins of the
same shape. Each config prints a JSON line with cost/gap/throughput.
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def _result(config, name, **kw):
    line = {"config": config, "name": name}
    line.update(kw)
    print(json.dumps(line))
    return line


def config1_tsp50(quick=False):
    """TSP-50 via the HTTP service boundary into NN+2-opt-grade search."""
    import threading
    import urllib.request

    import store.memory as mem
    from service.app import serve
    from vrpms_tpu.io.synth import synth_tsp
    from vrpms_tpu.solvers import solve_nn_2opt

    inst = synth_tsp(51, seed=10)
    d = np.asarray(inst.durations[0])
    mem.seed_locations("l", [{"id": i} for i in range(51)])
    mem.seed_durations("d", d.tolist())

    import os

    os.environ["VRPMS_STORE"] = "memory"
    srv = serve(port=0)
    port = srv.server_address[1]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    body = {
        "solutionName": "bench",
        "solutionDescription": "config1",
        "locationsKey": "l",
        "durationsKey": "d",
        "customers": list(range(1, 51)),
        "startNode": 0,
        "startTime": 0,
        "seed": 0,
        "iterationCount": 2000 if quick else 20000,
    }
    t0 = time.perf_counter()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/tsp/sa",
        data=json.dumps(body).encode(),
        method="POST",
    )
    with urllib.request.urlopen(req) as resp:
        payload = json.load(resp)
    elapsed = time.perf_counter() - t0
    srv.shutdown()
    served = payload["message"]["duration"]
    local = float(solve_nn_2opt(inst).cost)
    return _result(
        1,
        "tsp50-api-to-solver",
        service_duration=round(served, 1),
        nn2opt_duration=round(local, 1),
        seconds=round(elapsed, 2),
    )


def _sa_gap(inst, name, config, n_chains, n_iters, seed=0, bks=None):
    from vrpms_tpu.io.metrics import gap_percent
    from vrpms_tpu.solvers.ils import ILSParams, solve_ils
    from vrpms_tpu.solvers.sa import SAParams

    # The production top-quality pipeline (the service's ilsRounds
    # option): iterated rounds of anneal -> elite-pool delta polish ->
    # reseed, splitting the sweep budget across rounds. Measured on
    # synth X-n200: 36.8k vs 37.3k for one long anneal + polish, in a
    # third of the wall time (BASELINE.md).
    t0 = time.perf_counter()
    res = solve_ils(
        inst,
        key=seed,
        params=ILSParams.from_budget(
            4, SAParams(n_chains=n_chains, n_iters=0), n_iters, pool=32
        ),
    )
    elapsed = time.perf_counter() - t0
    extra = {}
    feasible = (
        float(res.breakdown.cap_excess) == 0.0
        and float(res.breakdown.tw_lateness) == 0.0
    )
    if bks:
        if feasible:
            # Caveat: BKS distances assume the literature vehicle count;
            # loaders may provision a larger fleet, so treat small gaps
            # as indicative rather than record-comparable.
            extra["gap_to_bks_pct"] = round(
                gap_percent(float(res.breakdown.distance), bks), 2
            )
        else:
            extra["gap_to_bks_pct"] = None  # infeasible: not comparable to BKS
    if feasible:
        extra["certified_gap_ub_percent"] = _certified_gap(
            float(res.breakdown.distance), inst
        )
    line = _result(
        config,
        name,
        cost=round(float(res.breakdown.distance), 1),
        cap_excess=float(res.breakdown.cap_excess),
        tw_lateness=round(float(res.breakdown.tw_lateness), 2),
        seconds=round(elapsed, 2),
        evals_per_sec=round(int(res.evals) / elapsed, 1),
        **extra,
    )
    return line, res


def _certified_gap(distance: float, inst):
    """BKS-free optimality certificate: true gap <= this (polynomial
    lower bounds, vrpms_tpu.io.bounds; validated against BF oracles).
    For time-windowed instances the certificate covers the DISTANCE
    component only; time-dependent instances certify against the
    elementwise cheapest slice."""
    from vrpms_tpu.io.bounds import certified_gap_percent

    gap = certified_gap_percent(distance, inst)
    return round(gap, 2) if gap is not None else None


def config3_budget(seconds, vrp_path=None, seed=0, chains=4096, rounds=None,
                   per_round=1536):
    """Cost-at-budget on the config-3 instance: ONE deadline-bounded ILS
    solve (the service's ilsRounds pipeline) with `timeLimit=seconds`.

    The north-star claim (BASELINE.json: <=2% gap in <10 s on one chip)
    is about a FRESH process answering inside the budget, so run this
    under --budget-series, which spawns a new interpreter per point:
    each pays its own jax/device init and persistent-cache loads
    (enable_compile_cache amortizes actual XLA compiles across
    processes). `seconds` bounds the solve only; the parent records the
    whole process wall clock next to it.
    """
    if vrp_path:
        inst, name, bks = _load_vrp(vrp_path)
    else:
        from vrpms_tpu.io.synth import synth_cvrp

        inst, name, bks = synth_cvrp(200, 36, seed=0), "cvrp-n200-k36-budget", None
    from vrpms_tpu.io.metrics import gap_percent
    from vrpms_tpu.solvers.ils import ILSParams, solve_ils
    from vrpms_tpu.solvers.sa import SAParams

    # Tuned on one v5e chip (2026-07, synth X-n200): B=4096 chains,
    # 1536-sweep rounds (a 512 multiple, so only ONE anneal-block
    # program shape ever loads; ~1.3 s each) + pool-32 polish reached 37.2k in
    # 8 s steady-state vs 36.8k for the 123 s record — smaller rounds
    # convert a tight budget into more polish/reseed cycles. The round
    # count scales with the budget (the deadline cuts the tail anyway).
    if rounds is None:
        rounds = max(4, int(float(seconds) / 1.2) + 1)
    p = ILSParams.from_budget(
        rounds, SAParams(n_chains=chains, n_iters=0), rounds * per_round,
        pool=32,
    )

    def one(k):
        t0 = time.perf_counter()
        res = solve_ils(inst, key=k, params=p, deadline_s=float(seconds))
        return res, time.perf_counter() - t0

    # Startup warmup, exactly what a restarted service runs before
    # accepting requests (service.warmup): two small untimed ILS rounds
    # compile/load the pipeline programs (anneal, polish, reseed, exact
    # eval), then warm_anneal_blocks covers the rate-fitted shrunk block
    # shapes and persists measured sweep rates. This is counted in the
    # budget-series' process_seconds, NOT in the solve wall — the
    # north-star claim is that a SOLVE honors its deadline, and before
    # this warm existed the first tight-deadline solve absorbed those
    # compiles (12.0 s at a 1 s budget; VERDICT round 3).
    from vrpms_tpu.solvers.sa import warm_anneal_blocks

    t_warm = time.perf_counter()
    solve_ils(
        inst, key=99,
        params=ILSParams.from_budget(
            2, SAParams(n_chains=chains, n_iters=0), 2 * 512, pool=32
        ),
    )
    warm_anneal_blocks(inst, chains)
    warm_s = time.perf_counter() - t_warm

    # cold: first timed solve after startup warmup (the restarted-
    # service number); steady: the long-running-service number.
    res, elapsed = one(seed)
    res2, elapsed2 = one(seed + 1)
    extra = {}
    if bks and float(res.breakdown.cap_excess) == 0.0:
        extra["gap_to_bks_pct"] = round(
            gap_percent(float(res.breakdown.distance), bks), 2
        )
    if bks and float(res2.breakdown.cap_excess) == 0.0:
        extra["steady_gap_to_bks_pct"] = round(
            gap_percent(float(res2.breakdown.distance), bks), 2
        )
    if float(res2.breakdown.cap_excess) == 0.0:
        extra["certified_gap_ub_percent"] = _certified_gap(
            float(res2.breakdown.distance), inst
        )
    return _result(
        3,
        name,
        budget_s=float(seconds),
        warmup_seconds=round(warm_s, 2),
        cost=round(float(res.breakdown.distance), 1),
        cap_excess=float(res.breakdown.cap_excess),
        solve_seconds=round(elapsed, 2),
        evals=int(res.evals),
        steady_cost=round(float(res2.breakdown.distance), 1),
        steady_solve_seconds=round(elapsed2, 2),
        steady_evals=int(res2.evals),
        **extra,
    )


def budget_series(seconds_list, vrp_path=None, cpu=False):
    """Fresh interpreter per budget point — the honest cold-ish-process
    measurement (in-process jit caches empty; disk compile cache warm
    after the first ever run on a machine)."""
    import subprocess
    import sys

    points = []
    for s in seconds_list:
        cmd = [sys.executable, "-m", "benchmarks.ladder", "--configs", "3",
               "--budget", str(s)]
        if vrp_path:
            cmd += ["--vrp", vrp_path]
        if cpu:
            cmd += ["--cpu"]
        t0 = time.perf_counter()
        proc = subprocess.run(cmd, capture_output=True, text=True)
        wall = time.perf_counter() - t0
        line = None
        for out_line in reversed(proc.stdout.strip().splitlines()):
            try:
                line = json.loads(out_line)
                break
            except json.JSONDecodeError:
                continue
        if proc.returncode != 0 or line is None:
            print(proc.stderr[-2000:], flush=True)
            raise RuntimeError(f"budget point {s}s failed")
        line["process_seconds"] = round(wall, 2)
        points.append(line)
    print(json.dumps({"config": 3, "name": "budget-series", "points": points}))
    return points


def _load_vrp(path):
    """CVRPLIB file -> (instance, display name, BKS-if-known)."""
    from vrpms_tpu.io import load_cvrplib
    from vrpms_tpu.io.metrics import best_known

    inst, meta = load_cvrplib(path)
    name = str(meta.get("name", "cvrplib")).lower()
    return inst, name, best_known(name)


def config2_small_cvrp(quick=False, vrp_path=None, exact_s=60.0):
    """Small CVRP on the REAL A-n32-k5 (embedded fixture, published
    optimum 784): the gap column here is a TRUE gap-to-BKS, not a
    synth-relative number (VERDICT round-2 item 1). After the heuristic
    solve, branch-and-bound gets `exact_s` seconds to close the
    instance outright (item 3); when it proves the optimum the line
    carries exact_optimum/exact_proven and the certified gap is 0."""
    if vrp_path:
        inst, name, bks = _load_vrp(vrp_path)
    else:
        from vrpms_tpu.io.fixtures import load_fixture

        inst, meta = load_fixture("A-n32-k5")
        name, bks = "a-n32-k5-fixture", meta["bks"]
    line, res_h = _sa_gap(inst, name, 2, 128, 2000 if quick else 20000, bks=bks)
    if quick:
        exact_s = min(exact_s, 5.0)  # quick is the smoke pass, not a proof
    if exact_s and not inst.has_tw and not inst.time_dependent:
        from vrpms_tpu.core.encoding import routes_from_giant
        from vrpms_tpu.solvers.exact import solve_cvrp_bnb

        # the heuristic champion seeds the search as incumbent ROUTES,
        # so an exhausted tree proves ITS optimality (a cost-only bound
        # cannot certify what it returns — see solve_cvrp_bnb)
        routes = cost = None
        if line["cap_excess"] == 0.0:
            routes = [r for r in routes_from_giant(np.asarray(res_h.giant)) if r]
            cost = float(res_h.breakdown.distance)
        t0 = time.perf_counter()
        res, proven, stats = solve_cvrp_bnb(
            inst, time_limit_s=float(exact_s),
            incumbent_routes=routes, incumbent_cost=cost,
        )
        _result(
            2,
            name + "-exact",
            exact_cost=round(float(res.breakdown.distance), 1),
            exact_proven=bool(proven),
            bnb_nodes=int(stats["nodes"]),
            seconds=round(time.perf_counter() - t0, 2),
            root_qroute_bound=(
                round(stats["qroute_bound"], 1) if stats["qroute_bound"] else None
            ),
        )
    return line


def config3_big_cvrp(quick=False, vrp_path=None):
    if vrp_path:
        inst, name, bks = _load_vrp(vrp_path)
    else:
        from vrpms_tpu.io.synth import synth_cvrp

        inst, name, bks = synth_cvrp(200, 36, seed=0), "cvrp-n200-k36-vmap-sa", None
        # a REAL mid-size CVRP line beside the synthetic scale line:
        # E-n51-k5 (round-5 fixture, published optimum 521) gives
        # config 3 a true gap the synth stand-in cannot (VERDICT r4)
        from vrpms_tpu.io.fixtures import load_fixture

        inst_r, meta = load_fixture("E-n51-k5")
        _sa_gap(
            inst_r, "e-n51-k5-fixture", 3, 256 if quick else 2048,
            2000 if quick else 20000, bks=meta["bks"],
        )
    return _sa_gap(inst, name, 3, 256 if quick else 2048,
                   2000 if quick else 20000, bks=bks)[0]


def config4_ga_islands(quick=False):
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.mesh import IslandParams, solve_ga_islands
    from vrpms_tpu.solvers.ga import GAParams

    inst = synth_cvrp(100, 12, seed=12)
    t0 = time.perf_counter()
    res = solve_ga_islands(
        inst,
        key=0,
        params=GAParams(population=256, generations=100 if quick else 1000, elites=4),
        island_params=IslandParams(migrate_every=25, n_migrants=2),
        pool=8,
    )
    ga_cost = float(res.breakdown.distance)
    ga_evals = int(res.evals)
    ga_elapsed = time.perf_counter() - t0  # throughput excludes polish
    # polish the elite pool and keep the winner (the service's
    # localSearchPool pipeline; distinct genomes sit in distinct basins)
    from vrpms_tpu.core.cost import CostWeights, exact_cost, exact_cost_batch
    from vrpms_tpu.solvers.delta_ls import delta_polish_batch

    w = CostWeights.make()
    giants, _, _ = delta_polish_batch(res.pool, inst, w, max_sweeps=128)
    import jax.numpy as jnp

    # rank the (small) polished pool EXACTLY — mode-precision costs can
    # misrank near-ties and drop a genuinely better row
    ecosts = exact_cost_batch(giants, inst, w)
    champ = giants[int(jnp.argmin(ecosts))]
    bd, cost = exact_cost(champ, inst, w)
    if float(cost) < float(res.cost):
        res = res._replace(giant=champ, cost=cost, breakdown=bd)
    elapsed = time.perf_counter() - t0
    line = _result(
        4,
        "cvrp-n100-ga-islands",
        cost=round(float(res.breakdown.distance), 1),
        ga_cost=round(ga_cost, 1),
        cap_excess=float(res.breakdown.cap_excess),
        seconds=round(elapsed, 2),
        evals_per_sec=round(ga_evals / ga_elapsed, 1),
    )
    # ACO on the SAME instance (VERDICT round-2 item 7 / round-3 item 7:
    # ACO quality tracked against GA). Round 4 made the comparison
    # structurally fair: the GA line polishes its elite pool, so the ACO
    # line gets the SAME pool polish + exact re-rank, and the ant budget
    # matches the single-colony bench family (128 ants) instead of the
    # old 64 — the round-3 ACO-trails-GA gap was mostly this asymmetry.
    from vrpms_tpu.mesh import solve_aco_islands
    from vrpms_tpu.solvers.aco import ACOParams

    t0 = time.perf_counter()
    res_aco = solve_aco_islands(
        inst,
        key=0,
        params=ACOParams(n_ants=128, n_iters=100 if quick else 500),
        island_params=IslandParams(migrate_every=25, n_migrants=2),
        pool=8,
    )
    aco_raw = float(res_aco.breakdown.distance)
    giants_a, _, _ = delta_polish_batch(res_aco.pool, inst, w, max_sweeps=128)
    ecosts_a = exact_cost_batch(giants_a, inst, w)
    champ_a = giants_a[int(jnp.argmin(ecosts_a))]
    bd_a, cost_a = exact_cost(champ_a, inst, w)
    if float(cost_a) < float(res_aco.cost):
        res_aco = res_aco._replace(giant=champ_a, cost=cost_a, breakdown=bd_a)
    _result(
        4,
        "cvrp-n100-aco-islands",
        cost=round(float(res_aco.breakdown.distance), 1),
        aco_raw_cost=round(aco_raw, 1),
        cap_excess=float(res_aco.breakdown.cap_excess),
        seconds=round(time.perf_counter() - t0, 2),
        # the round-3 demand: ACO islands at/below GA islands
        at_or_below_ga=bool(
            float(res_aco.breakdown.distance) <= line["cost"] + 1e-6
        ),
    )
    return line


def config5_vrptw(quick=False, solomon_path=None):
    """VRPTW: the real R101.25 fixture (exact optimum 617.1, Kohl et
    al.) for a TRUE gap line, plus the R101-shaped synth at full size
    for the throughput-at-scale line the fixture is too small to give."""
    bks = None
    if solomon_path:
        from vrpms_tpu.io import load_solomon
        from vrpms_tpu.io.metrics import best_known

        inst, meta = load_solomon(solomon_path)
        name = str(meta.get("name", "vrptw-solomon")).lower()
        bks = best_known(name)
        return _sa_gap(inst, name, 5, 256, 2000 if quick else 30000, bks=bks)[0]
    from vrpms_tpu.io.fixtures import load_fixture
    from vrpms_tpu.io.synth import synth_vrptw

    inst, meta = load_fixture("R101.25")
    _sa_gap(
        inst, "r101.25-fixture", 5, 256,
        2000 if quick else 12000, bks=meta["bks"],
    )
    # the REAL full 100-customer R101 (round-5 fixture): the TW delta
    # kernel's intended production instance. One deadline-bounded
    # B=16384 delta anneal; the true gap line only counts for a
    # FEASIBLE (zero-lateness, zero-excess) champion
    import jax as _jax

    if _jax.devices()[0].platform != "cpu" and not quick:
        from vrpms_tpu.core.cost import CostWeights
        from vrpms_tpu.io.metrics import gap_percent
        from vrpms_tpu.solvers.sa import (
            SAParams, _delta_supported, solve_sa_delta,
        )

        inst, meta = load_fixture("R101")
        w = CostWeights.make()
        assert _delta_supported(inst, w, "pallas")
        t0 = time.perf_counter()
        res = solve_sa_delta(
            inst, key=1,
            params=SAParams(n_chains=16384, n_iters=1_000_000),
            deadline_s=120.0, pool=32,
        )
        bd = res.breakdown
        feasible = (
            float(bd.tw_lateness) == 0.0 and float(bd.cap_excess) == 0.0
        )
        gap = None
        dist = float(bd.distance)
        if feasible:
            gap = round(gap_percent(dist, meta["bks"]), 2)
        else:
            # the gap line takes the best FEASIBLE pool member (the
            # cost-champion may carry epsilon lateness)
            from vrpms_tpu.core.cost import best_feasible_pool

            fb = best_feasible_pool(res.pool, inst)
            if fb is not None:
                gap = round(gap_percent(fb, meta["bks"]), 2)
                dist = fb
        _result(
            5,
            "r101-full-fixture-delta",
            cost=round(float(bd.distance), 1),
            feasible_dist=round(dist, 1) if gap is not None else None,
            bks=meta["bks"],
            gap_pct=gap,
            tw_lateness=round(float(bd.tw_lateness), 2),
            cap_excess=float(bd.cap_excess),
            seconds=round(time.perf_counter() - t0, 1),
        )
    inst = synth_vrptw(101, 19, seed=13)
    return _sa_gap(inst, "vrptw-r101-shaped", 5, 256, 2000 if quick else 30000)[0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--configs", default="1,2,3,4,5")
    ap.add_argument("--cpu", action="store_true", help="force CPU platform")
    ap.add_argument("--solomon", help="path to a Solomon instance for config 5")
    ap.add_argument("--vrp", help="path to a CVRPLIB .vrp for config 3")
    ap.add_argument("--vrp-small", help="path to a CVRPLIB .vrp for config 2")
    ap.add_argument(
        "--budget", type=float,
        help="config 3 as ONE deadline-bounded ILS solve with this "
        "timeLimit (seconds); prints cost-at-budget",
    )
    ap.add_argument(
        "--budget-series",
        help="comma-separated seconds (e.g. 1,5,10,30); fresh process "
        "per point for honest cold-process cost-at-budget",
    )
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    from vrpms_tpu.utils import enable_compile_cache

    enable_compile_cache()
    if args.budget_series:
        budget_series(
            [float(s) for s in args.budget_series.split(",")],
            vrp_path=args.vrp,
            cpu=args.cpu,
        )
        return
    if args.budget is not None:
        config3_budget(args.budget, vrp_path=args.vrp)
        return
    wanted = {int(c) for c in args.configs.split(",")}
    if 1 in wanted:
        config1_tsp50(args.quick)
    if 2 in wanted:
        config2_small_cvrp(args.quick, args.vrp_small)
    if 3 in wanted:
        config3_big_cvrp(args.quick, args.vrp)
    if 4 in wanted:
        config4_ga_islands(args.quick)
    if 5 in wanted:
        config5_vrptw(args.quick, args.solomon)


if __name__ == "__main__":
    main()
