"""Elastic-fleet benchmark: demand ramp, zero-loss scale-in, churn
compiles, and controller overhead (the ISSUE-18 acceptance gate).

Four phases against an RTT-shimmed store-backed queue (the hosted
store's real per-op cost, the batched_claims convention):

  ramp — a 1 -> 4 -> 1 replica ramp driven by the controller itself:
      a steady trickle holds the recommendation at 1; a backlog burst
      raises it (scale-up is immediate) and an HPA-emulation loop adds
      in-process peer replicas to match; the drained backlog drops it
      back to 1 after cooldown. Gates: every sampled recommendation
      sits at or above the QoS-feasible minimum for that sample's own
      backlog (desired >= clamped raw — scale-up immediate, scale-down
      damped), the burst reaches the cap, the final recommendation
      returns to 1, and the desired series changes direction <= 3
      times (1 -> 4 -> 1 is two reversals; hysteresis + cooldown must
      not flap it).

  scalein — POST /api/admin/scalein mid-backlog (forced self-victim:
      in-process peers share this process's heartbeat doc, so relaying
      to "them" would loop back here). The service replica checkpoint-
      drains; peers finish everything. Gates: zero lost jobs, zero
      burned attempts (every record attempt still 1 — voluntary
      handoff, not a crash reclaim), every job completed exactly once
      (acked-completion spy).

  churn — post-churn cold compiles, in fresh SUBPROCESSES (in-process
      replicas share one jit cache, so cold compiles are only
      measurable with per-box isolation, the multi_replica
      convention). A two-member ring loses a peer; the survivor's
      inherited tier-ladder shapes come from the SAME
      inherited_spec the churn watcher computes. Both scenarios prime
      the shape-independent programs and measure a steady serving
      window first; then "prewarmed" runs the churn-hardening warmup
      for the inherited spec before serving the post-churn trace,
      "cold" serves it straight. Gate: prewarmed post-churn serving
      compiles <= 2x the steady-window compiles, AND strictly fewer
      than the cold contrast (no vacuous pass).

  overhead — same-seed paired on/off 2-job blocks, finely interleaved
      (VRPMS_AUTOSCALE toggled per block, alternating order, an HPA
      poller hitting /api/debug/fleet at 4 Hz in BOTH arms): median
      paired delta of solve wall-clock < 1%. The fixed-seed
      byte-identity contract is tests/test_autoscale.py's job, not a
      timing bench's.

In-process note: peer joins during the ramp churn the ring, and the
REAL churn watcher fires; its background warmup is intercepted at the
_launch_warmup seam (launch count recorded) because in-process
compiles would land inside the ramp's measurement window — the honest
compile accounting is exactly what the churn phase's subprocesses do.

    JAX_PLATFORMS=cpu python -m benchmarks.elastic_fleet \
        [--rtt-ms 25] [--burst 20] [--scalein-jobs 10] [--pairs 96] \
        [--skip-churn] [--out records/elastic_fleet_r22.json]
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import statistics
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

#: burst/trickle instance: 7 locations pads to the 8-tier (one shape,
#: one prewarmed program family — controller effects, not compile
#: noise, are the measurement). 30k iterations makes a warm solve
#: ~0.4s: heavy enough that the job-seconds EWMA times the burst depth
#: unambiguously demands the cap, and that a 0.3s drain grace really
#: exercises the checkpoint-nack handoff. (timeLimit would be the
#: obvious knob, but it is an EDF budget that queue wait consumes — a
#: 20-deep burst of timeLimit jobs would expire in queue.)
TRACE_N = 7
TRACE_ITERS = 30000
TRACE_POP = 8

#: the ramp cap: 1 -> CAP -> 1
CAP = 4

#: churn-phase priming size: pads to tier 48, which the child's
#: steady/serve tier sets exclude (the multi_replica convention — the
#: shape-independent once-per-process programs are deployment warmup's
#: bill, not churn's)
PRIME_N = 40

#: the option profile the tier warmup compiles (service.warmup) — the
#: churn child serves with the SAME profile so a prewarmed tier is a
#: jit-cache hit by construction, exactly like post-warmup traffic
WARM_OPTS = {
    "population_size": None,
    "iteration_count": 512,
    "time_limit": 0.0,
    "local_search": True,
    "local_search_pool": 32,
}


class _RttStore:
    """Every queue-store op behind a fixed round-trip delay. Unlike
    batched_claims' explicit-method shim this delegates EVERYTHING
    (replica_infos, depth_by_class, info-carrying heartbeats, nack
    notes) — the elastic-fleet controller reads registry surfaces the
    older benches never touched."""

    def __init__(self, inner, rtt_s: float):
        self._inner = inner
        self._rtt = rtt_s

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr

        def call(*args, **kw):
            if self._rtt > 0:
                time.sleep(self._rtt)
            return attr(*args, **kw)

        return call


# ---------------------------------------------------------------------------
# HTTP helpers (the multi_replica idiom)
# ---------------------------------------------------------------------------


def _post(base, path, body):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as resp:
        return resp.status, json.loads(resp.read())


def _seed_store(n: int) -> None:
    import numpy as np

    import store.memory as mem

    rng = np.random.default_rng(17)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        f"bench{n}",
        [{"id": i, "demand": 2 if i else 0} for i in range(n)],
    )
    mem.seed_durations(f"bench{n}", d.tolist())


def _body(n: int, seed: int) -> dict:
    return {
        "problem": "vrp", "algorithm": "sa",
        "solutionName": f"elastic-{n}", "solutionDescription": "fleet",
        "locationsKey": f"bench{n}", "durationsKey": f"bench{n}",
        "capacities": [3 * n] * 3, "startTimes": [0, 0, 0],
        "ignoredCustomers": [], "completedCustomers": [],
        "seed": seed, "iterationCount": TRACE_ITERS,
        "populationSize": TRACE_POP,
    }


def _wait_done(base, job_ids, timeout_s=300.0) -> dict:
    """Poll every job to terminal; returns {jobId: record}."""
    out = {}
    deadline = time.monotonic() + timeout_s
    pending = list(job_ids)
    while pending and time.monotonic() < deadline:
        still = []
        for jid in pending:
            _, r = _get(base, f"/api/jobs/{jid}")
            if r["job"]["status"] in ("done", "failed"):
                out[jid] = r["job"]
            else:
                still.append(jid)
        pending = still
        if pending:
            time.sleep(0.05)
    for jid in pending:
        out[jid] = {"status": "timeout"}
    return out


def _direction_changes(series) -> int:
    moves = [b - a for a, b in zip(series, series[1:]) if b != a]
    return sum(
        1 for a, b in zip(moves, moves[1:]) if (a > 0) != (b > 0)
    ) + (1 if moves else 0)


# ---------------------------------------------------------------------------
# churn child: one fresh process = one replica's post-churn compile bill
# ---------------------------------------------------------------------------


def _churn_child(spec_json: str) -> None:
    cfg = json.loads(spec_json)
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    from vrpms_tpu.obs import compile as cobs

    cobs.install()
    from service.solve import _run_solver
    from vrpms_tpu.core import tiers
    from vrpms_tpu.io.synth import synth_cvrp

    def solve(n, v, seed):
        inst = tiers.maybe_pad(synth_cvrp(n, v, seed=seed))
        errors: list = []
        _run_solver(
            inst, "sa", dict(WARM_OPTS, seed=seed), {}, errors, "vrp",
            None,
        )
        if errors:
            print(json.dumps({"error": errors}), flush=True)
            raise SystemExit(1)

    solve(PRIME_N, 3, 0)
    prime_compiles, _ = cobs.snapshot()
    # steady state: the tiers this replica owned pre-churn — first pass
    # pays their compiles (deployment warmup's bill), the second pass
    # IS the steady serving window
    for i, (n, v) in enumerate(cfg["steady"]):
        solve(n, v, 100 + i)
    warm_compiles, _ = cobs.snapshot()
    for i, (n, v) in enumerate(cfg["steady"]):
        solve(n, v, 200 + i)
    after_steady, _ = cobs.snapshot()
    steady_compiles = after_steady - warm_compiles
    # churn hardening (prewarmed scenario only): compile the inherited
    # spec the watcher computed, exactly as the background thread would
    warmup_compiles = 0
    if cfg["mode"] == "prewarmed":
        from service.warmup import warmup

        warmup(cfg["spec"], ("sa",), log=False)
        after_warm, _ = cobs.snapshot()
        warmup_compiles = after_warm - after_steady
    before_serve, _ = cobs.snapshot()
    t0 = time.perf_counter()
    # the post-churn serving window: traffic on the INHERITED tiers
    for i, (n, v) in enumerate(cfg["serve"]):
        solve(n, v, 300 + i)
    serving_compiles = cobs.snapshot()[0] - before_serve
    print(json.dumps({
        "mode": cfg["mode"],
        "primeCompiles": prime_compiles,
        "steadyCompiles": steady_compiles,
        "warmupCompiles": warmup_compiles,
        "servingCompiles": serving_compiles,
        "serveSeconds": round(time.perf_counter() - t0, 2),
    }), flush=True)


def _run_churn_child(cfg: dict) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    out = subprocess.run(
        [sys.executable, "-m", "benchmarks.elastic_fleet",
         "--churn-child", json.dumps(cfg)],
        capture_output=True, text=True, env=env, timeout=1800,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if out.returncode != 0:
        raise RuntimeError(f"churn child failed: {out.stdout}\n{out.stderr}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def churn_phase() -> dict:
    """Survivor inherits a dead peer's tier-ladder arcs: serving those
    tiers after the watcher's pre-warm vs serving them cold."""
    from service import autoscale as autoscale_mod
    from service import warmup as warmup_mod
    from vrpms_tpu.sched.ring import HashRing, slot

    pairs = [
        (shape, tok) for shape, tok in autoscale_mod.ladder_tokens()
        if shape != "48x4"  # the prime tier stays out of both windows
    ]
    assert pairs, "tier ladder must be on"
    svc = "replica-a"
    # deterministic scan: a peer whose loss hands the survivor at least
    # one ladder tier while it keeps at least one of its own
    for i in range(50):
        peer = f"peer-{i}"
        prev, new = HashRing([svc, peer]), HashRing([svc])
        inherited = [
            s for s, t in pairs
            if new.owner(slot(t)) == svc and prev.owner(slot(t)) != svc
        ]
        steady = [s for s, t in pairs if prev.owner(slot(t)) == svc]
        if inherited and steady:
            break
    assert inherited and steady, "no peer split the ladder in 50 tries"
    # the spec the watcher itself would compute for this churn
    spec = autoscale_mod.inherited_spec(prev, new, svc)
    assert sorted(spec.split(",")) == sorted(inherited), (spec, inherited)

    def dims(shape):
        n, v = warmup_mod.parse_shapes(shape)[0][:2]
        return [n, v]

    base_cfg = {
        "spec": spec,
        "steady": [dims(s) for s in steady],
        "serve": [dims(s) for s in inherited],
    }
    print(f"== churn: survivor keeps {steady}, inherits {inherited}")
    results = {}
    for mode in ("prewarmed", "cold"):
        results[mode] = _run_churn_child(dict(base_cfg, mode=mode))
        print(f"   {mode}: {json.dumps(results[mode])}")
    return {
        "spec": spec,
        "steadyTiers": steady,
        "inheritedTiers": inherited,
        "prewarmed": results["prewarmed"],
        "cold": results["cold"],
    }


# ---------------------------------------------------------------------------
# fleet phases (one process, RTT-shimmed shared queue)
# ---------------------------------------------------------------------------


def _fleet_sample(base) -> dict:
    _, resp = _get(base, "/api/debug/fleet")
    return resp["fleet"].get("autoscale") or {}


def _spawn_peer(jobs_mod, i: int):
    """An in-process peer replica with its own scheduler (the
    one-replica-per-box model, multi_replica's harness)."""
    from vrpms_tpu.sched import Scheduler

    sched = Scheduler(
        jobs_mod._runner,
        queue_limit=64,
        window_s=0.01,
        max_batch=1,
        on_event=jobs_mod._on_event,
        watchdog_s=0,
    )
    rep = jobs_mod.build_replica(
        f"peer-{i}", scheduler=sched,
        lease_s=5.0, poll_s=0.01, heartbeat_s=0.25,
    ).start()
    rep._bench_sched = sched
    return rep


def _stop_peer(rep) -> None:
    rep.stop()
    rep._bench_sched.shutdown(timeout=2.0)


def ramp_phase(base, jobs_mod, args, completions) -> tuple[dict, list]:
    """steady-1 -> burst (HPA emulation grows peers to the
    recommendation) -> drained -> back to 1."""
    # steady trickle: the recommendation must sit at 1
    steady_desired = []
    for i in range(3):
        status, resp = _post(base, "/api/jobs", _body(TRACE_N, 500 + i))
        assert status == 202, resp
        _wait_done(base, [resp["jobId"]])
        steady_desired.append(_fleet_sample(base).get("desired"))
    print(f"== ramp: steady desired {steady_desired}")

    samples: list = []
    stop = threading.Event()

    def sampler():
        t0 = time.monotonic()
        while not stop.is_set():
            try:
                block = _fleet_sample(base)
                samples.append({
                    "t": round(time.monotonic() - t0, 3),
                    "desired": block.get("desired"),
                    "raw": block.get("raw"),
                    "decision": block.get("decision"),
                    "members": block.get("members"),
                    "depth": block.get("depth"),
                })
            except Exception:
                pass
            time.sleep(0.15)

    st = threading.Thread(target=sampler, daemon=True)
    st.start()
    time.sleep(0.5)  # a few pre-burst samples at desired=1

    burst_ids = []
    for i in range(args.burst):
        status, resp = _post(base, "/api/jobs", _body(TRACE_N, 1000 + i))
        assert status == 202, resp
        burst_ids.append(resp["jobId"])

    # HPA emulation: grow in-process peers toward the recommendation
    peers: list = []
    done = {}
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        desired = _fleet_sample(base).get("desired") or 1
        while len(peers) < min(desired, CAP) - 1:
            peers.append(_spawn_peer(jobs_mod, len(peers)))
            print(f"   scale-up: peer-{len(peers) - 1} joins "
                  f"(desired {desired})")
        done = _wait_done(base, burst_ids, timeout_s=0.5)
        if all(done[j]["status"] == "done" for j in burst_ids):
            break
    assert all(done[j]["status"] == "done" for j in burst_ids), done
    # drained: the recommendation must return to 1 after cooldown
    final_desired = None
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        final_desired = _fleet_sample(base).get("desired")
        if final_desired == 1:
            break
        time.sleep(0.2)
    time.sleep(0.4)  # tail samples at the settled value
    stop.set()
    st.join(timeout=5)

    desired_series = [s["desired"] for s in samples if s["desired"]]
    tracks = all(
        s["desired"] >= min(s["raw"], CAP)
        for s in samples
        if s["desired"] and s["raw"]
    )
    records = [done[j] for j in burst_ids]
    out = {
        "steadyDesired": steady_desired,
        "burstJobs": args.burst,
        "done": sum(1 for r in records if r["status"] == "done"),
        "maxDesired": max(desired_series),
        "finalDesired": final_desired,
        "directionChanges": _direction_changes(desired_series),
        "tracksFeasibleMin": tracks,
        "attemptsLeq1": all(
            r.get("attempt") in (None, 1) for r in records
        ),
        "duplicateCompletions": sum(
            1 for j in burst_ids if completions[j] > 1
        ),
        "peersSpawned": len(peers),
        "samples": samples,
    }
    return out, peers


def scalein_phase(base, jobs_mod, peers, args, completions) -> dict:
    """Drain the service replica mid-backlog; peers finish the work."""
    if not peers:
        peers.append(_spawn_peer(jobs_mod, 0))
    job_ids = []
    for i in range(args.scalein_jobs):
        status, resp = _post(base, "/api/jobs", _body(TRACE_N, 2000 + i))
        assert status == 202, resp
        job_ids.append(resp["jobId"])
    time.sleep(0.4)  # let the service replica lease some of them
    self_id = jobs_mod.replica_id()
    status, resp = _post(
        base, "/api/admin/scalein",
        {"replicaId": self_id, "graceS": 0.3},
    )
    assert status == 202, resp
    print(f"== scalein: victim {resp['scalein']['victim']} (local)")
    # drain completes: leases finished within grace or checkpoint-
    # nacked to the peers
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        _, d = _get(base, "/api/admin/drain")
        if (d.get("drain") or {}).get("complete"):
            break
        time.sleep(0.1)
    done = _wait_done(base, job_ids, timeout_s=180)
    records = [done[j] for j in job_ids]
    return {
        "victim": resp["scalein"]["victim"],
        "local": bool(resp["scalein"].get("local")),
        "jobs": args.scalein_jobs,
        "done": sum(1 for r in records if r["status"] == "done"),
        "lost": sum(1 for r in records if r["status"] == "timeout"),
        "requeued": (d.get("drain") or {}).get("requeued"),
        "attemptsLeq1": all(
            r.get("attempt") in (None, 1) for r in records
        ),
        "burnedAttempts": sum(
            1 for r in records if (r.get("attempt") or 1) > 1
        ),
        "duplicateCompletions": sum(
            1 for j in job_ids if completions[j] > 1
        ),
    }


def overhead_phase(base, args) -> dict:
    """Same-seed paired on/off micro-blocks, finely interleaved; an
    HPA poller hits /api/debug/fleet at 4 Hz in BOTH arms. Host timing
    on a shared box drifts in multi-second regimes (frequency,
    placement) with ~5% fast jitter on top, so long per-arm rounds
    alias a regime shift straight into the paired delta; instead each
    pair runs a 2-job block per arm back-to-back (~2s window, drift
    ~constant across it) with the SAME seeds in both arms (per-seed
    local-search effort differs — identical data cancels it), and the
    median over many pairs shrugs off the regime-boundary outliers.
    Runs after the ramp peers scaled back in: one claim loop."""
    stop = threading.Event()

    def poller():
        while not stop.is_set():
            try:
                _get(base, "/api/debug/fleet")
            except Exception:
                pass
            time.sleep(0.25)

    pt = threading.Thread(target=poller, daemon=True)
    pt.start()

    def block(seed0: int) -> float:
        t0 = time.perf_counter()
        ids = []
        for i in range(args.block_jobs):
            status, resp = _post(
                base, "/api/jobs", _body(TRACE_N, seed0 + i)
            )
            assert status == 202, resp
            ids.append(resp["jobId"])
        done = _wait_done(base, ids, timeout_s=120)
        assert all(done[j]["status"] == "done" for j in ids), done
        return time.perf_counter() - t0

    block(8000)
    block(8100)  # warm both arms' steady state
    deltas, on_total, off_total = [], 0.0, 0.0
    for p in range(args.pairs):
        order = ("off", "on") if p % 2 == 0 else ("on", "off")
        t = {}
        for arm in order:
            os.environ["VRPMS_AUTOSCALE"] = arm
            t[arm] = block(9000 + 10 * p)
        deltas.append((t["on"] - t["off"]) / t["off"])
        on_total += t["on"]
        off_total += t["off"]
    os.environ.pop("VRPMS_AUTOSCALE", None)
    stop.set()
    pt.join(timeout=5)
    overhead_pct = 100.0 * statistics.median(deltas)
    aggregate_pct = 100.0 * (on_total - off_total) / off_total
    print(f"== overhead: on {on_total:.2f}s / off {off_total:.2f}s "
          f"median {overhead_pct:+.2f}% aggregate {aggregate_pct:+.2f}%")
    return {
        "pairs": args.pairs,
        "blockJobs": args.block_jobs,
        "onSeconds": round(on_total, 3),
        "offSeconds": round(off_total, 3),
        "pairDeltasPct": [round(100 * d, 2) for d in deltas],
        "aggregatePct": round(aggregate_pct, 3),
        "overheadPct": round(overhead_pct, 3),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--churn-child", help=argparse.SUPPRESS)
    ap.add_argument("--rtt-ms", type=float, default=25.0)
    ap.add_argument("--burst", type=int, default=20)
    ap.add_argument("--scalein-jobs", type=int, default=10)
    ap.add_argument("--pairs", type=int, default=96)
    ap.add_argument("--block-jobs", type=int, default=2)
    ap.add_argument("--skip-churn", action="store_true")
    ap.add_argument("--out", default=None)
    ap.add_argument("--note", default=None)
    args = ap.parse_args()
    if args.churn_child:
        _churn_child(args.churn_child)
        return

    os.environ["VRPMS_STORE"] = "memory"
    # churn pre-warm rides the boot-warmup switch; serve() never acts
    # on it (only the CLI does), so setting it here arms the watcher
    # without paying a boot compile
    os.environ["VRPMS_WARMUP"] = "tiers"
    os.environ["VRPMS_QUEUE_POLL_MS"] = "10"
    os.environ["VRPMS_RECLAIM_S"] = "0.5"
    os.environ["VRPMS_LEASE_S"] = "5"
    os.environ["VRPMS_HEARTBEAT_S"] = "0.25"
    # one lease per replica: fleet size IS the concurrency knob, so
    # the QoS-feasible minimum is directly actuator-visible
    os.environ["VRPMS_QUEUE_MAX_INFLIGHT"] = "1"
    # solo dispatch + cache off: no batch-shape compiles or cache hits
    # inside measurement windows (the multi_replica convention)
    os.environ["VRPMS_SCHED_MAX_BATCH"] = "1"
    os.environ["VRPMS_CACHE"] = "off"
    # a tight controller: headroom/cooldown sized so a ~20-job burst
    # of subsecond solves walks the whole 1 -> 4 -> 1 ramp in seconds
    os.environ["VRPMS_AUTOSCALE_HEADROOM_S"] = "2"
    # long enough that EWMA drift under 4-way CPU contention cannot
    # bounce a mid-burst down into an immediate re-up (flap guard)
    os.environ["VRPMS_AUTOSCALE_COOLDOWN_S"] = "2.5"
    os.environ["VRPMS_AUTOSCALE_MAX"] = str(CAP)
    os.environ["VRPMS_DEPTH_MEMO_MS"] = "100"
    _seed_store(TRACE_N)

    import store
    from store.memory import InMemoryJobQueue
    from service import autoscale as autoscale_mod
    from service import jobs as jobs_mod
    from service.app import serve

    rtt_s = args.rtt_ms / 1e3
    store.get_queue_store = lambda: _RttStore(InMemoryJobQueue(), rtt_s)

    # acked-completion spy: exactly-once evidence for the gates
    completions: collections.Counter = collections.Counter()
    real_complete = jobs_mod._dist_complete

    def spy_complete(job, entry, acked):
        if acked:
            completions[job.id] += 1
        return real_complete(job, entry, acked)

    jobs_mod._dist_complete = spy_complete

    # peer joins churn the ring and the REAL watcher fires; intercept
    # its background warmup at the seam (see module docstring) —
    # launches are still counted as evidence the watcher ran
    churn_warm_launches: list = []
    autoscale_mod._launch_warmup = churn_warm_launches.append

    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    os.environ["VRPMS_QUEUE"] = "store"
    jobs_mod.get_replica()  # the service replica claims from boot

    print("== prewarm: compiling the trace shape")
    warm = []
    for i in range(2):
        status, resp = _post(base, "/api/jobs", _body(TRACE_N, 900 + i))
        assert status == 202, resp
        warm.append(resp["jobId"])
    _wait_done(base, warm)

    ramp, peers = ramp_phase(base, jobs_mod, args, completions)
    print(json.dumps({k: v for k, v in ramp.items() if k != "samples"},
                     indent=2))
    # the ramp ended at desired 1: scale the peers back in before the
    # timing phase (one claim loop = minimal jitter), scalein respawns
    for rep in peers:
        _stop_peer(rep)
    peers = []
    overhead = overhead_phase(base, args)
    scalein = scalein_phase(base, jobs_mod, peers, args, completions)
    print(json.dumps(scalein, indent=2))

    for rep in peers:
        _stop_peer(rep)
    jobs_mod.shutdown_scheduler()
    srv.shutdown()

    churn = None if args.skip_churn else churn_phase()

    gate = {
        "rampTracksFeasibleMin": ramp["tracksFeasibleMin"],
        "maxDesired": ramp["maxDesired"],
        "cap": CAP,
        "finalDesired": ramp["finalDesired"],
        "directionChanges": ramp["directionChanges"],
        "directionChangesMax": 3,
        "jobsLost": scalein["lost"]
        + (ramp["burstJobs"] - ramp["done"]),
        "burnedAttempts": scalein["burnedAttempts"],
        "duplicateCompletions": ramp["duplicateCompletions"]
        + scalein["duplicateCompletions"],
        "overheadPct": overhead["overheadPct"],
        "overheadMax": 1.0,
    }
    checks = [
        gate["rampTracksFeasibleMin"],
        gate["maxDesired"] == CAP,
        gate["finalDesired"] == 1,
        gate["directionChanges"] <= gate["directionChangesMax"],
        gate["jobsLost"] == 0,
        gate["burnedAttempts"] == 0,
        gate["duplicateCompletions"] == 0,
        ramp["attemptsLeq1"] and scalein["attemptsLeq1"],
        gate["overheadPct"] < gate["overheadMax"],
    ]
    if churn is not None:
        gate["steadyCompiles"] = churn["prewarmed"]["steadyCompiles"]
        gate["postChurnCompiles"] = churn["prewarmed"]["servingCompiles"]
        gate["coldChurnCompiles"] = churn["cold"]["servingCompiles"]
        checks.append(
            gate["postChurnCompiles"] <= 2 * gate["steadyCompiles"]
        )
        # no vacuous pass: the hardening must beat the cold contrast
        checks.append(
            gate["coldChurnCompiles"] > gate["postChurnCompiles"]
        )
    gate["pass"] = all(checks)

    record = {
        "bench": "elastic_fleet",
        "generatedAt": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "note": args.note,
        "config": {
            "rttMs": args.rtt_ms,
            "burst": args.burst,
            "scaleinJobs": args.scalein_jobs,
            "pairs": args.pairs,
            "blockJobs": args.block_jobs,
            "traceN": TRACE_N,
            "headroomS": 2.0,
            "cooldownS": 2.5,
            "cap": CAP,
            "maxInflight": 1,
        },
        "ramp": ramp,
        "scalein": scalein,
        "churn": churn,
        "overhead": overhead,
        "churnWarmLaunchesDuringRamp": len(churn_warm_launches),
        "gate": gate,
    }
    print(json.dumps({"gate": gate}, indent=2))
    if args.out:
        path = args.out
        if not os.path.isabs(path):
            path = os.path.join(os.path.dirname(__file__), path)
        with open(path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(f"wrote {path}")
    if not gate["pass"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
