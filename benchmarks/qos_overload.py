"""QoS overload benchmark: interactive p99 holds while batch sheds.

The ISSUE-12 acceptance gate, chaos_latency-style: a 2-replica
in-process fleet on the store-backed queue (RTT-shimmed like
batched_claims — the hosted store's real per-op cost) is driven at
~2x sustained overload with a mixed-class trace (interactive +
standard + batch closed-loop clients; batch clients retry shortly
after each 429, keeping the offered load above fleet capacity).
Everything QoS promises has to show up at once:

  * claim ordering + priority pop: interactive-class requests jump the
    shared backlog AND the local queue, so their p99 stays within 1.3x
    of the same fleet's UNLOADED interactive baseline;
  * selective shed: the batch class admits only to its fraction of the
    admission bound (VRPMS_QOS_SHED_BATCH, 0.5 default) and standard
    to its (set to 0.8 here), so >= 80% of all 429s land on batch;
  * equal correctness: fixed-seed probes through the loaded fleet
    visit the exact customer set.

A contrast phase re-runs the same overload with VRPMS_QOS=off (plain
FIFO, uniform shed) and records interactive p99 there — the delta is
the subsystem's whole point, but it is recorded, not gated (FIFO
interactive latency under overload is backlog-bound and noisy).

The trace is the PR-2 overhead-bound regime (single-chain SA on one
tiny tier): per-launch fixed cost dominates, which is the only regime
where scheduling effects are measurable on this 1-core container.

Gate (asserted — the script exits nonzero on failure): loaded
interactive p99 <= 1.3x unloaded interactive p99, batch absorbs >= 80%
of sheds, zero failures among admitted jobs, correctness probes exact.

    JAX_PLATFORMS=cpu python -m benchmarks.qos_overload \
        [--duration 12] [--warmup 4] [--rtt-ms 25] \
        [--out records/qos_overload_r16.json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import threading
import time

from benchmarks.batched_claims import _RttQueue
from benchmarks.multi_replica import _body, _get, _post, _seed_store


def _job_body(n, iters, pop, seed, qos=None, time_limit=None) -> dict:
    body = _body(n, iters, pop, seed)
    if qos is not None:
        body["qos"] = qos
    if time_limit is not None:
        body["timeLimit"] = time_limit
    return body


def _pct(sorted_ms, p):
    if not sorted_ms:
        return None
    k = min(len(sorted_ms) - 1, int(round(p / 100 * (len(sorted_ms) - 1))))
    return round(sorted_ms[k], 1)


class _Clients:
    """Closed-loop mixed-class clients: submit -> poll -> next; a 429
    counts as a shed for the client's class and retries after a short
    backoff (NOT the full Retry-After — the bench needs the offered
    load to stay ~2x capacity, which a fully obedient client would
    collapse)."""

    def __init__(self, base, n, iters, pop):
        self.base = base
        self.n, self.iters, self.pop = n, iters, pop
        self.stop = threading.Event()
        self.measuring = threading.Event()
        self.lock = threading.Lock()
        self.latencies: dict = {}   # class -> [seconds]
        self.sheds: dict = {}       # class -> count
        self.failures: dict = {}    # class -> count
        self.attempts: dict = {}    # class -> count
        self.threads: list = []

    def _client(self, qos_class, seed0, time_limit, backoff_s):
        seed = seed0
        while not self.stop.is_set():
            seed += 1
            t0 = time.perf_counter()
            status, resp = _post(
                self.base, "/api/jobs",
                _job_body(self.n, self.iters, self.pop, seed,
                          qos=qos_class, time_limit=time_limit),
            )
            if self.measuring.is_set():
                with self.lock:
                    self.attempts[qos_class] = (
                        self.attempts.get(qos_class, 0) + 1
                    )
            if status == 429:
                if self.measuring.is_set():
                    with self.lock:
                        self.sheds[qos_class] = (
                            self.sheds.get(qos_class, 0) + 1
                        )
                time.sleep(backoff_s)
                continue
            ok = status == 202
            if ok:
                jid = resp["jobId"]
                while not self.stop.is_set():
                    _, r = _get(self.base, f"/api/jobs/{jid}")
                    if r["job"]["status"] in ("done", "failed"):
                        ok = r["job"]["status"] == "done"
                        break
                    time.sleep(0.03)
            dt = time.perf_counter() - t0
            if not self.measuring.is_set():
                continue
            with self.lock:
                if ok:
                    self.latencies.setdefault(qos_class, []).append(dt)
                else:
                    self.failures[qos_class] = (
                        self.failures.get(qos_class, 0) + 1
                    )

    def spawn(self, qos_class, count, time_limit=None, backoff_s=0.2):
        for i in range(count):
            t = threading.Thread(
                target=self._client,
                args=(qos_class, 10_000 * (len(self.threads) + 1),
                      time_limit, backoff_s),
                daemon=True,
            )
            self.threads.append(t)
            t.start()

    def run(self, warmup_s, duration_s) -> dict:
        time.sleep(warmup_s)
        self.measuring.set()
        t0 = time.perf_counter()
        time.sleep(duration_s)
        measured = time.perf_counter() - t0
        self.stop.set()
        for t in self.threads:
            t.join(timeout=300)
        out: dict = {"measuredSeconds": round(measured, 2), "classes": {}}
        total_done = total_attempts = 0
        with self.lock:
            for cls in ("interactive", "standard", "batch"):
                lat = sorted(1e3 * x for x in self.latencies.get(cls, []))
                if not lat and cls not in self.attempts:
                    continue
                out["classes"][cls] = {
                    "done": len(lat),
                    "attempts": self.attempts.get(cls, 0),
                    "sheds": self.sheds.get(cls, 0),
                    "failures": self.failures.get(cls, 0),
                    "p50Ms": _pct(lat, 50),
                    "p99Ms": _pct(lat, 99),
                    "meanMs": (
                        round(statistics.mean(lat), 1) if lat else None
                    ),
                }
                total_done += len(lat)
                total_attempts += self.attempts.get(cls, 0)
        out["jobsPerSec"] = round(total_done / measured, 2)
        out["offeredFactor"] = (
            round(total_attempts / max(1, total_done), 2)
        )
        return out


def _correctness_probe(base, n, iters, pop, seeds) -> dict:
    """Fixed-seed solves THROUGH the loaded fleet, one per class:
    every result must visit the exact customer set."""
    costs = []
    for seed, cls in zip(seeds, ("interactive", "standard", "batch")):
        status = resp = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            status, resp = _post(
                base, "/api/jobs",
                _job_body(n, iters, pop, seed, qos=cls),
            )
            if status == 202:
                break
            time.sleep(0.3)  # shed: the probe retries into the load
        assert status == 202, resp
        job = None
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            _, r = _get(base, f"/api/jobs/{resp['jobId']}")
            if r["job"]["status"] in ("done", "failed"):
                job = r["job"]
                break
            time.sleep(0.05)
        assert job is not None and job["status"] == "done", job
        visited = sorted(
            c for v in job["message"]["vehicles"] for c in v["tour"][1:-1]
        )
        assert visited == list(range(1, n)), (
            f"seed {seed} ({cls}): visited {visited}"
        )
        costs.append(job["message"]["durationSum"])
    return {"seeds": list(seeds), "durationSums": costs, "valid": True}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--duration", type=float, default=12.0)
    ap.add_argument("--warmup", type=float, default=4.0)
    ap.add_argument("--n", type=int, default=12)
    ap.add_argument("--iters", type=int, default=600)
    ap.add_argument("--pop", type=int, default=1)
    ap.add_argument("--max-batch", type=int, default=2)
    ap.add_argument("--rtt-ms", type=float, default=25.0)
    ap.add_argument("--interactive-clients", type=int, default=2)
    ap.add_argument("--standard-clients", type=int, default=2)
    ap.add_argument("--batch-clients", type=int, default=8)
    ap.add_argument("--out", default=None)
    ap.add_argument("--note", default=None)
    args = ap.parse_args()

    os.environ["VRPMS_STORE"] = "memory"
    os.environ["VRPMS_QUEUE_POLL_MS"] = "5"
    os.environ["VRPMS_RECLAIM_S"] = "0.5"
    os.environ["VRPMS_CACHE"] = "off"  # hits would hide the economics
    os.environ["VRPMS_SCHED_MAX_BATCH"] = str(args.max_batch)
    # a small admission bound + a ONE-lease ceiling make overload (and
    # shedding) reachable with a handful of clients on one core: each
    # replica leases a single entry at a time, so fleet capacity is
    # pinned at the claim/ack round-trip cost (the store RTT — the
    # regime where latency is fixed-cost-dominated and the scheduling
    # decision, WHICH entry each claim takes, is the whole game) and
    # excess work accumulates as SHARED depth where the class
    # fractions act on it (fleet bound = 4 x 2 replicas = 8; batch
    # sheds at 4, standard at 6, interactive rides to 8). Standard
    # reserves 20% headroom for interactive on top of batch's default
    # 50%.
    os.environ["VRPMS_SCHED_QUEUE"] = "4"
    os.environ["VRPMS_QUEUE_MAX_INFLIGHT"] = "1"
    os.environ["VRPMS_QOS_SHED_STANDARD"] = "0.8"
    _seed_store(args.n)

    import store
    from store.memory import InMemoryJobQueue
    from service import jobs as jobs_mod
    from service.app import serve
    from vrpms_tpu.sched import Scheduler

    rtt_s = args.rtt_ms / 1e3
    real_factory = store.get_queue_store
    store.get_queue_store = lambda: _RttQueue(InMemoryJobQueue(), rtt_s)

    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    # deterministic prewarm (the batched_claims recipe): one lone HTTP
    # job compiles the solo dispatch, direct stacked launches compile
    # every K <= max_batch
    os.environ["VRPMS_QUEUE"] = "local"
    print("== prewarm: compiling the trace shape (solo + stacked K, "
          "with and without a deadline — interactive jobs carry "
          "timeLimit, so their solve variant differs)")
    for seed, tl in ((900, None), (901, 30)):
        status, resp = _post(
            base, "/api/jobs",
            _job_body(args.n, args.iters, args.pop, seed, time_limit=tl),
        )
        assert status == 202, resp
        while True:
            _, r = _get(base, f"/api/jobs/{resp['jobId']}")
            if r["job"]["status"] in ("done", "failed"):
                break
            time.sleep(0.05)
    jobs_mod.shutdown_scheduler()
    from vrpms_tpu.core import tiers
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.sched.batch import solve_sa_batch
    from vrpms_tpu.solvers import SAParams

    insts = [
        tiers.maybe_pad(synth_cvrp(args.n, 3, seed=s))
        for s in range(args.max_batch)
    ]
    params = SAParams(n_chains=args.pop, n_iters=args.iters)
    for k in range(2, args.max_batch + 1):
        for dl in (None, 30.0):
            print(f"   stacked launch K={k} deadline={dl}")
            solve_sa_batch(insts[:k], list(range(k)), params=params,
                           deadline_s=dl)

    def fleet():
        """The 2-replica fleet: the service's own replica + one
        in-process peer with its own scheduler (one-per-box)."""
        sched = Scheduler(
            jobs_mod._runner,
            queue_limit=int(os.environ["VRPMS_SCHED_QUEUE"]),
            window_s=float(
                os.environ.get("VRPMS_SCHED_WINDOW_MS", "10")
            ) / 1e3,
            max_batch=args.max_batch,
            on_event=jobs_mod._on_event,
            watchdog_s=0,
            queue_policy=(
                jobs_mod.get_qos_policy()
                if jobs_mod.qos_enabled() else None
            ),
        )
        peer = jobs_mod.build_replica(
            "qos-bench-peer", scheduler=sched,
            lease_s=10.0, poll_s=0.005, heartbeat_s=0.5,
        ).start()
        return sched, peer

    out: dict = {}
    try:
        os.environ["VRPMS_QUEUE"] = "store"

        # -- phase 1: unloaded interactive baseline --------------------
        sched, peer = fleet()
        print("== baseline: unloaded interactive clients")
        clients = _Clients(base, args.n, args.iters, args.pop)
        clients.spawn("interactive", args.interactive_clients,
                      time_limit=30)
        out["baseline"] = clients.run(args.warmup, args.duration)
        print(json.dumps(out["baseline"], indent=2))
        peer.stop()
        sched.shutdown(timeout=2.0)
        jobs_mod.shutdown_scheduler()

        # -- phase 2: ~2x overload, mixed classes, QoS on --------------
        sched, peer = fleet()
        print("== overload: mixed classes, QoS on")
        clients = _Clients(base, args.n, args.iters, args.pop)
        clients.spawn("interactive", args.interactive_clients,
                      time_limit=30)
        clients.spawn("standard", args.standard_clients)
        clients.spawn("batch", args.batch_clients)
        out["overload"] = clients.run(args.warmup, args.duration)
        print(json.dumps(out["overload"], indent=2))
        out["overload"]["correctness"] = _correctness_probe(
            base, args.n, args.iters, args.pop, seeds=(7801, 7802, 7803)
        )
        peer.stop()
        sched.shutdown(timeout=2.0)
        jobs_mod.shutdown_scheduler()

        # -- phase 3 (contrast, recorded not gated): QoS off -----------
        os.environ["VRPMS_QOS"] = "off"
        sched, peer = fleet()
        print("== contrast: same overload, VRPMS_QOS=off (plain FIFO)")
        clients = _Clients(base, args.n, args.iters, args.pop)
        clients.spawn("interactive", args.interactive_clients,
                      time_limit=30)
        clients.spawn("standard", args.standard_clients)
        clients.spawn("batch", args.batch_clients)
        out["fifoContrast"] = clients.run(args.warmup, args.duration)
        print(json.dumps(out["fifoContrast"], indent=2))
        peer.stop()
        sched.shutdown(timeout=2.0)
        jobs_mod.shutdown_scheduler()
    finally:
        store.get_queue_store = real_factory
        for var in ("VRPMS_QUEUE", "VRPMS_QOS", "VRPMS_SCHED_QUEUE",
                    "VRPMS_QOS_SHED_STANDARD", "VRPMS_SCHED_MAX_BATCH",
                    "VRPMS_QUEUE_MAX_INFLIGHT", "VRPMS_CACHE"):
            os.environ.pop(var, None)
        srv.shutdown()

    base_p99 = out["baseline"]["classes"]["interactive"]["p99Ms"]
    load_p99 = out["overload"]["classes"]["interactive"]["p99Ms"]
    sheds = {
        cls: info["sheds"]
        for cls, info in out["overload"]["classes"].items()
    }
    total_sheds = sum(sheds.values())
    batch_share = sheds.get("batch", 0) / total_sheds if total_sheds else 0.0
    failures = sum(
        info["failures"] for info in out["overload"]["classes"].values()
    )
    ratio = load_p99 / base_p99 if base_p99 else float("inf")
    out["gate"] = {
        "interactiveP99Ratio": round(ratio, 3),
        "interactiveP99RatioMax": 1.3,
        "batchShedShare": round(batch_share, 3),
        "batchShedShareMin": 0.8,
        "totalSheds": total_sheds,
        "overloadFactor": out["overload"]["offeredFactor"],
        "pass": (
            ratio <= 1.3
            and batch_share >= 0.8
            and total_sheds > 0
            and failures == 0
            and out["overload"]["correctness"]["valid"]
        ),
    }
    print(
        f"qos-overload gate (interactive p99 {load_p99}ms <= 1.3x "
        f"baseline {base_p99}ms = {ratio:.2f}x; batch shed share "
        f"{batch_share:.0%} >= 80%): "
        f"{'PASS' if out['gate']['pass'] else 'FAIL'}"
    )

    import jax

    record = {
        "benchmark": "qos_overload",
        "backend": jax.default_backend(),
        "note": args.note,
        "config": {
            "duration": args.duration,
            "n": args.n,
            "iterationCount": args.iters,
            "populationSize": args.pop,
            "maxBatch": args.max_batch,
            "queueRttMs": args.rtt_ms,
            "replicas": 2,
            "schedQueue": 4,
            "maxInflight": 1,
            "shedStandard": 0.8,
            "clients": {
                "interactive": args.interactive_clients,
                "standard": args.standard_clients,
                "batch": args.batch_clients,
            },
        },
        "results": out,
    }
    if args.out:
        path = args.out if os.path.isabs(args.out) else os.path.join(
            os.path.dirname(__file__), args.out
        )
        with open(path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(f"record -> {path}")
    if not out["gate"]["pass"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
