"""Batched-claims benchmark: claim-K fleets vs single-claim fleets.

The ISSUE-11 perf gate. Under `VRPMS_QUEUE=store`, a single-claim fleet
leases same-tier jobs one conditional update at a time: every entry
costs the replica loop a claim round trip plus an ack round trip, both
serialized on the loop thread, and jobs trickle into the local queue at
claim-RTT cadence — K jobs a single box would have vmapped together run
as K launches fed at store speed. Claim-K-matching
(`JobQueueStore.claim_batch`) leases the same backlog in ONE
conditional update and submits it with batch hints, so the per-job
store cost collapses to RTT/K + ack and the worker assembles one
vmapped launch with no window wait.

Setup (all CPU-verifiable):

  * the PR-2 overhead-bound regime (records/sched_throughput_r7.json):
    single-chain SA (`populationSize=1`) on one tiny tier — per-launch
    fixed cost (dispatch + scan-step overhead + threefry presample)
    dominates per-chain math, which is the one regime where batching
    multiplies throughput on this 1-core container (compute-bound
    regimes need TPU parallelism for the vmap dividend);
  * a 2-replica in-process fleet (the service's own replica + one peer
    with its own scheduler) on the shared store-backed queue;
  * the queue store is the in-memory backend behind a fixed per-op RTT
    shim (default 25 ms — conservative for the hosted Supabase HTTPS
    API): claims are the variable under test and their real-world cost
    IS the round trip, which an in-process memory table would
    otherwise hide. Job records stay on the plain memory store.
  * closed-loop async clients (submit -> poll -> next), identical trace
    in both modes; the ONLY difference between modes is
    VRPMS_CLAIM_BATCH=1 (single) vs =max_batch (claim-K).

Prewarm is DETERMINISTIC: one lone HTTP job compiles the solo service
dispatch (it can only launch alone), then direct solve_sa_batch calls
compile every stacked K <= max_batch — no mode ever pays a stacked-
launch compile inside its measurement window.

Gate: batched-claim jobs/sec >= 1.5x single-claim, zero failures in
both modes, and every correctness-probe solution visits the exact
customer set. (Exactly-once + lease semantics at K>1 are proven by
tests/test_distqueue.py, which CI runs in full.)

    JAX_PLATFORMS=cpu python -m benchmarks.batched_claims \
        [--duration 10] [--warmup 5] [--clients 16] [--iters 600] \
        [--pop 1] [--rtt-ms 25] [--out records/batched_claims_r15.json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import threading
import time

from benchmarks.multi_replica import _body, _get, _post, _seed_store


class _RttQueue:
    """The in-memory shared queue behind a fixed per-op round-trip
    delay: every queue operation — enqueue, claim, claim_batch, renew,
    ack, nack, reclaim, depth, membership — pays the same RTT a hosted
    queue store charges, so the single-claim loop's K round trips vs
    claim-K's one are measured at their real relative cost."""

    def __init__(self, inner, rtt_s: float):
        self._inner = inner
        self._rtt = rtt_s

    def _call(self, name, *args, **kw):
        if self._rtt > 0:
            time.sleep(self._rtt)
        return getattr(self._inner, name)(*args, **kw)

    def enqueue(self, entry):
        return self._call("enqueue", entry)

    def claim(self, owner, lease_s, slots=None):
        return self._call("claim", owner, lease_s, slots)

    def claim_batch(self, owner, lease_s, k, slots=None):
        return self._call("claim_batch", owner, lease_s, k, slots)

    def renew(self, owner, job_id, lease_s):
        return self._call("renew", owner, job_id, lease_s)

    def ack(self, owner, job_id):
        return self._call("ack", owner, job_id)

    def nack(self, owner, job_id):
        return self._call("nack", owner, job_id)

    def reclaim_expired(self, max_attempts=None):
        return self._call("reclaim_expired", max_attempts)

    def depth(self):
        return self._call("depth")

    def register_replica(self, replica_id, ttl_s):
        return self._call("register_replica", replica_id, ttl_s)

    def replicas(self):
        return self._call("replicas")


def _drive(base, n, clients, duration_s, warmup_s, iters, pop) -> dict:
    """Closed-loop async clients: submit -> poll to terminal -> next.
    Polls at a 20 ms cadence — gentle enough that 16 client threads do
    not saturate the single core with HTTP handling (the bottleneck
    under test is the claim path, not the poll storm)."""
    stop = threading.Event()
    measuring = threading.Event()
    latencies: list[float] = []
    failures: list = []
    lock = threading.Lock()

    def client(i: int) -> None:
        seed = 1000 * i
        while not stop.is_set():
            seed += 1
            t0 = time.perf_counter()
            status, resp = _post(base, "/api/jobs",
                                 _body(n, iters, pop, seed))
            ok = status == 202
            if ok:
                jid = resp["jobId"]
                while not stop.is_set():
                    _, r = _get(base, f"/api/jobs/{jid}")
                    if r["job"]["status"] in ("done", "failed"):
                        ok = r["job"]["status"] == "done"
                        break
                    time.sleep(0.02)
            dt = time.perf_counter() - t0
            if not measuring.is_set():
                continue
            with lock:
                (latencies if ok else failures).append(dt)

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    time.sleep(warmup_s)
    measuring.set()
    t_meas = time.perf_counter()
    time.sleep(duration_s)
    measured_s = time.perf_counter() - t_meas
    stop.set()
    for t in threads:
        t.join(timeout=300)
    lat_ms = sorted(1e3 * x for x in latencies)

    def pct(p):
        if not lat_ms:
            return None
        k = min(len(lat_ms) - 1, int(round(p / 100 * (len(lat_ms) - 1))))
        return round(lat_ms[k], 1)

    return {
        "jobs": len(lat_ms),
        "jobsPerSec": round(len(lat_ms) / measured_s, 2),
        "p50Ms": pct(50),
        "p99Ms": pct(99),
        "meanMs": round(statistics.mean(lat_ms), 1) if lat_ms else None,
        "failures": len(failures),
        "measuredSeconds": round(measured_s, 2),
    }


def _poll_done(base, job_id, timeout=180.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        _, r = _get(base, f"/api/jobs/{job_id}")
        if r["job"]["status"] in ("done", "failed"):
            return r["job"]
        time.sleep(0.02)
    raise RuntimeError(f"job {job_id} never finished")


def _correctness_probe(base, n, iters, pop, seeds) -> dict:
    """Fixed-seed solves through the mode under test: every result must
    visit the exact customer set (equal correctness — the batched path
    must produce valid solutions, not just fast ones)."""
    costs = []
    for seed in seeds:
        status, resp = _post(base, "/api/jobs", _body(n, iters, pop, seed))
        assert status == 202, resp
        job = _poll_done(base, resp["jobId"])
        assert job["status"] == "done", job
        visited = sorted(
            c for v in job["message"]["vehicles"] for c in v["tour"][1:-1]
        )
        assert visited == list(range(1, n)), (
            f"seed {seed}: visited {visited}"
        )
        costs.append(job["message"]["durationSum"])
    return {"seeds": list(seeds), "durationSums": costs, "valid": True}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--warmup", type=float, default=5.0)
    ap.add_argument("--n", type=int, default=12)
    ap.add_argument("--iters", type=int, default=600)
    ap.add_argument("--pop", type=int, default=1)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--rtt-ms", type=float, default=25.0)
    ap.add_argument("--out", default=None)
    ap.add_argument("--note", default=None)
    args = ap.parse_args()

    os.environ["VRPMS_STORE"] = "memory"
    os.environ["VRPMS_QUEUE_POLL_MS"] = "5"
    os.environ["VRPMS_RECLAIM_S"] = "0.5"
    # cache off: a hit would serve jobs at store-read latency and hide
    # the launch economics under test (the multi_replica precedent)
    os.environ["VRPMS_CACHE"] = "off"
    # one bounded stacked-shape family: every K in 2..max_batch is
    # prewarmed below, so no mode compiles inside a measurement window
    os.environ["VRPMS_SCHED_MAX_BATCH"] = str(args.max_batch)
    _seed_store(args.n)

    import store
    from store.memory import InMemoryJobQueue
    from service import jobs as jobs_mod
    from service.app import serve
    from vrpms_tpu.sched import Scheduler

    rtt_s = args.rtt_ms / 1e3
    real_factory = store.get_queue_store
    store.get_queue_store = lambda: _RttQueue(InMemoryJobQueue(), rtt_s)

    # claim-batch-size spy: the mean assembled size per mode is the
    # mechanism's own evidence (single mode must sit at 1.0)
    sizes: list = []
    orig_event = jobs_mod._dist_event

    def spy_event(name, replicaId=None, **kw):
        if name == "claim_batch":
            sizes.append(int(kw.get("size") or 1))
        return orig_event(name, replicaId=replicaId, **kw)

    jobs_mod._dist_event = spy_event

    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    # deterministic prewarm (see module docstring)
    os.environ["VRPMS_QUEUE"] = "local"
    print("== prewarm: compiling the trace shape (solo + stacked K)")
    status, resp = _post(
        base, "/api/jobs", _body(args.n, args.iters, args.pop, 900)
    )
    assert status == 202, resp
    _poll_done(base, resp["jobId"])
    jobs_mod.shutdown_scheduler()
    from vrpms_tpu.core import tiers
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.sched.batch import solve_sa_batch
    from vrpms_tpu.solvers import SAParams

    insts = [
        tiers.maybe_pad(synth_cvrp(args.n, 3, seed=s))
        for s in range(args.max_batch)
    ]
    params = SAParams(n_chains=args.pop, n_iters=args.iters)
    for k in range(2, args.max_batch + 1):
        print(f"   stacked launch K={k}")
        solve_sa_batch(insts[:k], list(range(k)), params=params,
                       deadline_s=None)

    out: dict = {}
    try:
        for label, claim_batch in (
            ("single", "1"),
            ("batched", str(args.max_batch)),
        ):
            os.environ["VRPMS_QUEUE"] = "store"
            os.environ["VRPMS_CLAIM_BATCH"] = claim_batch
            del sizes[:]
            # the 2-replica fleet: the service's own replica plus one
            # in-process peer with its own scheduler (one-per-box)
            sched = Scheduler(
                jobs_mod._runner,
                queue_limit=int(os.environ.get("VRPMS_SCHED_QUEUE", "64")),
                window_s=float(
                    os.environ.get("VRPMS_SCHED_WINDOW_MS", "10")
                ) / 1e3,
                max_batch=args.max_batch,
                on_event=jobs_mod._on_event,
                watchdog_s=0,
            )
            peer = jobs_mod.build_replica(
                f"bench-peer-{label}", scheduler=sched,
                lease_s=10.0, poll_s=0.005, heartbeat_s=0.5,
            ).start()
            print(f"== {label}-claim fleet: {args.clients} clients, "
                  f"{args.duration:.0f}s measure, rtt {args.rtt_ms:g}ms")
            out[label] = _drive(
                base, args.n, args.clients, args.duration, args.warmup,
                args.iters, args.pop,
            )
            out[label]["claimRounds"] = len(sizes)
            out[label]["meanClaimBatch"] = (
                round(sum(sizes) / len(sizes), 2) if sizes else None
            )
            out[label]["maxClaimBatch"] = max(sizes) if sizes else None
            out[label]["correctness"] = _correctness_probe(
                base, args.n, args.iters, args.pop,
                seeds=range(7700, 7703),
            )
            print(json.dumps(out[label], indent=2))
            peer.stop()
            sched.shutdown(timeout=2.0)
            jobs_mod.shutdown_scheduler()
    finally:
        jobs_mod._dist_event = orig_event
        store.get_queue_store = real_factory
        for var in ("VRPMS_QUEUE", "VRPMS_CLAIM_BATCH",
                    "VRPMS_SCHED_MAX_BATCH", "VRPMS_CACHE"):
            os.environ.pop(var, None)
        srv.shutdown()

    single, batched = out["single"], out["batched"]
    ratio = (
        batched["jobsPerSec"] / single["jobsPerSec"]
        if single["jobsPerSec"] else float("inf")
    )
    out["speedup"] = round(ratio, 2)
    out["gate"] = {
        "threshold": 1.5,
        "pass": (
            ratio >= 1.5
            and single["failures"] == 0
            and batched["failures"] == 0
            and single["correctness"]["valid"]
            and batched["correctness"]["valid"]
        ),
    }
    print(f"batched-claims gate (>=1.5x jobs/sec at equal correctness): "
          f"{out['speedup']}x {'PASS' if out['gate']['pass'] else 'FAIL'}")

    import jax

    record = {
        "benchmark": "batched_claims",
        "backend": jax.default_backend(),
        "note": args.note,
        "config": {
            "clients": args.clients,
            "duration": args.duration,
            "n": args.n,
            "iterationCount": args.iters,
            "populationSize": args.pop,
            "maxBatch": args.max_batch,
            "queueRttMs": args.rtt_ms,
            "replicas": 2,
        },
        "throughput": out,
    }
    if args.out:
        path = args.out if os.path.isabs(args.out) else os.path.join(
            os.path.dirname(__file__), args.out
        )
        with open(path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(f"record -> {path}")


if __name__ == "__main__":
    main()
