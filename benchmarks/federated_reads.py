"""Federated live-progress reads: visibility, watcher scale, overhead.

    JAX_PLATFORMS=cpu python -m benchmarks.federated_reads \
        [--n 14] [--iters 6000] [--chains 16] [--watchers 64] \
        [--out benchmarks/records/federated_reads_r20.json]

The federated-reads acceptance bar (ISSUE 16), five phases on one
in-process fleet (a real HTTP replica + the shared store queue — the
non-owner read paths are driven directly, since they are exactly the
code a second replica would run when `get_live_job` misses):

  1. **Checkpoint visibility** — while one replica solves a long job,
     a non-owning reader polls the checkpoint overlay
     (`_checkpoint_incumbent`, VRPMS_READ_TTL_MS=0 so every row lands).
     Gates: the observed incumbent stream is monotone non-increasing,
     every snapshot is marked `incumbentSource=checkpoint`, and each
     NEW incumbent is first seen within one checkpoint cadence of its
     write (`staleMs` at first sight <= cadence).
  2. **Owner relay** — the same solve watched through `_relay_snap`,
     with the heartbeat registry pointing at the owner's real HTTP
     address: snapshots ride the owner's live view, marked
     `incumbentSource=relay`, monotone.
  3. **Watcher scale** — `--watchers` status polls of one job inside
     one TTL window against a counting store: gate exactly ONE store
     read (vs one per poll with VRPMS_READ_TTL_MS=0), bodies
     byte-identical across both arms.
  4. **Store down** — the checkpoint store hard-fails; every federated
     status poll must still answer 200 with `degraded: true` (never a
     500, never invented state).
  5. **Overhead** — paired submit+SSE-wait rounds, federation
     (relay + read cache) on vs off, alternating: gate < 1% wall-clock
     overhead on the solve path.

Prints the record JSON on stdout; `--out` writes the committed record
the CI gate asserts; diagnostics to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import threading
import time
import urllib.error
import urllib.request

GATE_OVERHEAD_PCT = 1.0


def _post(base: str, path: str, body: dict) -> tuple[int, dict]:
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base: str, path: str) -> tuple[int, dict]:
    try:
        with urllib.request.urlopen(base + path, timeout=60) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _seed_store(n: int) -> None:
    import numpy as np

    import store.memory as mem

    mem.reset()
    rng = np.random.default_rng(53)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        "fedbench",
        [{"id": i, "demand": 2 if i else 0} for i in range(n)],
    )
    mem.seed_durations("fedbench", d.tolist())


def _body(n: int, iters: int, chains: int, seed: int) -> dict:
    return {
        "solutionName": "fed-bench",
        "solutionDescription": "federated_reads",
        "locationsKey": "fedbench",
        "durationsKey": "fedbench",
        "capacities": [3 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": iters,
        "populationSize": chains,
        "problem": "vrp",
        "algorithm": "sa",
        "timeLimit": 300.0,
    }


def _submit(base, n, iters, chains, seed) -> str:
    status, resp = _post(base, "/api/jobs", _body(n, iters, chains, seed))
    assert status == 202, resp
    return resp["jobId"]


def _wait_done(base, jid, timeout_s=300.0) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        _, poll = _get(base, f"/api/jobs/{jid}")
        st = poll["job"]["status"]
        if st in ("done", "failed"):
            assert st == "done", poll
            return
        time.sleep(0.01)
    raise AssertionError(f"job {jid} never finished")


def _watch(jobs_mod, base, jid, take_snap, poll_s=0.015):
    """Poll `take_snap` on the reader side until the job turns
    terminal; return the distinct snapshots in arrival order, each
    tagged with its first-sight staleMs."""
    snaps, last_key = [], None
    while True:
        snap = take_snap()
        if snap is not None:
            key = (snap.get("bestCost"), snap.get("block"))
            if key != last_key:
                last_key = key
                snaps.append(dict(snap))
        _, poll = _get(base, f"/api/jobs/{jid}")
        if poll["job"]["status"] in ("done", "failed"):
            assert poll["job"]["status"] == "done", poll
            return snaps
        time.sleep(poll_s)


def _monotone(costs) -> bool:
    return all(b <= a + 1e-9 for a, b in zip(costs, costs[1:]))


class _CountingDB:
    """Delegates every store op, counting job/checkpoint reads."""

    def __init__(self, inner):
        self._inner = inner
        self.reads = 0

    def get_job(self, job_id, errors):
        self.reads += 1
        return self._inner.get_job(job_id, errors)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _CkptDownDB:
    """Job reads work; checkpoint reads are an outage."""

    def __init__(self, inner):
        self._inner = inner

    def get_checkpoint(self, job_id, errors=None):
        if errors is not None:
            errors += [{
                "what": "Database read error",
                "reason": "injected: checkpoint store down",
            }]
        return None

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _OwnerRegistryStub:
    """The reader replica's view of the heartbeat registry: one peer
    (the real HTTP server in this process) owns the job."""

    def __init__(self, owner: str, addr: str):
        self._owner = owner
        self._addr = addr
        self.store = self

    def owner_of(self, job_id):
        return self._owner

    def replica_infos(self):
        return {self._owner: {"addr": self._addr}}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=24)
    ap.add_argument("--iters", type=int, default=6000)
    ap.add_argument("--chains", type=int, default=16)
    ap.add_argument("--ckpt-ms", type=float, default=250.0)
    ap.add_argument("--watchers", type=int, default=64)
    ap.add_argument("--down-reads", type=int, default=20)
    ap.add_argument("--trace-jobs", type=int, default=3)
    ap.add_argument("--trace-rounds", type=int, default=3)
    ap.add_argument("--trace-iters", type=int, default=3000)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    os.environ["VRPMS_LOG"] = "off"
    os.environ["VRPMS_STORE"] = "memory"
    os.environ["VRPMS_QUEUE"] = "store"  # federation is a fleet feature
    os.environ["VRPMS_CACHE"] = "off"  # same-seed pairs must re-solve
    os.environ["VRPMS_CKPT_MS"] = str(args.ckpt_ms)
    os.environ["VRPMS_READ_TTL_MS"] = "0"  # the reader sees every row
    os.environ["VRPMS_REPLICA_ID"] = "fed-bench-owner"

    import store
    from service import jobs as jobs_mod
    from service.app import serve

    _seed_store(args.n)
    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    jobs_mod.get_replica()  # start the claim loop (app.main does this)
    try:
        print(f"[federated_reads] warmup solve on {base}", file=sys.stderr)
        _wait_done(base, _submit(base, args.n, 800, args.chains, seed=1))

        # -- phase 1: checkpoint visibility ------------------------------
        jid = _submit(base, args.n, args.iters, args.chains, seed=2)
        ckpt_snaps = _watch(
            jobs_mod, base, jid,
            lambda: jobs_mod._checkpoint_incumbent(jid)[0],
        )
        ckpt_costs = [s["bestCost"] for s in ckpt_snaps]
        first_sight = [
            s["staleMs"] for s in ckpt_snaps if s["staleMs"] is not None
        ]
        ckpt_marked = all(
            s.get("incumbentSource") == "checkpoint" for s in ckpt_snaps
        )
        worst_lag = max(first_sight) if first_sight else None
        print(
            f"[federated_reads] checkpoint arm: {len(ckpt_snaps)} snaps, "
            f"worst first-sight lag {worst_lag} ms "
            f"(cadence {args.ckpt_ms:.0f} ms)",
            file=sys.stderr,
        )

        # -- phase 2: owner relay ----------------------------------------
        jid2 = _submit(base, args.n, args.iters, args.chains, seed=3)
        real_replica = jobs_mod._replica
        jobs_mod._replica = _OwnerRegistryStub(
            "fed-bench-peer", base.removeprefix("http://")
        )
        try:
            relay_snaps = _watch(
                jobs_mod, base, jid2, lambda: jobs_mod._relay_snap(jid2)
            )
        finally:
            jobs_mod._replica = real_replica
        relay_costs = [s["bestCost"] for s in relay_snaps]
        relay_marked = all(
            s.get("incumbentSource") == "relay" for s in relay_snaps
        )
        print(
            f"[federated_reads] relay arm: {len(relay_snaps)} snaps",
            file=sys.stderr,
        )

        # -- phase 3: watcher scale --------------------------------------
        # jid is terminal now — the record read is the whole poll cost
        real_get_database = store.get_database
        db = _CountingDB(real_get_database("vrp", None))
        store.get_database = lambda *a, **kw: db
        try:
            os.environ["VRPMS_READ_TTL_MS"] = "60000"
            cached_bodies = [
                _get(base, f"/api/jobs/{jid}") for _ in range(args.watchers)
            ]
            reads_cached = db.reads
            jobs_mod.shutdown_scheduler()  # clears the read cache
            db.reads = 0
            os.environ["VRPMS_READ_TTL_MS"] = "0"
            through_bodies = [
                _get(base, f"/api/jobs/{jid}") for _ in range(args.watchers)
            ]
            reads_through = db.reads
        finally:
            store.get_database = real_get_database
            os.environ["VRPMS_READ_TTL_MS"] = "0"
        # per-request envelope fields (requestId) legitimately vary;
        # the JOB payload is what the cache must not change
        bodies_identical = json.dumps(
            [(c, b.get("job")) for c, b in cached_bodies], sort_keys=True
        ) == json.dumps(
            [(c, b.get("job")) for c, b in through_bodies], sort_keys=True
        )
        print(
            f"[federated_reads] watcher scale: {args.watchers} polls -> "
            f"{reads_cached} store read(s) cached, "
            f"{reads_through} read-through",
            file=sys.stderr,
        )

        # -- phase 4: store down -----------------------------------------
        running_jid = "fed-bench-running"
        real_get_database("vrp", None).save_job(running_jid, {
            "jobId": running_jid, "status": "running",
            "problem": "vrp", "algorithm": "sa",
            "submittedAt": time.time(),
        })
        store.get_database = lambda *a, **kw: _CkptDownDB(
            real_get_database("vrp", None)
        )
        try:
            down = [
                _get(base, f"/api/jobs/{running_jid}")
                for _ in range(args.down_reads)
            ]
        finally:
            store.get_database = real_get_database
        served = sum(1 for code, _ in down if code == 200)
        degraded_marked = all(
            body.get("degraded") is True for _, body in down
        )
        served_frac = served / max(1, args.down_reads)
        print(
            f"[federated_reads] store down: {served}/{args.down_reads} "
            f"served 200 (degraded marked: {degraded_marked})",
            file=sys.stderr,
        )

        # -- phase 5: paired on/off overhead -----------------------------
        def one_round(seed0: int) -> float:
            """Solve-only wall seconds for one round: per job, the
            clock runs from the moment the claim lands (the job is
            LIVE) to the stream's terminal event — submit + claim
            latency is replica poll jitter, not the read path under
            test."""
            total = 0.0
            for i in range(args.trace_jobs):
                jid = _submit(
                    base, args.n, args.trace_iters, args.chains,
                    seed0 + i,
                )
                # wait (in-process, no HTTP reads that would differ
                # between arms) for the claim to land, so the stream
                # below attaches to the LIVE sink in both arms — the
                # non-owned follow path's poll cadence is a different
                # measurement
                db = real_get_database("vrp", None)
                while jobs_mod.get_live_job(jid) is None:
                    row = db.get_job(jid, [])
                    if row is not None and row.get("status") in (
                        "done", "failed",
                    ):
                        break
                    time.sleep(0.002)
                t0 = time.perf_counter()
                # SSE-wait: the stream closes at the terminal event, so
                # the wait adds no polling cadence of its own
                with urllib.request.urlopen(
                    f"{base}/api/jobs/{jid}/stream", timeout=600
                ) as resp:
                    resp.read()
                total += time.perf_counter() - t0
            return total

        arms = {
            "off": {"VRPMS_READ_RELAY": "off", "VRPMS_READ_TTL_MS": "0"},
            "on": {"VRPMS_READ_RELAY": "on", "VRPMS_READ_TTL_MS": "250"},
        }
        one_round(50)  # warm both arms' programs
        on_s, off_s = [], []
        for rnd in range(args.trace_rounds):
            seed0 = 100 + 10 * rnd
            order = ("off", "on") if rnd % 2 == 0 else ("on", "off")
            for arm in order:
                os.environ.update(arms[arm])
                t = one_round(seed0)
                (on_s if arm == "on" else off_s).append(t)
        t_on, t_off = sum(on_s), sum(off_s)
        # median of per-round paired deltas: one descheduled round must
        # not swamp the measurement (the trace_export convention)
        overhead_pct = 100.0 * statistics.median(
            (on - off) / off for on, off in zip(on_s, off_s)
        )
        print(
            f"[federated_reads] overhead: on {t_on:.2f}s / off "
            f"{t_off:.2f}s = {overhead_pct:+.2f}%",
            file=sys.stderr,
        )
    finally:
        srv.shutdown()
        jobs_mod.shutdown_scheduler()

    import jax

    within_cadence = bool(
        first_sight and max(first_sight) <= args.ckpt_ms
    )
    gate = {
        "ckptSnaps": len(ckpt_snaps),
        "ckptMonotone": _monotone(ckpt_costs),
        "ckptMarked": ckpt_marked,
        "firstSightWorstMs": worst_lag,
        "cadenceMs": args.ckpt_ms,
        "withinOneCadence": within_cadence,
        "relaySnaps": len(relay_snaps),
        "relayMonotone": _monotone(relay_costs),
        "relayMarked": relay_marked,
        "watchers": args.watchers,
        "readsCached": reads_cached,
        "readsThrough": reads_through,
        "watcherBodiesIdentical": bodies_identical,
        "storeDownServed": served_frac,
        "storeDownDegradedMarked": degraded_marked,
        "overheadPct": round(overhead_pct, 3),
        "overheadMax": GATE_OVERHEAD_PCT,
        "pass": bool(
            len(ckpt_snaps) >= 2
            and _monotone(ckpt_costs)
            and ckpt_marked
            and within_cadence
            and len(relay_snaps) >= 1
            and _monotone(relay_costs)
            and relay_marked
            and reads_cached == 1
            and reads_through == args.watchers
            and bodies_identical
            and served_frac == 1.0
            and degraded_marked
            and overhead_pct < GATE_OVERHEAD_PCT
        ),
    }
    record = {
        "bench": "federated_reads",
        "config": {
            "n": args.n,
            "iters": args.iters,
            "chains": args.chains,
            "ckptMs": args.ckpt_ms,
            "watchers": args.watchers,
            "downReads": args.down_reads,
            "traceJobs": args.trace_jobs,
            "traceRounds": args.trace_rounds,
            "traceIters": args.trace_iters,
            "backend": jax.default_backend(),
        },
        "checkpointArm": {
            "snaps": len(ckpt_snaps),
            "costs": [round(c, 3) for c in ckpt_costs],
            "firstSightMs": first_sight,
        },
        "relayArm": {
            "snaps": len(relay_snaps),
            "costs": [round(c, 3) for c in relay_costs],
        },
        "overhead": {
            "onS": round(t_on, 3),
            "offS": round(t_off, 3),
            "overheadPct": round(overhead_pct, 3),
        },
        "gate": gate,
    }
    out = json.dumps(record, indent=2)
    print(out)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0 if gate["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
