"""Giant-instance decomposition benchmark (ISSUE 13).

Two claims, one record (records/decompose_r17.json):

  1. **End-to-end above the ladder.** A clustered CVRP with n >= 5000
     customers — far beyond the tier ladder's top (n=1024), where the
     monolithic path has no canonical shape to pad to and is not
     attempted — solves through the full service path (run_vrp ->
     decompose -> batched shard solves -> stitch) to a bounded gap vs
     the shard-sum lower bound, with every customer served exactly once
     and capacities respected.
  2. **Batched shard dispatch.** The K same-tier shards dispatch as
     ceil(K / max_batch) vmapped launches; on this overhead-bound trace
     (small per-shard budgets, fixed per-launch costs dominating) the
     batched dispatch beats a forced shard-by-shard loop by >= 1.3x
     wall-clock at equal solver budget. Timed WARM (both program shapes
     compiled first) so the comparison is dispatch economics, not
     compile luck.

Run: JAX_PLATFORMS=cpu python -m benchmarks.decompose \
        --record benchmarks/records/decompose_r17.json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import statistics
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

GAP_MAX = 1.5        # durationSum <= (1 + GAP_MAX) * shard-sum LB
SPEEDUP_MIN = 1.3    # batched vs forced-solo wall clock


def build_instance(n_nodes: int, n_vehicles: int, seed: int):
    from vrpms_tpu.io.synth import synth_clustered_coords

    coords, demands = synth_clustered_coords(
        n_nodes, max(8, n_nodes // 125), seed=seed
    )
    d = np.linalg.norm(
        coords[:, None] - coords[None, :], axis=-1
    ).astype(np.float64)
    cap = float(np.ceil(demands.sum() * 1.25 / n_vehicles))
    locations = [
        {"id": i, "demand": float(demands[i])} for i in range(n_nodes)
    ]
    params = {
        "name": "decompose-bench",
        "capacities": [cap] * n_vehicles,
        "start_times": [0.0] * n_vehicles,
        "ignored_customers": [],
        "completed_customers": [],
    }
    return locations, d, params, demands


def end_to_end(locations, d, params, opts):
    from service.solve import run_vrp

    errors: list = []
    t0 = time.perf_counter()
    res = run_vrp("sa", params, dict(opts), {}, locations, d, errors)
    wall = time.perf_counter() - t0
    assert res is not None, errors
    served = sorted(c for v in res["vehicles"] for c in v["tour"][1:-1])
    valid = served == list(range(1, len(locations)))
    feasible = all(
        v["load"] <= v["capacity"] + 1e-6 for v in res["vehicles"]
    )
    dec = res["decomposition"]
    gap = (res["durationSum"] - dec["lowerBound"]) / dec["lowerBound"]
    return {
        "wallSeconds": round(wall, 2),
        "durationSum": res["durationSum"],
        "lowerBound": dec["lowerBound"],
        "gap": round(gap, 4),
        "shards": dec["shards"],
        "launches": dec["launches"],
        "maxBatch": dec["maxBatch"],
        "tier": dec["tier"],
        "boundary": dec["boundary"],
        "reoptimized": dec["reoptimized"],
        "rebalanced": dec["rebalanced"],
        "allServedOnce": valid,
        "capacityFeasible": feasible,
    }


def dispatch_trial(plan, params_sa, weights, seed, max_batch):
    """One warm solve_shards pass; returns (wall, launches, cost sum)."""
    from vrpms_tpu.core import decompose

    insts = decompose.shard_instances(plan)
    seeds = [seed + i for i in range(len(insts))]
    t0 = time.perf_counter()
    results, launches = decompose.solve_shards(
        insts, seeds, params_sa, weights=weights, max_batch=max_batch
    )
    wall = time.perf_counter() - t0
    cost = float(sum(float(r.cost) for r in results))
    return wall, launches, cost


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=5001,
                    help="node count incl. depot (default 5001)")
    ap.add_argument("--vehicles", type=int, default=96)
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--iters", type=int, default=256,
                    help="per-shard SA iterations of the END-TO-END run")
    ap.add_argument("--chains", type=int, default=16)
    ap.add_argument("--dispatch-iters", type=int, default=64,
                    help="per-shard iterations of the timed dispatch "
                    "trials (small on purpose: the overhead-bound "
                    "regime where per-launch fixed costs dominate)")
    ap.add_argument("--dispatch-chains", type=int, default=4)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--tier", type=int, default=128,
                    help="shard node tier (VRPMS_DECOMP_TIER)")
    ap.add_argument("--record", type=str, default=None)
    ap.add_argument("--note", type=str, default=None)
    args = ap.parse_args()

    os.environ["VRPMS_DECOMP"] = "auto"
    os.environ["VRPMS_DECOMP_TIER"] = str(args.tier)

    import jax

    from vrpms_tpu.core import decompose
    from vrpms_tpu.core.cost import CostWeights
    from vrpms_tpu.solvers import SAParams

    locations, d, params, demands = build_instance(
        args.n, args.vehicles, args.seed
    )
    opts = {
        "seed": args.seed,
        "iteration_count": args.iters,
        "population_size": args.chains,
    }

    print(f"[1/3] end-to-end run_vrp: n={args.n - 1} customers, "
          f"{args.vehicles} vehicles, tier {args.tier}", flush=True)
    e2e = end_to_end(locations, d, params, opts)
    print(json.dumps(e2e, indent=2), flush=True)

    print("[2/3] dispatch trials (warmup + timed)", flush=True)
    plan = decompose.build_plan(
        d, [loc["demand"] for loc in locations],
        [0.0] * len(locations), params["capacities"],
        params["start_times"], seed=args.seed,
    )
    w = CostWeights.make()
    params_sa = SAParams(
        n_chains=args.dispatch_chains, n_iters=args.dispatch_iters
    )
    k = plan.n_shards
    # warm both program families (batched chunk shapes + solo) so the
    # timed comparison is dispatch economics, not compile order
    dispatch_trial(plan, params_sa, w, args.seed, 16)
    dispatch_trial(plan, params_sa, w, args.seed, 1)

    print("[3/3] timed batched vs forced-solo "
          f"(median of {args.trials})", flush=True)
    b_walls, s_walls = [], []
    for _ in range(args.trials):
        wall, b_launches, b_cost = dispatch_trial(
            plan, params_sa, w, args.seed, 16
        )
        b_walls.append(wall)
        wall, s_launches, s_cost = dispatch_trial(
            plan, params_sa, w, args.seed, 1
        )
        s_walls.append(wall)
    b_wall = statistics.median(b_walls)
    s_wall = statistics.median(s_walls)
    speedup = s_wall / b_wall if b_wall > 0 else float("inf")

    gate = {
        "pass": bool(
            e2e["allServedOnce"]
            and e2e["capacityFeasible"]
            and e2e["gap"] <= GAP_MAX
            and e2e["launches"] == math.ceil(e2e["shards"] / e2e["maxBatch"])
            and b_launches == math.ceil(k / 16)
            and speedup >= SPEEDUP_MIN
        ),
        "gap": e2e["gap"],
        "gapMax": GAP_MAX,
        "launches": b_launches,
        "launchesMax": math.ceil(k / 16),
        "speedup": round(speedup, 2),
        "speedupMin": SPEEDUP_MIN,
    }
    record = {
        "benchmark": "decompose",
        "backend": jax.default_backend(),
        "note": args.note,
        "config": {
            "n": args.n,
            "vehicles": args.vehicles,
            "seed": args.seed,
            "iterationCount": args.iters,
            "populationSize": args.chains,
            "dispatchIters": args.dispatch_iters,
            "dispatchChains": args.dispatch_chains,
            "trials": args.trials,
            "shardTier": args.tier,
            "ladderTop": decompose.ceiling(),
        },
        "monolithic": {
            "attempted": False,
            "reason": (
                "above the tier ladder top (n=1024): no canonical tier "
                "to pad to, the TD delta kernel gates at n<=512, and a "
                "one-off n=5001 SA program would compile multi-GB state "
                "no other request shares — the exact ceiling the "
                "decomposition converts into a throughput knob"
            ),
        },
        "endToEnd": e2e,
        "dispatch": {
            "shards": k,
            "batched": {
                "wallSeconds": round(b_wall, 3),
                "walls": [round(x, 3) for x in b_walls],
                "launches": b_launches,
                "costSum": round(b_cost, 1),
            },
            "solo": {
                "wallSeconds": round(s_wall, 3),
                "walls": [round(x, 3) for x in s_walls],
                "launches": s_launches,
                "costSum": round(s_cost, 1),
            },
            "speedup": round(speedup, 2),
        },
        "gate": gate,
    }
    print(json.dumps(record["dispatch"], indent=2))
    print("gate:", json.dumps(gate))
    if args.record:
        with open(args.record, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(f"record written to {args.record}")


if __name__ == "__main__":
    main()
