"""Closed-loop scheduler throughput benchmark: sync-inline vs batched.

Starts the service in-process and drives it with N concurrent
closed-loop clients (each posts a solve, waits for the response, posts
the next) for a fixed measurement window, then reports solves/sec and
p50/p99 latency for two serving modes over the SAME request stream:

  inline — VRPMS_SCHED=off: every HTTP thread solves on its own
           (the PR-1 behavior), N threads contending for the device;
  sched  — the scheduler path: one device-owning worker drains the
           admission queue, merging same-shape requests into one
           vmapped launch (vrpms_tpu.sched.batch).

The ISSUE-2 acceptance gate: `sched` >= 2x `inline` solves/sec at >= 8
concurrent same-shape clients (CPU backend acceptable). `--mixed` adds
a second instance shape to show bucketing keeps mixed traffic correct.

    JAX_PLATFORMS=cpu python -m benchmarks.sched_throughput \
        [--clients 8] [--duration 10] [--warmup 4] [--n 12] \
        [--iters 2000] [--pop 64] [--mixed] [--out records/...json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import threading
import time
import urllib.error
import urllib.request


def _post(base: str, path: str, body: dict) -> tuple[int, dict]:
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:  # pragma: no cover - error path
        return e.code, json.loads(e.read())


def _seed_store(shapes: list[int]) -> None:
    import numpy as np

    import store.memory as mem

    mem.reset()
    rng = np.random.default_rng(17)
    for n in shapes:
        pts = rng.uniform(0, 100, size=(n, 2))
        d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
        mem.seed_locations(
            f"bench{n}", [{"id": i, "demand": 2 if i else 0} for i in range(n)]
        )
        mem.seed_durations(f"bench{n}", d.tolist())


def _body(problem: str, n: int, iters: int, pop: int, seed: int) -> dict:
    body = {
        "solutionName": f"bench-{n}",
        "solutionDescription": "sched_throughput",
        "locationsKey": f"bench{n}",
        "durationsKey": f"bench{n}",
        "seed": seed,
        "iterationCount": iters,
        "populationSize": pop,
    }
    if problem == "vrp":
        body.update(
            capacities=[3 * n] * 3,
            startTimes=[0, 0, 0],
            ignoredCustomers=[],
            completedCustomers=[],
        )
    else:
        body.update(customers=list(range(1, n)), startNode=0, startTime=0)
    return body


def run_phase(
    base: str,
    problem: str,
    shapes: list[int],
    clients: int,
    duration_s: float,
    warmup_s: float,
    iters: int,
    pop: int,
) -> dict:
    """Closed-loop drive: `clients` threads, each cycling its shape.

    The warmup window runs the identical loop but discards samples, so
    jit compiles (including the batched program's padded batch shapes)
    never pollute the measurement.
    """
    stop = threading.Event()
    measuring = threading.Event()
    latencies: list[float] = []
    failures: list[int] = []
    lock = threading.Lock()

    path = f"/api/{problem}/sa"

    def client(i: int) -> None:
        n = shapes[i % len(shapes)]
        seed = 0
        while not stop.is_set():
            seed += 1
            t0 = time.perf_counter()
            status, resp = _post(base, path, _body(problem, n, iters, pop, seed))
            dt = time.perf_counter() - t0
            if not measuring.is_set():
                continue
            with lock:
                if status == 200:
                    latencies.append(dt)
                else:
                    failures.append(status)

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    time.sleep(warmup_s)
    measuring.set()
    t_meas = time.perf_counter()
    time.sleep(duration_s)
    measured_s = time.perf_counter() - t_meas
    stop.set()
    for t in threads:
        t.join(timeout=600)
    lat_ms = sorted(1e3 * x for x in latencies)

    def pct(p: float) -> float | None:
        if not lat_ms:
            return None
        k = min(len(lat_ms) - 1, int(round(p / 100 * (len(lat_ms) - 1))))
        return round(lat_ms[k], 1)

    return {
        "solves": len(lat_ms),
        "solvesPerSec": round(len(lat_ms) / measured_s, 2),
        "p50Ms": pct(50),
        "p99Ms": pct(99),
        "meanMs": round(statistics.mean(lat_ms), 1) if lat_ms else None,
        "failures": len(failures),
        "measuredSeconds": round(measured_s, 2),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--problem", choices=("vrp", "tsp"), default="vrp")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--warmup", type=float, default=4.0)
    ap.add_argument("--n", type=int, default=12, help="locations per instance")
    ap.add_argument("--iters", type=int, default=2000)
    ap.add_argument("--pop", type=int, default=64)
    ap.add_argument("--mixed", action="store_true",
                    help="second shape (n+4) on half the clients")
    ap.add_argument("--out", default=None, help="record JSON path")
    ap.add_argument("--note", default=None, help="free-text note in record")
    args = ap.parse_args()

    os.environ["VRPMS_STORE"] = "memory"
    shapes = [args.n, args.n + 4] if args.mixed else [args.n]
    _seed_store(shapes)

    from service import jobs as jobs_mod
    from service.app import serve

    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    import jax

    record = {
        "benchmark": "sched_throughput",
        "backend": jax.default_backend(),
        "problem": args.problem,
        "clients": args.clients,
        "shapes": shapes,
        "iterationCount": args.iters,
        "populationSize": args.pop,
        "durationSeconds": args.duration,
        "note": args.note,
        "schedConfig": {
            "queue": int(os.environ.get("VRPMS_SCHED_QUEUE", "64")),
            "windowMs": float(os.environ.get("VRPMS_SCHED_WINDOW_MS", "10")),
            "maxBatch": int(os.environ.get("VRPMS_SCHED_MAX_BATCH", "16")),
        },
    }
    for mode in ("inline", "sched"):
        os.environ["VRPMS_SCHED"] = "off" if mode == "inline" else "on"
        print(f"== {mode}: {args.clients} clients, "
              f"{args.duration:.0f}s measure ({args.warmup:.0f}s warmup)")
        record[mode] = run_phase(
            base, args.problem, shapes, args.clients, args.duration,
            args.warmup, args.iters, args.pop,
        )
        print(json.dumps(record[mode], indent=2))
        jobs_mod.shutdown_scheduler()  # fresh scheduler per phase

    if record["inline"]["solvesPerSec"]:
        record["speedup"] = round(
            record["sched"]["solvesPerSec"]
            / record["inline"]["solvesPerSec"], 2,
        )
        print(f"speedup (sched/inline solves/sec): {record['speedup']}x")

    srv.shutdown()
    if args.out:
        out = os.path.join(os.path.dirname(__file__), args.out) if not (
            os.path.isabs(args.out)
        ) else args.out
        with open(out, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(f"record -> {out}")


if __name__ == "__main__":
    main()
