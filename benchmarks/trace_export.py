"""Durable trace export: overhead gate + store-down serving phase.

    python -m benchmarks.trace_export [--reps 8] [--iters 800]
                                      [--customers 60] [--chains 64]
                                      [--rtt-ms 25]
                                      [--out benchmarks/records/...json]

The fleet-observability acceptance bar (ISSUE 14), three phases:

  1. **Overhead** — the PR-5/PR-1 paired design on the REAL request
     path (service.solve.run_vrp bracketed by the exact per-request
     trace lifecycle the HTTP layer runs), alternating
     VRPMS_TRACE_EXPORT on/off each rep. The export store sits behind
     an RTT shim (default 25 ms per batch write — the hosted store's
     real per-op cost) so the measurement includes a realistically
     SLOW trace store; the exporter is a bounded background flusher,
     so solves/sec must not care: gate < 1% overhead.
  2. **Steady state** — after the on-arm drains, every offered span
     must be accounted `ok`: gate zero dropped.
  3. **Store down** — the trace store hard-fails; the same request mix
     must serve 100% (export failures only tick the `failed` counter)
     and the local debug ring must still hold the traces: gate 100%
     served, local trace present.

Prints one JSON line on stdout (bench.py convention); diagnostics to
stderr; `--out` also writes the committed record the CI gate asserts.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time


def build_request(n_customers: int, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    n = n_customers + 1
    pts = rng.uniform(0, 100, size=(n, 2))
    matrix = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1).tolist()
    locations = [
        {"id": i, "demand": 2 if i else 0} for i in range(n)
    ]
    n_vehicles = max(2, n_customers // 10)
    cap = 2.0 * n_customers / n_vehicles * 1.3
    params = {
        "name": "trace-export",
        "description": "bench",
        "auth": None,
        "ignored_customers": [],
        "completed_customers": [],
        "capacities": [cap] * n_vehicles,
        "start_times": [0.0] * n_vehicles,
    }
    return params, locations, matrix


class RttShim:
    """The hosted store's per-op latency, applied to the export write
    path only — the background flusher pays it, requests must not."""

    def __init__(self, inner, rtt_s: float):
        self.inner = inner
        self.rtt_s = rtt_s
        self.writes = 0

    def put_trace_spans(self, rows):
        time.sleep(self.rtt_s)
        self.writes += 1
        return self.inner.put_trace_spans(rows)


class DownStore:
    """A hard-down trace store: every batch write fails."""

    def put_trace_spans(self, rows):
        raise RuntimeError("injected: trace store down")


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--reps", type=int, default=8,
                        help="measured solve pairs (one per export state)")
    parser.add_argument("--iters", type=int, default=800)
    parser.add_argument("--customers", type=int, default=60)
    parser.add_argument("--chains", type=int, default=64)
    parser.add_argument("--rtt-ms", type=float, default=25.0,
                        help="simulated store RTT per export batch write")
    parser.add_argument("--down-requests", type=int, default=6,
                        help="requests served during the store-down phase")
    parser.add_argument("--out", default=None,
                        help="also write the committed record here")
    args = parser.parse_args()

    os.environ["VRPMS_LOG"] = "off"  # isolate the export delta
    os.environ["VRPMS_STORE"] = "memory"
    os.environ["VRPMS_TRACING"] = "on"
    os.environ["VRPMS_TRACE_EXPORT"] = "off"
    import store
    from service import obs as service_obs
    from service.solve import run_vrp
    from vrpms_tpu.obs import export, spans

    def count(outcome: str) -> float:
        return service_obs.TRACE_EXPORT.labels(outcome=outcome).value

    params, locations, matrix = build_request(args.customers)
    opts = {
        "seed": 1,
        "iteration_count": args.iters,
        "population_size": args.chains,
    }

    def one_solve(seed: int) -> float:
        """One request-shaped solve under the current export state: the
        exact per-request span lifecycle the service runs (the PR-5
        trace_overhead harness)."""
        errors: list = []
        t0 = time.perf_counter()
        trace = spans.start_trace(None)
        tokens = None
        if trace is not None:
            root = trace.span("POST /api/vrp/sa")
            tokens = spans.activate(trace, root)
        try:
            result = run_vrp(
                "sa", params, dict(opts, seed=seed), {}, locations, matrix,
                errors, database=None,
            )
        finally:
            if trace is not None:
                trace.root().end()
                spans.deactivate(tokens)
                trace.finish()
        elapsed = (time.perf_counter() - t0) * 1e3
        assert result is not None and not errors, errors
        return elapsed

    shim = RttShim(store.get_database("vrp", None), args.rtt_ms / 1e3)
    export.set_store_factory(lambda: shim)

    print(
        f"[trace_export] warmup solve ({args.customers} customers, "
        f"{args.chains}x{args.iters})",
        file=sys.stderr,
    )
    one_solve(0)  # compile

    # -- phase 1: paired on/off overhead ------------------------------------
    on_ms, off_ms = [], []
    offered_spans = 0
    for rep in range(args.reps):
        pair = (("on", on_ms), ("off", off_ms))
        if rep % 2:
            pair = pair[::-1]
        for state, sink in pair:
            os.environ["VRPMS_TRACE_EXPORT"] = state
            sink.append(one_solve(rep + 1))
    os.environ["VRPMS_TRACE_EXPORT"] = "on"
    assert export.flush(30.0), "exporter failed to drain"
    overhead_pct = 100.0 * statistics.median(
        (on - off) / off for on, off in zip(on_ms, off_ms)
    )

    # -- phase 2: steady-state accounting -----------------------------------
    ok, dropped, failed = count("ok"), count("dropped"), count("failed")
    offered_spans = ok + dropped + failed
    print(
        f"[trace_export] steady state: ok={ok:.0f} dropped={dropped:.0f} "
        f"failed={failed:.0f} batchWrites={shim.writes}",
        file=sys.stderr,
    )

    # -- phase 3: store down --------------------------------------------------
    export.set_store_factory(lambda: DownStore())
    served = 0
    last_tid = None
    for i in range(args.down_requests):
        errors: list = []
        trace = spans.start_trace(None)
        root = trace.span("POST /api/vrp/sa")
        tokens = spans.activate(trace, root)
        try:
            result = run_vrp(
                "sa", params, dict(opts, seed=100 + i), {}, locations,
                matrix, errors, database=None,
            )
        finally:
            trace.root().end()
            spans.deactivate(tokens)
            trace.finish()
        if result is not None and not errors:
            served += 1
        last_tid = trace.trace_id
    export.flush(30.0)
    down_failed = count("failed") - failed
    local_trace_ok = spans.ring_get(last_tid) is not None
    export.set_store_factory(None)
    export.reset_exporter()

    served_frac = served / max(1, args.down_requests)
    gate = {
        "overheadPct": round(overhead_pct, 3),
        "overheadMax": 1.0,
        "droppedSteadyState": int(dropped),
        "offeredSpans": int(offered_spans),
        "okSpans": int(ok),
        "storeDownServed": served_frac,
        "storeDownFailedSpans": int(down_failed),
        "localTraceServedWhileDown": bool(local_trace_ok),
        "pass": (
            overhead_pct < 1.0
            and dropped == 0
            and failed == 0
            and ok > 0
            and served_frac == 1.0
            and down_failed > 0
            and local_trace_ok
        ),
    }
    line = {
        "bench": "trace_export",
        "customers": args.customers,
        "chains": args.chains,
        "iters": args.iters,
        "reps": args.reps,
        "rttMs": args.rtt_ms,
        "solve_ms_export_on": round(statistics.median(on_ms), 2),
        "solve_ms_export_off": round(statistics.median(off_ms), 2),
        "batchWrites": shim.writes,
        "gate": gate,
        "pass": gate["pass"],
    }
    print(json.dumps(line))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(line, f, indent=2)
            f.write("\n")
    return 0 if line["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
