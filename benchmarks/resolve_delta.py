"""Dynamic re-solve benchmark: a rolling-horizon trace, warm vs cold.

Real dispatch re-solves a rolling horizon: every step a customer
completes (drop) and a new one arrives (add), and the fleet wants the
updated plan NOW. This bench replays such a trace against the
in-process service and measures what the warm-start continuation path
(ISSUE 8) buys over solving each horizon cold:

  * per step, the COLD baseline solves the post-delta instance from
    scratch at the full iteration budget I (fixed seed);
  * the WARM path sends the SAME instance as the PREVIOUS horizon's
    request body plus a `delta` (drop/add) and a `warmStart` inline
    tour carrying the previous horizon's solution — the service
    repairs the tour over the separator encoding and SA continues
    annealing from the repaired incumbent at a continuation
    temperature (solvers.sa.continuation_params);
  * the warm path then re-runs at shrinking budgets (I, I/2, ... I/16)
    to find the smallest budget whose cost still MATCHES the cold
    result — evals-to-match is the headline: how much of the budget
    the continuation actually needs.

Cache OFF throughout (VRPMS_CACHE=off): the point is the continuation
machinery itself, and the warm path must work without the cache (the
jobId/tour seed sources do not ride it).

Gates (ISSUE 8 acceptance):
  * every step's warm re-solve matches the cold cost with >= 2x fewer
    evals (evalsColdFull / evalsWarmAtMatch >= 2, min over steps);
  * at the FULL budget the warm cost is never worse than cold.

    JAX_PLATFORMS=cpu python -m benchmarks.resolve_delta \
        [--n 14] [--steps 4] [--iters 600] [--chains 16] \
        [--out records/resolve_delta_r13.json]
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
import urllib.error
import urllib.request

GATE_EVALS_RATIO = 2.0
REL_EPS = 1e-6


def _post(base: str, path: str, body: dict) -> tuple[int, dict]:
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _seed_store(n: int) -> None:
    import numpy as np

    import store.memory as mem

    mem.reset()
    rng = np.random.default_rng(43)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        "resolvebench",
        [{"id": i, "demand": 2 if i else 0} for i in range(n)],
    )
    mem.seed_durations("resolvebench", d.tolist())


def _body(n: int, iters: int, chains: int, seed: int, ignored: list) -> dict:
    return {
        "solutionName": "resolve-bench",
        "solutionDescription": "resolve_delta",
        "locationsKey": "resolvebench",
        "durationsKey": "resolvebench",
        "capacities": [3 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": list(ignored),
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": iters,
        "populationSize": chains,
        "includeStats": True,
    }


def _solve(base, body):
    t0 = time.perf_counter()
    status, resp = _post(base, "/api/vrp/sa", body)
    wall_ms = 1e3 * (time.perf_counter() - t0)
    assert status == 200, resp
    msg = resp["message"]
    return {
        "cost": float(msg["durationSum"]),
        "evals": int(msg["stats"]["evals"]),
        "wallMs": round(wall_ms, 1),
        "routes": [v["tour"][1:-1] for v in msg["vehicles"]],
        "stats": msg["stats"],
    }


def run_trace(base, n, steps, iters, chains, horizon) -> list[dict]:
    """The rolling horizon: start with the last `horizon` customers
    ignored (not yet arrived); each step completes the lowest active
    customer and admits the next arrival. Returns one record per
    re-solve step."""
    customers = list(range(1, n))
    ignored = customers[-horizon:]
    active = [c for c in customers if c not in ignored]
    # horizon 0: the plan in hand before the first re-solve
    carried = _solve(base, _body(n, iters, chains, 1, ignored))
    results = []
    budgets = []
    b = iters
    while b >= max(1, iters // 16):
        budgets.append(b)
        b //= 2
    for step in range(1, steps + 1):
        drop = active[0]
        add = ignored[0]
        prev_ignored = list(ignored)
        ignored = [c for c in ignored if c != add] + [drop]
        active = [c for c in active if c != drop] + [add]
        seed = 1 + step
        delta = {"drop": [drop], "add": [add]}
        # COLD: the post-delta instance, spelled directly, full budget
        cold = _solve(base, _body(n, iters, chains, seed, ignored))
        # WARM: previous horizon's body + delta + carried tour
        warm_runs = {}
        for budget in budgets:
            body = _body(n, budget, chains, seed, prev_ignored)
            body["delta"] = delta
            body["warmStart"] = {"tour": carried["routes"]}
            warm_runs[budget] = _solve(base, body)
        full = warm_runs[iters]
        match_budget = None
        for budget in sorted(budgets):
            if warm_runs[budget]["cost"] <= cold["cost"] * (1 + REL_EPS):
                match_budget = budget
                break
        rec = {
            "step": step,
            "drop": drop,
            "add": add,
            "coldCost": cold["cost"],
            "coldEvals": cold["evals"],
            "coldWallMs": cold["wallMs"],
            "warmFullCost": full["cost"],
            "warmFullEvals": full["evals"],
            "neverWorse": full["cost"] <= cold["cost"] * (1 + REL_EPS),
            "matchBudget": match_budget,
            "matchEvals": (
                None if match_budget is None
                else warm_runs[match_budget]["evals"]
            ),
            "matchWallMs": (
                None if match_budget is None
                else warm_runs[match_budget]["wallMs"]
            ),
            "evalsRatio": (
                None if match_budget is None
                else round(
                    cold["evals"]
                    / max(1, warm_runs[match_budget]["evals"]), 2
                )
            ),
            "seeded": full["stats"]["resolve"]["seeded"],
            "continuation": full["stats"]["resolve"]["continuation"],
        }
        results.append(rec)
        carried = full  # the fleet runs the warm plan forward
    return results


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=14,
                    help="locations incl. depot")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--iters", type=int, default=600)
    ap.add_argument("--chains", type=int, default=16)
    ap.add_argument("--horizon", type=int, default=4,
                    help="customers initially outside the horizon")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    os.environ["VRPMS_STORE"] = "memory"
    os.environ["VRPMS_CACHE"] = "off"
    _seed_store(args.n)
    from service.app import serve

    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        steps = run_trace(
            base, args.n, args.steps, args.iters, args.chains, args.horizon
        )
    finally:
        srv.shutdown()
        from service.jobs import shutdown_scheduler

        shutdown_scheduler()

    ratios = [s["evalsRatio"] for s in steps]
    never_worse = all(s["neverWorse"] for s in steps)
    matched = all(r is not None for r in ratios)
    min_ratio = min(ratios) if matched else 0.0
    import jax

    record = {
        "bench": "resolve_delta",
        "config": {
            "n": args.n, "steps": args.steps, "iters": args.iters,
            "chains": args.chains, "horizon": args.horizon,
            "backend": jax.default_backend(),
            "cache": "off",
        },
        "steps": steps,
        "summary": {
            "minEvalsRatio": min_ratio,
            "medianEvalsRatio": sorted(ratios)[len(ratios) // 2]
            if matched else None,
            "neverWorseAtEqualBudget": never_worse,
        },
        "gate": {
            "evalsRatioMin": GATE_EVALS_RATIO,
            "pass": bool(never_worse and matched
                         and min_ratio >= GATE_EVALS_RATIO),
        },
    }
    out = json.dumps(record, indent=2)
    print(out)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0 if record["gate"]["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
