"""Solution-cache benchmark: a repeat-heavy trace, cache on vs. off.

Million-user traffic repeats: the same city/depot/customer set arrives
again and again. This bench replays an 80%-repeat trace (K distinct
requests, each repeated until repeats are 80% of the trace) against the
in-process service twice — `VRPMS_CACHE=off` (every request pays a full
metaheuristic solve, the pre-ISSUE-6 behavior) and cache on (repeats
are exact hits served at store-read latency, bypassing the admission
queue and the solver).

Reported: p50/p99 per phase, hit-only p50/p99, `solvesAvoided` (exact
hits that cost a store read instead of a solve), and the headline
ratio cache-off p50 / hit p50 — gated >= 5x (ISSUE 6 acceptance).

    JAX_PLATFORMS=cpu python -m benchmarks.cache_hit \
        [--distinct 5] [--repeat-pct 80] [--n 8] [--iters 300] \
        [--out records/cache_hit_r11.json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import threading
import time
import urllib.error
import urllib.request

GATE_HIT_P50_SPEEDUP = 5.0


def _post(base: str, path: str, body: dict) -> tuple[int, dict]:
    req = urllib.request.Request(
        base + path,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=600) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _seed_store(n: int) -> None:
    import numpy as np

    import store.memory as mem

    mem.reset()
    rng = np.random.default_rng(31)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        "cachebench", [{"id": i, "demand": 2 if i else 0} for i in range(n)]
    )
    mem.seed_durations("cachebench", d.tolist())


def _body(n: int, iters: int, seed: int) -> dict:
    return {
        "solutionName": "cache-bench",
        "solutionDescription": "cache_hit",
        "locationsKey": "cachebench",
        "durationsKey": "cachebench",
        "capacities": [3 * n] * 3,
        "startTimes": [0, 0, 0],
        "ignoredCustomers": [],
        "completedCustomers": [],
        "seed": seed,
        "iterationCount": iters,
        "populationSize": 8,
    }


def _trace(distinct: int, repeat_pct: float, rng) -> list[int]:
    """Seed indices for the request trace: each of the `distinct`
    requests appears once cold, then repeats fill the trace until
    repeats/total reaches `repeat_pct` — shuffled deterministically."""
    repeats_per = max(1, round(repeat_pct / (100.0 - repeat_pct)))
    trace = list(range(distinct)) * (1 + repeats_per)
    rng.shuffle(trace)
    return trace


def _pct(sorted_ms: list[float], p: float):
    if not sorted_ms:
        return None
    k = min(len(sorted_ms) - 1, int(round(p / 100 * (len(sorted_ms) - 1))))
    return round(sorted_ms[k], 2)


def run_phase(base, trace, n, iters) -> dict:
    lat_all: list[float] = []
    lat_hit: list[float] = []
    lat_solve: list[float] = []
    for seed_idx in trace:
        t0 = time.perf_counter()
        status, resp = _post(base, "/api/vrp/sa", _body(n, iters, seed_idx + 1))
        dt_ms = 1e3 * (time.perf_counter() - t0)
        assert status == 200, resp
        lat_all.append(dt_ms)
        if resp["message"].get("cacheHit"):
            lat_hit.append(dt_ms)
        else:
            lat_solve.append(dt_ms)
    lat_all.sort(), lat_hit.sort(), lat_solve.sort()
    return {
        "requests": len(lat_all),
        "p50Ms": _pct(lat_all, 50),
        "p99Ms": _pct(lat_all, 99),
        "meanMs": round(statistics.mean(lat_all), 2),
        "hits": len(lat_hit),
        "hitP50Ms": _pct(lat_hit, 50),
        "hitP99Ms": _pct(lat_hit, 99),
        "solves": len(lat_solve),
        "solveP50Ms": _pct(lat_solve, 50),
    }


def main() -> None:
    import numpy as np

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--distinct", type=int, default=5,
                    help="distinct requests in the trace")
    ap.add_argument("--repeat-pct", type=float, default=80.0)
    ap.add_argument("--n", type=int, default=8, help="locations per instance")
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--out", default=None, help="record JSON path")
    ap.add_argument("--note", default=None)
    args = ap.parse_args()

    os.environ["VRPMS_STORE"] = "memory"
    _seed_store(args.n)
    trace = _trace(args.distinct, args.repeat_pct, np.random.default_rng(17))
    repeats = len(trace) - args.distinct

    from service import jobs as jobs_mod
    from service import obs
    from service.app import serve
    import store.memory as mem

    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    # one throwaway solve warms the tier's compiled program so neither
    # phase pays XLA compiles inside the measurement
    os.environ["VRPMS_CACHE"] = "off"
    _post(base, "/api/vrp/sa", _body(args.n, args.iters, 999))

    import jax

    record = {
        "benchmark": "cache_hit",
        "backend": jax.default_backend(),
        "locations": args.n,
        "iterationCount": args.iters,
        "distinctRequests": args.distinct,
        "traceLength": len(trace),
        "repeatPct": round(100.0 * repeats / len(trace), 1),
        "note": args.note,
    }

    print(f"== cache off: {len(trace)} requests, every one solves")
    record["cache_off"] = run_phase(base, trace, args.n, args.iters)
    print(json.dumps(record["cache_off"], indent=2))

    os.environ.pop("VRPMS_CACHE", None)
    mem._tables["solution_cache"].clear()
    avoided0 = obs.CACHE_SOLVES_AVOIDED.value
    print(f"== cache on: same trace, repeats should hit")
    record["cache_on"] = run_phase(base, trace, args.n, args.iters)
    record["cache_on"]["solvesAvoided"] = int(
        obs.CACHE_SOLVES_AVOIDED.value - avoided0
    )
    print(json.dumps(record["cache_on"], indent=2))

    off_p50 = record["cache_off"]["p50Ms"]
    hit_p50 = record["cache_on"]["hitP50Ms"]
    speedup = round(off_p50 / hit_p50, 1) if hit_p50 else None
    record["hitP50SpeedupX"] = speedup
    record["gate"] = {
        "requiredHitP50SpeedupX": GATE_HIT_P50_SPEEDUP,
        "passed": bool(speedup and speedup >= GATE_HIT_P50_SPEEDUP),
    }
    print(json.dumps({"hitP50SpeedupX": speedup, "gate": record["gate"]},
                     indent=2))

    jobs_mod.shutdown_scheduler()
    srv.shutdown()
    if args.out:
        out = args.out if os.path.isabs(args.out) else os.path.join(
            os.path.dirname(__file__), args.out
        )
        with open(out, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(f"record -> {out}")


if __name__ == "__main__":
    main()
