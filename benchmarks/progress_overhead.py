"""Live-progress overhead micro-check: sink attached vs not.

    python -m benchmarks.progress_overhead [--reps 11] [--iters 4096]
                                           [--customers 100] [--chains 64]

The live-progress subsystem's acceptance bar (ISSUE 7): always-on
progress recording — a ProgressSink attached for the whole solve,
publishing the synced incumbent at every improving block boundary —
must cost < 1% of solve wall time. Measured on the block-cadence path
the production scheduler actually runs (a generous deadline engages
run_blocked's timed loop, so the solve crosses many 512-iteration
block boundaries and the sink is exercised at full cadence, while the
iteration budget — not the clock — bounds the work, keeping wall time
comparable across the pair).

Same paired design as benchmarks/obs_overhead.py: each rep solves the
SAME seed once per sink state in alternating within-pair order, and
the estimator is the median per-pair relative delta. Prints one JSON
line on stdout (bench.py convention); diagnostics to stderr.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import statistics
import sys
import time


def build_instance(n_customers: int, seed: int = 0):
    import numpy as np

    from vrpms_tpu.core import make_instance

    rng = np.random.default_rng(seed)
    n = n_customers + 1
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    n_vehicles = max(2, n_customers // 10)
    cap = 2.0 * n_customers / n_vehicles * 1.3
    return make_instance(
        d,
        demands=[0.0] + [2.0] * n_customers,
        capacities=[cap] * n_vehicles,
    )


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--reps", type=int, default=11,
                        help="measured solve pairs (one per sink state); "
                        "sub-percent deltas need many pairs on a noisy "
                        "shared host")
    parser.add_argument("--iters", type=int, default=4096,
                        help="SA iterations (>= several 512-blocks)")
    parser.add_argument("--customers", type=int, default=100)
    parser.add_argument("--chains", type=int, default=64)
    args = parser.parse_args()

    os.environ["VRPMS_LOG"] = "off"  # isolate the progress delta
    import jax

    from vrpms_tpu.io.bounds import quick_lower_bound
    from vrpms_tpu.obs import progress
    from vrpms_tpu.solvers import SAParams, solve_sa

    inst = build_instance(args.customers)
    lb = quick_lower_bound(inst)
    params = SAParams(n_chains=args.chains, n_iters=args.iters)

    def one_solve(seed: int, with_sink: bool) -> tuple[float, int]:
        sink = (
            progress.ProgressSink(
                job_id="bench", problem="vrp", algorithm="sa",
                lower_bound=lb,
            )
            if with_sink
            else None
        )
        ctx = progress.attach(sink) if with_sink else contextlib.nullcontext()
        t0 = time.perf_counter()
        with ctx:
            res = solve_sa(inst, key=seed, params=params, deadline_s=3600.0)
        jax.block_until_ready(res.cost)
        elapsed = (time.perf_counter() - t0) * 1e3
        blocks = 0
        if sink is not None:
            prof = sink.profile()
            blocks = 0 if prof is None else prof["blocks"]
        return elapsed, blocks

    print(
        f"[progress_overhead] warmup solve ({args.customers} customers, "
        f"{args.chains}x{args.iters})",
        file=sys.stderr,
    )
    one_solve(0, True)  # compile + seed the sweep-rate cache

    on_ms, off_ms, blocks_seen = [], [], 0
    for rep in range(args.reps):
        pair = ((True, on_ms), (False, off_ms))
        if rep % 2:
            pair = pair[::-1]
        for with_sink, bucket in pair:
            elapsed, blocks = one_solve(rep + 1, with_sink)
            bucket.append(elapsed)
            blocks_seen = max(blocks_seen, blocks)

    overhead_pct = 100.0 * statistics.median(
        (on - off) / off for on, off in zip(on_ms, off_ms)
    )
    line = {
        "bench": "progress_overhead",
        "customers": args.customers,
        "chains": args.chains,
        "iters": args.iters,
        "reps": args.reps,
        "blocks_per_solve": blocks_seen,
        "lower_bound": None if lb is None else round(lb, 1),
        "solve_ms_sink_on": round(statistics.median(on_ms), 2),
        "solve_ms_sink_off": round(statistics.median(off_ms), 2),
        "overhead_pct": round(overhead_pct, 3),
        # negative deltas are timing noise; the bar is one-sided
        "pass": overhead_pct < 1.0,
    }
    print(json.dumps(line))
    return 0 if line["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
