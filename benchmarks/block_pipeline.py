"""Pipelined block dispatch benchmark: overlap host bookkeeping with
device compute in the solver driver loop.

    python -m benchmarks.block_pipeline [--blocks 24] [--rtt-ms 2.5]
                                        [--jobs 4] [--reps 5]

The pipelined driver's acceptance bar (ISSUE 19): on an
overhead-bound sink-on trace, VRPMS_PIPELINE=on must cut the
per-block HOST overhead (wall time beyond pure device compute) by
>= 2x and lift end-to-end jobs/sec by >= 1.15x vs the serial loop —
while fixed-seed solver output stays byte-identical between modes.

"Overhead-bound" is built the way benchmarks/trace_export.py builds
it: the progress sink's per-boundary publish pays a simulated store
round-trip (--rtt-ms), modeling what a production boundary actually
pays when the incumbent publish / durable-checkpoint write crosses
to a remote store. In the serial loop the device idles through that
RTT at EVERY boundary; the pipelined driver overlaps it with the
next in-flight block (time.sleep releases the GIL, so XLA's compute
pool genuinely runs underneath — the same overlap DMA/RPC gets on an
accelerator). Device block time is auto-calibrated to a few ms so
boundaries dominate, exactly the small-block regime where the serial
driver loses the most.

Measurements, paired within each rep (on/off alternating order):
  * wall_dev — the same block sequence launched back-to-back with no
    sink and ONE final sync: pure device pipeline time, the floor
    both modes share. Per-block host overhead is
    (wall_mode - wall_dev) / blocks.
  * jobs/sec — `--jobs` back-to-back run_blocked jobs with the sink
    attached, whole-set wall clock.
  * identity — solve_sa at a fixed seed under each mode (hint cache
    isolated between runs so the decomposition matches): giant tour
    bytes, cost, and evals must be identical.

Prints one JSON line on stdout (bench.py convention); diagnostics to
stderr. Commit the record under benchmarks/records/ — the tier-1
workflow asserts its gate.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time


def _isolate_rate_cache() -> None:
    """Point the sweep-rate hint cache at a throwaway file and clear
    the in-process table: a rate hint learned by one mode would change
    the other mode's block decomposition (hint -> no 128 probe), which
    breaks both the identity check and the paired timing."""
    from vrpms_tpu.solvers import common

    common._SWEEP_RATE.clear()
    common._RATE_LOADED = True  # skip the file load; env points away too


def build_instance(n_customers: int, seed: int = 0):
    import numpy as np

    from vrpms_tpu.core import make_instance

    rng = np.random.default_rng(seed)
    n = n_customers + 1
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    n_vehicles = max(2, n_customers // 10)
    cap = 2.0 * n_customers / n_vehicles * 1.3
    return make_instance(
        d,
        demands=[0.0] + [2.0] * n_customers,
        capacities=[cap] * n_vehicles,
    )


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--blocks", type=int, default=24,
                        help="512-iteration blocks per job")
    parser.add_argument("--batch", type=int, default=8192,
                        help="best-array rows (the per-boundary pull "
                        "the scalar reduction avoids)")
    parser.add_argument("--rtt-ms", type=float, default=2.5,
                        help="simulated store round-trip the sink pays "
                        "per boundary publish")
    parser.add_argument("--target-block-ms", type=float, default=6.0,
                        help="auto-calibrated device time per block")
    parser.add_argument("--jobs", type=int, default=4,
                        help="back-to-back jobs per throughput sample")
    parser.add_argument("--reps", type=int, default=5,
                        help="measured on/off pairs")
    parser.add_argument("--customers", type=int, default=12,
                        help="identity-check SA instance size")
    args = parser.parse_args()

    os.environ["VRPMS_LOG"] = "off"  # isolate the driver delta
    os.environ["VRPMS_RATE_CACHE"] = os.path.join(
        tempfile.mkdtemp(prefix="vrpms_bench_rates_"), "rates.json"
    )
    import jax
    import jax.numpy as jnp
    import numpy as np

    from vrpms_tpu.obs import progress
    from vrpms_tpu.solvers.common import run_blocked

    _isolate_rate_cache()
    block = 512
    n_total = args.blocks * block

    # ---- synthetic overhead-bound job: a deterministic jitted block
    # over a (batch,)-wide best array; `work` fori_loop rounds are
    # calibrated below so one block costs ~target ms on this host
    def make_step(work: int):
        @jax.jit
        def one_block(state):
            best, x = state

            def body(_, x):
                return jnp.cos(x) * jnp.float32(1.0001) + jnp.float32(1e-4)

            x = jax.lax.fori_loop(0, work, body, x)
            return jnp.minimum(best, x), x

        def step_block(state, nb, start):
            # iteration count is priced by the driver; the device work
            # per block is fixed, which is all the timing needs
            return one_block(state)

        return step_block

    def fresh_state():
        x = jnp.linspace(1.0, 2.0, args.batch, dtype=jnp.float32)
        return jnp.full((args.batch,), 1e9, dtype=jnp.float32), x

    sync = lambda st: st[0]  # noqa: E731

    # calibrate `work` to the target device block time
    work = 256
    while True:
        step = make_step(work)
        st = fresh_state()
        st = step(st, block, 0)
        jax.block_until_ready(sync(st))  # compile
        t0 = time.perf_counter()
        for _ in range(4):
            st = step(st, block, 0)
        jax.block_until_ready(sync(st))
        per_block_ms = (time.perf_counter() - t0) / 4 * 1e3
        if per_block_ms >= args.target_block_ms or work >= 1 << 20:
            break
        scale = max(2.0, args.target_block_ms / max(per_block_ms, 1e-3))
        work = int(work * min(scale, 8.0))
    print(f"[block_pipeline] calibrated work={work} "
          f"({per_block_ms:.2f} ms/block device)", file=sys.stderr)

    class StoreShimSink(progress.ProgressSink):
        """ProgressSink whose per-boundary publish pays a simulated
        store round-trip (the trace_export.py overhead-bound shim):
        what the boundary costs when the incumbent publish crosses to
        a remote store. sleep releases the GIL, so the pipelined
        driver's in-flight block computes underneath."""

        def __init__(self, rtt_s: float, **kw):
            super().__init__(**kw)
            self._rtt_s = rtt_s

        def record(self, best, iters, evals_per_iter=None):
            super().record(best, iters, evals_per_iter)
            time.sleep(self._rtt_s)

    class CkptHandle:
        """Bounded-cadence capture handle (the service/checkpoint.py
        shape): due on a wall-clock interval, offer pulls the full
        array to host — the one transfer that is allowed to stay
        array-sized, and only when a capture is actually due."""

        def __init__(self, interval_s: float = 0.02):
            self._interval_s = interval_s
            self._last = 0.0
            self.captures = 0

        def due(self, sink) -> bool:
            return time.monotonic() - self._last >= self._interval_s

        def offer(self, sink, giant) -> None:
            np.asarray(giant)
            self._last = time.monotonic()
            self.captures += 1

    def one_job() -> None:
        sink = StoreShimSink(
            args.rtt_ms / 1e3, job_id="bench", problem="vrp",
            algorithm="sa",
        )
        sink.ckpt = CkptHandle()
        with progress.attach(sink):
            st, done = run_blocked(
                step, fresh_state(), n_total, block, 3600.0, sync,
                incumbent=lambda s: s[0],
            )
        jax.block_until_ready(sync(st))
        assert done == n_total, (done, n_total)
        assert sink.ckpt.captures >= 1

    def device_floor() -> float:
        # the same launch count back-to-back, no sink, one final sync:
        # the pure device pipeline both modes sit on top of. The timed
        # driver opens with a 128 probe then full blocks, so launches
        # = blocks + 1; match that here.
        st = fresh_state()
        t0 = time.perf_counter()
        for _ in range(args.blocks + 1):
            st = step(st, block, 0)
        jax.block_until_ready(sync(st))
        return time.perf_counter() - t0

    def set_mode(on: bool) -> None:
        os.environ["VRPMS_PIPELINE"] = "on" if on else "off"

    # warm both mode paths once (compile + first-touch costs out of
    # the measured pairs)
    for on in (True, False):
        set_mode(on)
        one_job()
    dev_walls = [device_floor() for _ in range(3)]
    wall_dev = statistics.median(dev_walls)

    job_on, job_off, jps_on, jps_off = [], [], [], []
    for rep in range(args.reps):
        modes = (True, False) if rep % 2 == 0 else (False, True)
        for on in modes:
            set_mode(on)
            t0 = time.perf_counter()
            one_job()
            (job_on if on else job_off).append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            for _ in range(args.jobs):
                one_job()
            jps = args.jobs / (time.perf_counter() - t0)
            (jps_on if on else jps_off).append(jps)
        print(f"[block_pipeline] rep {rep + 1}/{args.reps}: "
              f"job on {job_on[-1] * 1e3:.1f} ms / off "
              f"{job_off[-1] * 1e3:.1f} ms", file=sys.stderr)

    ov_on_ms = [
        max(0.0, (w - wall_dev)) / args.blocks * 1e3 for w in job_on
    ]
    ov_off_ms = [
        max(0.0, (w - wall_dev)) / args.blocks * 1e3 for w in job_off
    ]
    overhead_on = statistics.median(ov_on_ms)
    overhead_off = statistics.median(ov_off_ms)
    overhead_cut = overhead_off / max(overhead_on, 1e-6)
    ratio = statistics.median(
        on / off for on, off in zip(jps_on, jps_off)
    )

    # ---- fixed-seed identity: the REAL solver, each mode from an
    # identical empty hint cache so the decompositions match
    from vrpms_tpu.solvers import SAParams, solve_sa

    inst = build_instance(args.customers)
    params = SAParams(n_chains=16, n_iters=1536)
    outs = {}
    for on in (True, False):
        set_mode(on)
        _isolate_rate_cache()
        res = solve_sa(inst, key=7, params=params, deadline_s=3600.0)
        outs[on] = (
            np.asarray(res.giant).tobytes(),
            float(res.cost),
            int(res.evals),
        )
    identical = outs[True] == outs[False]

    gate = {
        "overheadCutMin": 2.0,
        "overheadCut": round(overhead_cut, 2),
        "jobsPerSecRatioMin": 1.15,
        "jobsPerSecRatio": round(ratio, 3),
        "fixedSeedIdentical": identical,
    }
    gate["pass"] = (
        gate["overheadCut"] >= gate["overheadCutMin"]
        and gate["jobsPerSecRatio"] >= gate["jobsPerSecRatioMin"]
        and identical
    )
    line = {
        "bench": "block_pipeline",
        "config": {
            "blocks": args.blocks,
            "blockSize": block,
            "batch": args.batch,
            "rttMs": args.rtt_ms,
            "deviceBlockMs": round(per_block_ms, 2),
            "work": work,
            "jobs": args.jobs,
            "reps": args.reps,
            "backend": jax.default_backend(),
        },
        "perBlock": {
            "deviceFloorMs": round(wall_dev / (args.blocks + 1) * 1e3, 3),
            "overheadOffMs": round(overhead_off, 3),
            "overheadOnMs": round(overhead_on, 3),
        },
        "throughput": {
            "jobsPerSecOn": round(statistics.median(jps_on), 3),
            "jobsPerSecOff": round(statistics.median(jps_off), 3),
        },
        "identity": {
            "fixedSeedIdentical": identical,
            "cost": outs[True][1],
            "evals": outs[True][2],
        },
        "gate": gate,
    }
    print(json.dumps(line, indent=2))
    return 0 if gate["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
