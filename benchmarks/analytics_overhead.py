"""Solve analytics: overhead gate + rollup correctness + store-down.

    python -m benchmarks.analytics_overhead [--reps 8] [--iters 800]
                                            [--customers 60] [--chains 64]
                                            [--rtt-ms 25]
                                            [--out benchmarks/records/...json]

The solve-analytics acceptance bar (ISSUE 20), four phases:

  1. **Overhead** — the paired design on the REAL request path
     (service.solve.run_vrp bracketed by the exact per-request trace
     lifecycle the HTTP layer runs), alternating VRPMS_ANALYTICS
     on/off each rep. The flight-record store sits behind an RTT shim
     (default 25 ms per batch write — the hosted store's real per-op
     cost) so the measurement includes a realistically SLOW analytics
     store; the exporter is a bounded background flusher, so
     solves/sec must not care: gate < 1% overhead.
  2. **Steady state** — after the on-arm drains, every offered flight
     record must be accounted `ok`: gate zero dropped.
  3. **Rollup correctness** — the captured records must be RIGHT, not
     just cheap: the recorded padding occupancy must equal the value
     recomputed by hand from the record's own tier label and the known
     real instance size, and the debug-endpoint rollup aggregation
     must reproduce it: gate exact (4-decimal) agreement.
  4. **Store down** — the analytics store hard-fails; the same request
     mix must serve 100% (export failures only tick the `failed`
     counter) and the local ring must still hold the records: gate
     100% served, local record present.

Prints one JSON line on stdout (bench.py convention); diagnostics to
stderr; `--out` also writes the committed record the CI gate asserts.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time


def build_request(n_customers: int, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    n = n_customers + 1
    pts = rng.uniform(0, 100, size=(n, 2))
    matrix = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1).tolist()
    locations = [
        {"id": i, "demand": 2 if i else 0} for i in range(n)
    ]
    n_vehicles = max(2, n_customers // 10)
    cap = 2.0 * n_customers / n_vehicles * 1.3
    params = {
        "name": "analytics-overhead",
        "description": "bench",
        "auth": None,
        "ignored_customers": [],
        "completed_customers": [],
        "capacities": [cap] * n_vehicles,
        "start_times": [0.0] * n_vehicles,
    }
    return params, locations, matrix, n, n_vehicles


class RttShim:
    """The hosted store's per-op latency, applied to the flight-record
    write path only — the background flusher pays it, requests must
    not."""

    def __init__(self, inner, rtt_s: float):
        self.inner = inner
        self.rtt_s = rtt_s
        self.writes = 0

    def put_flight_records(self, rows):
        time.sleep(self.rtt_s)
        self.writes += 1
        return self.inner.put_flight_records(rows)


class DownStore:
    """A hard-down analytics store: every batch write fails."""

    def put_flight_records(self, rows):
        raise RuntimeError("injected: analytics store down")


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--reps", type=int, default=8,
                        help="measured solve pairs (one per analytics state)")
    parser.add_argument("--iters", type=int, default=800)
    parser.add_argument("--customers", type=int, default=60)
    parser.add_argument("--chains", type=int, default=64)
    parser.add_argument("--rtt-ms", type=float, default=25.0,
                        help="simulated store RTT per record batch write")
    parser.add_argument("--down-requests", type=int, default=6,
                        help="requests served during the store-down phase")
    parser.add_argument("--out", default=None,
                        help="also write the committed record here")
    args = parser.parse_args()

    os.environ["VRPMS_LOG"] = "off"  # isolate the analytics delta
    os.environ["VRPMS_STORE"] = "memory"
    os.environ["VRPMS_TRACING"] = "on"
    os.environ["VRPMS_CACHE"] = "off"  # every rep pays a real solve
    os.environ["VRPMS_ANALYTICS"] = "off"
    import store
    from service import obs as service_obs
    from service.debug import analytics_rollup
    from service.solve import run_vrp
    from vrpms_tpu.obs import analytics, spans

    def count(outcome: str) -> float:
        return service_obs.ANALYTICS_TOTAL.labels(outcome=outcome).value

    params, locations, matrix, n_real, v_real = build_request(
        args.customers
    )
    opts = {
        "seed": 1,
        "iteration_count": args.iters,
        "population_size": args.chains,
    }

    def one_solve(seed: int) -> float:
        """One request-shaped solve under the current analytics state:
        the exact per-request span lifecycle the service runs, so the
        flight record's finish-seam capture is on the measured path."""
        errors: list = []
        t0 = time.perf_counter()
        trace = spans.start_trace(None)
        tokens = None
        if trace is not None:
            root = trace.span("POST /api/vrp/sa")
            tokens = spans.activate(trace, root)
        try:
            result = run_vrp(
                "sa", params, dict(opts, seed=seed), {}, locations, matrix,
                errors, database=None,
            )
        finally:
            if trace is not None:
                trace.root().end()
                spans.deactivate(tokens)
                trace.finish()
        elapsed = (time.perf_counter() - t0) * 1e3
        assert result is not None and not errors, errors
        return elapsed

    shim = RttShim(store.get_database("vrp", None), args.rtt_ms / 1e3)
    analytics.set_store_factory(lambda: shim)

    print(
        f"[analytics_overhead] warmup solve ({args.customers} customers, "
        f"{args.chains}x{args.iters})",
        file=sys.stderr,
    )
    one_solve(0)  # compile

    # -- phase 1: paired on/off overhead ------------------------------------
    on_ms, off_ms = [], []
    for rep in range(args.reps):
        pair = (("on", on_ms), ("off", off_ms))
        if rep % 2:
            pair = pair[::-1]
        for state, sink in pair:
            os.environ["VRPMS_ANALYTICS"] = state
            sink.append(one_solve(rep + 1))
    os.environ["VRPMS_ANALYTICS"] = "on"
    assert analytics.flush(30.0), "exporter failed to drain"
    overhead_pct = 100.0 * statistics.median(
        (on - off) / off for on, off in zip(on_ms, off_ms)
    )

    # -- phase 2: steady-state accounting -----------------------------------
    ok, dropped, failed = count("ok"), count("dropped"), count("failed")
    offered = ok + dropped + failed
    print(
        f"[analytics_overhead] steady state: ok={ok:.0f} "
        f"dropped={dropped:.0f} failed={failed:.0f} "
        f"batchWrites={shim.writes}",
        file=sys.stderr,
    )

    # -- phase 3: rollup correctness ----------------------------------------
    # the record's occupancy must match a hand recomputation from its
    # own tier label: compute occupancy = real work / padded work
    docs = analytics.recent_records()
    assert docs, "no flight records captured on the on-arm"
    doc = docs[0]
    shape = doc["tier"].split(":", 1)[1].split("x")
    n_pad, v_pad = int(shape[0]), int(shape[1])
    expect_occ = round((n_real + v_real) / (n_pad + v_pad), 4)
    recorded_occ = doc["occupancy"]["compute"]
    rollup = analytics_rollup(docs)
    tier_row = next(
        t for t in rollup["tiers"] if t["tier"] == doc["tier"]
    )
    rollup_occ = tier_row["meanOccupancy"]
    rollup_correct = (
        n_pad >= n_real
        and v_pad >= v_real
        and recorded_occ == expect_occ
        and abs(rollup_occ - expect_occ) < 5e-4
        and doc["deviceS"] > 0
        and doc["evals"] > 0
    )
    print(
        f"[analytics_overhead] rollup probe: tier={doc['tier']} "
        f"recorded={recorded_occ} expected={expect_occ} "
        f"rollupMean={rollup_occ}",
        file=sys.stderr,
    )

    # -- phase 4: store down --------------------------------------------------
    analytics.set_store_factory(lambda: DownStore())
    served = 0
    before = len(analytics.recent_records())
    for i in range(args.down_requests):
        errors: list = []
        trace = spans.start_trace(None)
        root = trace.span("POST /api/vrp/sa")
        tokens = spans.activate(trace, root)
        try:
            result = run_vrp(
                "sa", params, dict(opts, seed=100 + i), {}, locations,
                matrix, errors, database=None,
            )
        finally:
            trace.root().end()
            spans.deactivate(tokens)
            trace.finish()
        if result is not None and not errors:
            served += 1
    analytics.flush(30.0)
    down_failed = count("failed") - failed
    local_records_ok = len(analytics.recent_records()) >= before + served
    analytics.set_store_factory(None)
    analytics.reset_analytics()

    served_frac = served / max(1, args.down_requests)
    gate = {
        "overheadPct": round(overhead_pct, 3),
        "overheadMax": 1.0,
        "droppedSteadyState": int(dropped),
        "offeredRecords": int(offered),
        "okRecords": int(ok),
        "rollupCorrect": bool(rollup_correct),
        "recordedOccupancy": recorded_occ,
        "expectedOccupancy": expect_occ,
        "storeDownServed": served_frac,
        "storeDownFailedRecords": int(down_failed),
        "localRecordsServedWhileDown": bool(local_records_ok),
        "pass": (
            overhead_pct < 1.0
            and dropped == 0
            and failed == 0
            and ok > 0
            and rollup_correct
            and served_frac == 1.0
            and down_failed > 0
            and local_records_ok
        ),
    }
    line = {
        "bench": "analytics_overhead",
        "customers": args.customers,
        "chains": args.chains,
        "iters": args.iters,
        "reps": args.reps,
        "rttMs": args.rtt_ms,
        "solve_ms_analytics_on": round(statistics.median(on_ms), 2),
        "solve_ms_analytics_off": round(statistics.median(off_ms), 2),
        "tier": doc["tier"],
        "batchWrites": shim.writes,
        "gate": gate,
        "pass": gate["pass"],
    }
    print(json.dumps(line))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(line, f, indent=2)
            f.write("\n")
    return 0 if line["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
