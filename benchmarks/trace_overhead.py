"""Span-tracing overhead micro-check: tracing on vs VRPMS_TRACING=off.

    python -m benchmarks.trace_overhead [--reps 10] [--iters 1000]
                                        [--customers 100] [--chains 64]

The tracing subsystem's acceptance bar (ISSUE 5): always-on span
recording — a Trace per request, the root/solver/finish spans the
service records, the completed-trace ring push, and the histogram
exemplar — must cost < 1% of solve wall time on a warmed SA solve.
Measured like benchmarks/obs_overhead.py: the REAL request path
(service.solve.run_vrp on a synthetic euclidean instance) bracketed by
the same trace lifecycle the HTTP layer runs (start_trace -> root span
-> activate -> finish), alternating VRPMS_TRACING between on and off
with a paired within-rep design so host drift cancels. Structured
logging is off so only the span-recording delta is measured; metrics
stay on in BOTH arms (their cost was priced by obs_overhead).

Prints one JSON line on stdout (bench.py convention); diagnostics to
stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time


def build_request(n_customers: int, seed: int = 0):
    import numpy as np

    rng = np.random.default_rng(seed)
    n = n_customers + 1
    pts = rng.uniform(0, 100, size=(n, 2))
    matrix = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1).tolist()
    locations = [
        {"id": i, "demand": 2 if i else 0} for i in range(n)
    ]
    n_vehicles = max(2, n_customers // 10)
    cap = 2.0 * n_customers / n_vehicles * 1.3
    params = {
        "name": "trace-overhead",
        "description": "bench",
        "auth": None,
        "ignored_customers": [],
        "completed_customers": [],
        "capacities": [cap] * n_vehicles,
        "start_times": [0.0] * n_vehicles,
    }
    return params, locations, matrix


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--reps", type=int, default=10,
                        help="measured solve pairs (one per tracing state)")
    parser.add_argument("--iters", type=int, default=1000)
    parser.add_argument("--customers", type=int, default=100)
    parser.add_argument("--chains", type=int, default=64)
    args = parser.parse_args()

    os.environ["VRPMS_LOG"] = "off"  # isolate the span-recording delta
    from service.solve import run_vrp
    from vrpms_tpu.obs import spans

    params, locations, matrix = build_request(args.customers)
    opts = {
        "seed": 1,
        "iteration_count": args.iters,
        "population_size": args.chains,
    }

    def one_solve(seed: int):
        """One request-shaped solve under the current VRPMS_TRACING:
        the exact per-request span lifecycle the service runs."""
        errors: list = []
        t0 = time.perf_counter()
        trace = spans.start_trace(None)
        tokens = None
        if trace is not None:
            root = trace.span("POST /api/vrp/sa")
            tokens = spans.activate(trace, root)
        try:
            result = run_vrp(
                "sa", params, dict(opts, seed=seed), {}, locations, matrix,
                errors, database=None,
            )
        finally:
            if trace is not None:
                trace.root().end()
                spans.deactivate(tokens)
                trace.finish()
        elapsed = (time.perf_counter() - t0) * 1e3
        assert result is not None and not errors, errors
        return elapsed

    print(
        f"[trace_overhead] warmup solve ({args.customers} customers, "
        f"{args.chains}x{args.iters})",
        file=sys.stderr,
    )
    os.environ["VRPMS_TRACING"] = "on"
    one_solve(0)  # compile

    on_ms, off_ms = [], []
    # paired design (see obs_overhead): each rep runs the SAME seed once
    # per tracing state, flipping the within-pair order each rep so
    # drift (thermal, GC, cache) cancels; the estimator is the median of
    # per-pair relative deltas
    for rep in range(args.reps):
        pair = (("on", on_ms), ("off", off_ms))
        if rep % 2:
            pair = pair[::-1]
        for state, sink in pair:
            os.environ["VRPMS_TRACING"] = state
            sink.append(one_solve(rep + 1))
    os.environ["VRPMS_TRACING"] = "on"

    overhead_pct = 100.0 * statistics.median(
        (on - off) / off for on, off in zip(on_ms, off_ms)
    )
    line = {
        "bench": "trace_overhead",
        "customers": args.customers,
        "chains": args.chains,
        "iters": args.iters,
        "reps": args.reps,
        "solve_ms_tracing_on": round(statistics.median(on_ms), 2),
        "solve_ms_tracing_off": round(statistics.median(off_ms), 2),
        "overhead_pct": round(overhead_pct, 3),
        # negative deltas are timing noise; the bar is one-sided
        "pass": overhead_pct < 1.0,
    }
    print(json.dumps(line))
    return 0 if line["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
