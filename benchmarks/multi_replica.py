"""Multi-replica distributed-queue benchmark: tier-affinity compile
gate, store-backed queue overhead, and the replica-scaling trajectory.

Three phases (all CPU-verifiable):

  affinity — the ISSUE-9 perf gate, compile-count based like PR 4's
      compile_amortization: a cold mixed-tier trace split across 2
      replicas, once with hash-routed claiming (each tier's jobs go to
      its consistent-hash ring owner — what Replica claims with
      stealing idle) and once with unrouted round-robin claiming (jobs
      alternate replicas regardless of tier). Each replica's share runs
      in its OWN fresh subprocess (fresh jit caches, persistent compile
      cache off) — exactly the per-box isolation real replicas have —
      and the subprocess reports its real XLA backend-compile count
      (vrpms_tpu.obs.compile). Each child first PRIMES on an off-trace
      tier: the shape-independent once-per-process programs (~9
      compiles here) are paid by every replica regardless of routing
      policy (deployment warmup covers them), so the gate compares the
      MARGINAL per-tier compiles routing actually controls; the primed
      count is recorded per replica for transparency. Gate: routed
      pays >= 1.8x fewer marginal cold compiles than round-robin.

  overhead — the store-backed queue at 1 replica vs the local in-memory
      queue, on the overhead-bound sched_throughput trace (tiny
      instances, closed-loop async submit+poll clients): jobs/sec and
      p50/p99, gate < 10% jobs/sec loss. Micro-batching is pinned off
      (VRPMS_SCHED_MAX_BATCH=1) for these phases: batch-size-dependent
      compiles landing inside a measurement window would swamp the
      millisecond-scale queue overhead under test, and the batching
      machinery downstream of the queue is IDENTICAL on both paths.

  scaling — 2- and 4-replica jobs/sec + p99 on the shared queue
      (in-process replicas, each with its own scheduler/worker),
      recorded for the trajectory. NOTE: this container has ONE CPU
      core, so compute-bound scaling cannot show here — the numbers
      document the harness and the overhead floor; run on real
      multi-device boxes for the scale story.

    JAX_PLATFORMS=cpu python -m benchmarks.multi_replica \
        [--duration 8] [--warmup 3] [--clients 4] [--iters 2000] \
        [--pop 64] [--skip-affinity] [--skip-scaling] \
        [--out records/multi_replica_r14.json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

#: the mixed-tier cold trace: location counts landing on four distinct
#: default-ladder tiers (8, 16, 24, 32), four jobs per tier
AFFINITY_SIZES = (7, 14, 22, 30)
AFFINITY_JOBS_PER_TIER = 4
AFFINITY_ITERS = 300
AFFINITY_POP = 16


# ---------------------------------------------------------------------------
# affinity phase: child process = one replica's cold compile bill
# ---------------------------------------------------------------------------


#: priming size: pads to tier 48, which no trace size lands on
PRIME_N = 40


def _child(spec_json: str) -> None:
    """Solve the assigned job list in THIS fresh process and print the
    real XLA compile count (the per-box cold-compile bill). Primes on
    an off-trace tier first so the reported `compiles` is the MARGINAL
    tier-specific count (see module docstring)."""
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    from vrpms_tpu.obs import compile as cobs

    cobs.install()
    from service.solve import _run_solver
    from vrpms_tpu.core import tiers
    from vrpms_tpu.io.synth import synth_cvrp

    def solve(n, v, seed):
        inst = tiers.maybe_pad(synth_cvrp(n, v, seed=seed))
        errors: list = []
        opts = {
            "seed": seed,
            "population_size": AFFINITY_POP,
            "iteration_count": AFFINITY_ITERS,
        }
        _run_solver(inst, "sa", opts, {}, errors, "vrp", None)
        if errors:
            print(json.dumps({"error": errors}), flush=True)
            raise SystemExit(1)

    solve(PRIME_N, 3, 0)
    prime_compiles, _ = cobs.snapshot()
    t0 = time.perf_counter()
    for n, v, seed in json.loads(spec_json):
        solve(n, v, seed)
    compiles, seconds = cobs.snapshot()
    print(json.dumps({
        "compiles": compiles - prime_compiles,
        "primeCompiles": prime_compiles,
        "compileSeconds": round(seconds, 2),
        "wallSeconds": round(time.perf_counter() - t0, 2),
    }), flush=True)


def _run_child(jobs: list) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    out = subprocess.run(
        [sys.executable, "-m", "benchmarks.multi_replica",
         "--child", json.dumps(jobs)],
        capture_output=True, text=True, env=env, timeout=1800,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    if out.returncode != 0:
        raise RuntimeError(f"child failed: {out.stdout}\n{out.stderr}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def affinity_phase() -> dict:
    """Routed vs round-robin claim assignment -> per-replica subprocess
    cold solves -> total real compiles."""
    from vrpms_tpu.core import tiers
    from vrpms_tpu.io.synth import synth_cvrp
    from vrpms_tpu.sched.ring import HashRing, slot

    members = ["replica-a", "replica-b"]
    ring = HashRing(members)
    trace = []  # (n, v, seed, ring token)
    v = 3
    seed = 0
    for n in AFFINITY_SIZES:
        # the ring token the service would compute: the PADDED shape
        # (service.jobs.ring_token) — derive it the same way
        inst = tiers.maybe_pad(synth_cvrp(n, v, seed=0))
        shape = "x".join(str(int(d)) for d in inst.durations.shape)
        token = f"vrp:{shape}x{int(inst.n_vehicles)}:tw0:het0:td0"
        for _ in range(AFFINITY_JOBS_PER_TIER):
            seed += 1
            trace.append((n, v, seed, token))

    def split(policy: str) -> dict[str, list]:
        shares: dict[str, list] = {m: [] for m in members}
        for i, (n, vv, s, token) in enumerate(trace):
            if policy == "routed":
                owner = ring.owner(slot(token))
            else:  # round-robin: tier-blind alternation
                owner = members[i % len(members)]
            shares[owner].append([n, vv, s])
        return shares

    result: dict = {
        "trace": {
            "sizes": list(AFFINITY_SIZES),
            "jobsPerTier": AFFINITY_JOBS_PER_TIER,
            "iterationCount": AFFINITY_ITERS,
            "populationSize": AFFINITY_POP,
        },
    }
    for policy in ("routed", "roundrobin"):
        shares = split(policy)
        total = {"compiles": 0, "compileSeconds": 0.0, "wallSeconds": 0.0}
        per_replica = {}
        for m, jobs in shares.items():
            print(f"== affinity/{policy}: {m} solves {len(jobs)} jobs "
                  f"({sorted(set(j[0] for j in jobs))}) in a fresh process")
            child = _run_child(jobs) if jobs else {
                "compiles": 0, "primeCompiles": 0,
                "compileSeconds": 0.0, "wallSeconds": 0.0,
            }
            per_replica[m] = dict(child, jobs=len(jobs))
            for k in total:
                total[k] = round(total[k] + child[k], 2)
        result[policy] = {"perReplica": per_replica, "total": total}
        print(f"   {policy}: total compiles {total['compiles']}")
    routed = result["routed"]["total"]["compiles"]
    rr = result["roundrobin"]["total"]["compiles"]
    result["compileRatio"] = round(rr / max(1, routed), 2)
    result["gate"] = {
        "threshold": 1.8,
        "pass": rr >= 1.8 * routed,
    }
    return result


# ---------------------------------------------------------------------------
# overhead + scaling phases: closed-loop async clients over HTTP
# ---------------------------------------------------------------------------


def _post(base, path, body):
    req = urllib.request.Request(
        base + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as resp:
        return resp.status, json.loads(resp.read())


def _seed_store(n: int) -> None:
    import numpy as np

    import store.memory as mem

    rng = np.random.default_rng(17)
    pts = rng.uniform(0, 100, size=(n, 2))
    d = np.linalg.norm(pts[:, None] - pts[None, :], axis=-1)
    mem.seed_locations(
        f"bench{n}",
        [{"id": i, "demand": 2 if i else 0} for i in range(n)],
    )
    mem.seed_durations(f"bench{n}", d.tolist())


def _body(n: int, iters: int, pop: int, seed: int) -> dict:
    return {
        "problem": "vrp", "algorithm": "sa",
        "solutionName": f"bench-{n}", "solutionDescription": "multi_replica",
        "locationsKey": f"bench{n}", "durationsKey": f"bench{n}",
        "capacities": [3 * n] * 3, "startTimes": [0, 0, 0],
        "ignoredCustomers": [], "completedCustomers": [],
        "seed": seed, "iterationCount": iters, "populationSize": pop,
    }


def drive_async(base, n, clients, duration_s, warmup_s, iters, pop) -> dict:
    """Closed-loop async clients: submit -> poll to terminal -> next."""
    stop = threading.Event()
    measuring = threading.Event()
    latencies: list[float] = []
    failures: list = []
    lock = threading.Lock()

    def client(i: int) -> None:
        seed = 1000 * i
        while not stop.is_set():
            seed += 1
            t0 = time.perf_counter()
            status, resp = _post(base, "/api/jobs", _body(n, iters, pop, seed))
            ok = status == 202
            if ok:
                jid = resp["jobId"]
                while not stop.is_set():
                    s, r = _get(base, f"/api/jobs/{jid}")
                    if r["job"]["status"] in ("done", "failed"):
                        ok = r["job"]["status"] == "done"
                        break
                    time.sleep(0.005)
            dt = time.perf_counter() - t0
            if not measuring.is_set():
                continue
            with lock:
                (latencies if ok else failures).append(dt)

    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(clients)
    ]
    for t in threads:
        t.start()
    time.sleep(warmup_s)
    measuring.set()
    t_meas = time.perf_counter()
    time.sleep(duration_s)
    measured_s = time.perf_counter() - t_meas
    stop.set()
    for t in threads:
        t.join(timeout=300)
    lat_ms = sorted(1e3 * x for x in latencies)

    def pct(p):
        if not lat_ms:
            return None
        k = min(len(lat_ms) - 1, int(round(p / 100 * (len(lat_ms) - 1))))
        return round(lat_ms[k], 1)

    return {
        "jobs": len(lat_ms),
        "jobsPerSec": round(len(lat_ms) / measured_s, 2),
        "p50Ms": pct(50),
        "p99Ms": pct(99),
        "meanMs": round(statistics.mean(lat_ms), 1) if lat_ms else None,
        "failures": len(failures),
        "measuredSeconds": round(measured_s, 2),
    }


def overhead_and_scaling(args) -> dict:
    os.environ["VRPMS_STORE"] = "memory"
    os.environ["VRPMS_QUEUE_POLL_MS"] = "5"
    os.environ["VRPMS_RECLAIM_S"] = "0.5"
    # solo dispatch only: one prewarmed program for every measured job
    # (see module docstring — isolates queue overhead from batch-shape
    # compile noise; the batching path is shared by both queue modes)
    os.environ["VRPMS_SCHED_MAX_BATCH"] = "1"
    # cache off for the same reason: a near hit mid-phase would swap in
    # the warm-SEEDED anneal variant (a different compiled program) and
    # serve some jobs at store-read latency — both orthogonal to queue
    # overhead and fatal to a stable comparison
    os.environ["VRPMS_CACHE"] = "off"
    _seed_store(args.n)

    from service import jobs as jobs_mod
    from service.app import serve
    from vrpms_tpu.sched import Scheduler

    srv = serve(port=0)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    threading.Thread(target=srv.serve_forever, daemon=True).start()

    # pre-warm the jit caches BEFORE any measured phase: in-process
    # phases share one process's caches, so without this the first
    # mode would pay every cold compile inside its own measurement
    # window and the comparison would order-of-execution bias
    os.environ["VRPMS_QUEUE"] = "local"
    print("== prewarm: compiling the trace shape (solo + batched)")
    warm_ids = []
    for i in range(max(2, args.clients)):
        status, resp = _post(base, "/api/jobs",
                             _body(args.n, args.iters, args.pop, 900 + i))
        assert status == 202, resp
        warm_ids.append(resp["jobId"])
    for jid in warm_ids:
        while True:
            _, r = _get(base, f"/api/jobs/{jid}")
            if r["job"]["status"] in ("done", "failed"):
                break
            time.sleep(0.05)
    jobs_mod.shutdown_scheduler()

    out: dict = {}
    configs = [("local", 0), ("store_1replica", 1)]
    if not args.skip_scaling:
        configs += [("store_2replicas", 2), ("store_4replicas", 4)]
    for label, replicas in configs:
        extras = []
        if replicas == 0:
            os.environ["VRPMS_QUEUE"] = "local"
        else:
            os.environ["VRPMS_QUEUE"] = "store"
            # replica 1 is the service's own; the rest are in-process
            # peers with their own scheduler/worker (one-per-box model)
            for i in range(replicas - 1):
                # mirror the service scheduler's env-driven config —
                # a different max_batch here would compile batch shapes
                # the prewarmed phases never pay, skewing the numbers
                sched = Scheduler(
                    jobs_mod._runner,
                    queue_limit=int(
                        os.environ.get("VRPMS_SCHED_QUEUE", "64")
                    ),
                    window_s=float(
                        os.environ.get("VRPMS_SCHED_WINDOW_MS", "10")
                    ) / 1e3,
                    max_batch=int(
                        os.environ.get("VRPMS_SCHED_MAX_BATCH", "16")
                    ),
                    on_event=jobs_mod._on_event,
                    watchdog_s=0,
                )
                rep = jobs_mod.build_replica(
                    f"bench-extra-{i}", scheduler=sched,
                    lease_s=10.0, poll_s=0.005, heartbeat_s=0.5,
                ).start()
                rep._bench_sched = sched
                extras.append(rep)
        print(f"== {label}: {args.clients} clients, "
              f"{args.duration:.0f}s measure")
        out[label] = drive_async(
            base, args.n, args.clients, args.duration, args.warmup,
            args.iters, args.pop,
        )
        out[label]["replicas"] = max(1, replicas) if replicas else 1
        print(json.dumps(out[label], indent=2))
        for rep in extras:
            rep.stop()
            rep._bench_sched.shutdown(timeout=2.0)
        jobs_mod.shutdown_scheduler()  # fresh scheduler+replica per mode
    os.environ.pop("VRPMS_QUEUE", None)
    os.environ.pop("VRPMS_SCHED_MAX_BATCH", None)
    os.environ.pop("VRPMS_CACHE", None)
    srv.shutdown()

    local, store1 = out["local"], out["store_1replica"]
    if local["jobsPerSec"]:
        overhead = 1.0 - store1["jobsPerSec"] / local["jobsPerSec"]
        out["storeQueueOverhead"] = round(overhead, 4)
        out["overheadGate"] = {
            "threshold": 0.10,
            "pass": overhead < 0.10,
        }
        print(f"store-backed queue overhead at 1 replica: "
              f"{100 * overhead:.1f}%")
    return out


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--warmup", type=float, default=4.0)
    ap.add_argument("--n", type=int, default=12)
    ap.add_argument("--iters", type=int, default=800)
    ap.add_argument("--pop", type=int, default=32)
    ap.add_argument("--skip-affinity", action="store_true")
    ap.add_argument("--skip-scaling", action="store_true")
    ap.add_argument("--out", default=None)
    ap.add_argument("--note", default=None)
    args = ap.parse_args()

    if args.child is not None:
        _child(args.child)
        return

    import jax

    record: dict = {
        "benchmark": "multi_replica",
        "backend": jax.default_backend(),
        "note": args.note,
    }
    if not args.skip_affinity:
        record["affinity"] = affinity_phase()
    record["throughput"] = overhead_and_scaling(args)

    if "affinity" in record:
        g = record["affinity"]["gate"]
        print(f"affinity gate (routed >= 1.8x fewer cold compiles): "
              f"{record['affinity']['compileRatio']}x "
              f"{'PASS' if g['pass'] else 'FAIL'}")
    if "overheadGate" in record["throughput"]:
        g = record["throughput"]["overheadGate"]
        print(f"overhead gate (<10% at 1 replica): "
              f"{'PASS' if g['pass'] else 'FAIL'}")

    if args.out:
        out = args.out if os.path.isabs(args.out) else os.path.join(
            os.path.dirname(__file__), args.out
        )
        with open(out, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(f"record -> {out}")


if __name__ == "__main__":
    main()
