from vrpms_tpu.kernels.sa_eval import (
    pallas_objective_batch,
    pallas_available,
)
