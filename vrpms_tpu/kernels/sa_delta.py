"""Pallas TPU kernel: one FUSED delta-evaluated SA step.

The round-2 anneal step paid three full-size dances per move: an XLA
one-hot move apply ((B, L, L) bf16 through HBM), a full objective
evaluation (O(L * N^2) MACs per chain — VERDICT round-2 weak #7: every
move changes O(1) legs, so full eval wastes 1-2 orders of magnitude),
and the proposal bookkeeping. This kernel performs the ENTIRE step —
candidate-list proposal decode, move apply, exact distance delta, exact
capacity excess of the candidate, Metropolis accept, state commit — in
VMEM per chain tile. Only the (L-hat, B) tour/demand state and a few
(1, B) rows cross HBM per step.

The enabling observation: every proposal family here (reverse / rotate /
swap of a window [lo, hi]) is a PER-LANE SUBLANE ROLL composed with
elementwise masks. A per-lane roll by rho_b is eight masked STATIC rolls
(binary decomposition of rho) — pure VPU work, no gather anywhere, which
matters because Mosaic's dynamic-gather lowering crashes in this
environment (see sa_eval.py's header) and one-hot matmul apply is
exactly the HBM dance being deleted. Distance deltas read 12 d[u, v]
pairs via one-hot matmuls on the MXU (the d table lives in VMEM, bf16 —
the same table rounding as every hot path).

Exactness contract: the committed `dist` state accumulates closed-form
deltas of the bf16-rounded table in f32 — identical rounding semantics
to the one-hot hot paths — and the solver re-syncs it against the fused
evaluation kernel at block boundaries to kill drift. Capacity excess is
recomputed exactly for every candidate (a move across separators can
reshape several routes; the segmented-scan recompute is cheaper than
casework and never wrong). The reverse-move delta assumes a SYMMETRIC
duration matrix (interior legs of a reversed segment re-cost only under
symmetry); callers gate on that (delta_supported).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

try:  # pallas imports fail on some CPU-only builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False

_NEG_BIG = -1e18


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _roll_up_static(x, s):
    """out[k] = x[(k + s) mod rows] for STATIC s — two sublane slices."""
    if s == 0:
        return x
    return jnp.concatenate([x[s:], x[:s]], axis=0)


def _flip_sublanes(x, lhat):
    """out[k, b] = x[lhat-1-k, b] — sublane reversal WITHOUT the MXU.

    Index reversal is XOR with lhat-1 (power-of-2 lhat), decomposed
    into log2(lhat) masked static roll pairs on the original sublane
    index: stage `bit` routes in[k ^ bit] to k, and the stages compose
    to the full XOR because every where-mask reads position, not data.
    Replaces the antidiagonal f32 matmul flip (round 5): XLA:TPU's
    default-precision dot bf16-truncates f32 VALUES — measured max
    error 2.0 on node ids <= 1001 at lhat=1024/2048 — and Mosaic's
    in-kernel dot, exact through lhat=1024 (the n=502 round-4
    bit-check), corrupts ids at lhat=2048 too. Pure selects are exact
    for every dtype on every backend, and integer arrays skip the
    f32 round-trip entirely."""
    iota = jax.lax.broadcasted_iota(jnp.int32, (lhat, 1), 0)
    out = x
    bit = 1
    while bit < lhat:
        up = _roll_up_static(out, bit)
        down = _roll_up_static(out, lhat - bit)
        out = jnp.where((iota & bit) != 0, down, up)
        bit <<= 1
    return out


def _roll_up_perlane(x, rho_row, lhat):
    """out[k, b] = x[(k + rho_b) mod lhat, b] — per-LANE dynamic sublane
    roll as ceil(log2(lhat)) masked static rolls (binary decomposition
    of rho). rho_row: (1, T) int32 in [0, lhat)."""
    out = x
    bit = 1
    while bit < lhat:
        take = (rho_row & bit) != 0  # (1, T) broadcast over sublanes
        out = jnp.where(take, _roll_up_static(out, bit & (lhat - 1)), out)
        bit <<= 1
    return out


def _value_at(gt, pos_row, iota_l):
    """(1, T) value of each lane's tour at its own position pos_b —
    one-hot sublane reduction (no gather)."""
    sel = iota_l == pos_row
    return jnp.sum(jnp.where(sel, gt, 0), axis=0, keepdims=True)


def _pair_lookup(d, u_rows, v_rows, nhat):
    """d[u_k, v_k] for K (1, T) node-row pairs -> list of (1, T).

    One (T, N-hat) one-hot matmul per pair selects the row vector on the
    MXU, then the v one-hot contracts it on the VPU. Pairs are processed
    one at a time — a stacked (K*T, N-hat) formulation was measured no
    faster and its concat buffers cost the VMEM that larger chain tiles
    need."""
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (u_rows[0].shape[1], nhat), 1)
    vals = []
    for u, v in zip(u_rows, v_rows):
        u_oh = (u.T == iota_n).astype(jnp.bfloat16)
        rows = jnp.dot(u_oh, d, preferred_element_type=jnp.float32)
        v_oh = (v.T == iota_n).astype(jnp.float32)
        vals.append(jnp.sum(rows * v_oh, axis=1, keepdims=True).T)
    return vals


def _prefix_sum_sublane(x, lhat):
    p = x
    k = 1
    while k < lhat:
        pad = jnp.zeros((k, x.shape[1]), x.dtype)
        p = p + jnp.concatenate([pad, p[: lhat - k]], axis=0)
        k *= 2
    return p


def _prefix_max_sublane(x, lhat):
    m = x
    k = 1
    while k < lhat:
        pad = jnp.full((k, x.shape[1]), _NEG_BIG, x.dtype)
        m = jnp.maximum(m, jnp.concatenate([pad, m[: lhat - k]], axis=0))
        k *= 2
    return m


def _cap_excess_of(cand, dp_cand, cap0, lhat):
    """Total capacity excess per lane of the candidate tours — the
    segmented max-scan trick from sa_eval.eval_tours_homog, single-shot:
    contributions land at route-closing depot zeros; pad rows are depot
    zeros closing empty routes, so they contribute nothing."""
    z = cand == 0
    cum = _prefix_sum_sublane(dp_cand, lhat)
    m = jnp.where(z, cum, _NEG_BIG)
    m = _prefix_max_sublane(m, lhat)
    last_close = jnp.concatenate(
        [jnp.full((1, cand.shape[1]), _NEG_BIG, m.dtype), m[: lhat - 1]], axis=0
    )
    last_close = jnp.maximum(last_close, 0.0)  # floor: nothing before row 0
    contrib = jnp.where(z, jnp.maximum(cum - last_close - cap0, 0.0), 0.0)
    return jnp.sum(contrib, axis=0, keepdims=True)


def _delta_step_kernel(
    gt_ref, dp_ref, dist_ref, cape_ref, best_ref, bestc_ref,
    i_ref, r_ref, mt_ref, m_ref, u_ref,
    d_ref, knn_ref, scal_ref,
    gt_out, dp_out, dist_out, cape_out, best_out, bestc_out,
    *, length, has_knn,
):
    """Single-step variant (the block kernel is the production path;
    this one exists for tests and for callers that need per-step host
    control). Same math via the shared _step_body."""
    lhat, t = gt_ref.shape
    nhat = d_ref.shape[0]
    temp = scal_ref[0, 0]
    cap0 = scal_ref[0, 1]
    wcap = scal_ref[0, 2]
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (lhat, t), 0)
    out = _step_body(
        gt_ref[:], dp_ref[:], dist_ref[:], cape_ref[:],
        best_ref[:], bestc_ref[:],
        i_ref[:], r_ref[:], mt_ref[:], m_ref[:], u_ref[:], temp,
        d_ref[:], knn_ref[:], cap0, wcap, iota_l,
        length=length, lhat=lhat, t=t, nhat=nhat, has_knn=has_knn,
    )
    gt_out[:], dp_out[:], dist_out[:], cape_out[:], best_out[:], bestc_out[:] = out


def _value_at_f(arr, pos_row, iota_l):
    sel = iota_l == pos_row
    return jnp.sum(jnp.where(sel, arr, 0.0), axis=0, keepdims=True)


def _step_body(
    gt, dp, dist, cape, best, bestc,
    i_row, r_row, mt_row, m_row, u_row, temp,
    d, knn, cap0, wcap, iota_l, *, length, lhat, t, nhat, has_knn,
):
    """The delta-step math on VALUE arrays — shared verbatim by the
    one-step kernel (scan path) and the in-kernel block loop."""
    # --- proposal decode: second endpoint -------------------------------
    if has_knn:
        a_for_knn = _value_at(gt, i_row, iota_l)
        iota_n = jax.lax.broadcasted_iota(jnp.int32, (t, nhat), 1)
        a_oh = (a_for_knn.T == iota_n).astype(jnp.bfloat16)
        rows = jnp.dot(a_oh, knn, preferred_element_type=jnp.float32)
        kw = knn.shape[1]
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (t, kw), 1)
        r_oh = (r_row.T == iota_k).astype(jnp.float32)
        bnode = jnp.sum(rows * r_oh, axis=1, keepdims=True)
        bnode = bnode.astype(jnp.int32).T
        match = gt == bnode
        j_row = jnp.min(jnp.where(match, iota_l, lhat), axis=0, keepdims=True)
    else:
        j_row = r_row
    j_row = jnp.clip(j_row, 1, length - 2)

    lo = jnp.minimum(i_row, j_row)
    hi = jnp.maximum(i_row, j_row)
    span = hi - lo + 1
    mm = jnp.minimum(m_row, span - 1)
    mt = mt_row

    a_ = _value_at(gt, lo - 1, iota_l)
    b0 = _value_at(gt, lo, iota_l)
    x2 = _value_at(gt, lo + 1, iota_l)
    b1 = _value_at(gt, lo + mm - 1, iota_l)
    x_ = _value_at(gt, lo + mm, iota_l)
    y2 = _value_at(gt, hi - 1, iota_l)
    c_ = _value_at(gt, hi, iota_l)
    e_ = _value_at(gt, hi + 1, iota_l)

    (
        d_ab, d_ce, d_ac, d_be, d_ax, d_cb, d_b1e, d_b1x,
        d_cx2, d_y2b, d_bx2, d_y2c,
    ) = _pair_lookup(
        d,
        [a_, c_, a_, b0, a_, c_, b1, b1, c_, y2, b0, y2],
        [b0, e_, c_, e_, x_, b0, e_, x_, x2, b0, x2, c_],
        nhat,
    )
    nontriv = hi > lo
    drev = jnp.where(nontriv, d_ac + d_be - d_ab - d_ce, 0.0)
    drot = jnp.where(
        (span >= 2) & (mm >= 1),
        d_ax + d_cb + d_b1e - d_ab - d_b1x - d_ce,
        0.0,
    )
    dswap_gen = d_ac + d_cx2 + d_y2b + d_be - d_ab - d_bx2 - d_y2c - d_ce
    dswap = jnp.where(hi == lo + 1, drev, jnp.where(nontriv, dswap_gen, 0.0))
    ddist = jnp.where(mt == 0, drev, jnp.where(mt == 1, drot, dswap))

    in_win = (iota_l >= lo) & (iota_l <= hi)
    mask = lhat - 1

    def apply_move(arr, flipped):
        rho_rev = (lhat - 1 - (lo + hi)) & mask
        rev = jnp.where(in_win, _roll_up_perlane(flipped, rho_rev, lhat), arr)
        fwd = _roll_up_perlane(arr, mm & mask, lhat)
        wrap = _roll_up_perlane(arr, (mm - span) & mask, lhat)
        rot = jnp.where(in_win, jnp.where(iota_l + mm <= hi, fwd, wrap), arr)
        return rev, rot

    gt_flip = _flip_sublanes(gt, lhat)
    dp_flip = _flip_sublanes(dp, lhat)
    gt_rev, gt_rot = apply_move(gt, gt_flip)
    dp_rev, dp_rot = apply_move(dp, dp_flip)
    dem_b0 = _value_at_f(dp, lo, iota_l)
    dem_c = _value_at_f(dp, hi, iota_l)
    gt_swp = jnp.where(iota_l == lo, c_, jnp.where(iota_l == hi, b0, gt))
    dp_swp = jnp.where(iota_l == lo, dem_c, jnp.where(iota_l == hi, dem_b0, dp))
    cand = jnp.where(mt == 0, gt_rev, jnp.where(mt == 1, gt_rot, gt_swp))
    dp_cand = jnp.where(mt == 0, dp_rev, jnp.where(mt == 1, dp_rot, dp_swp))

    cape_cand = _cap_excess_of(cand, dp_cand, cap0, lhat)
    new_dist = dist + ddist
    cur_cost = dist + wcap * cape
    cand_cost = new_dist + wcap * cape_cand
    delta = cand_cost - cur_cost
    accept = (delta < 0.0) | (u_row < jnp.exp(jnp.minimum(-delta / temp, 0.0)))
    gt_new = jnp.where(accept, cand, gt)
    dp_new = jnp.where(accept, dp_cand, dp)
    dist_new = jnp.where(accept, new_dist, dist)
    cape_new = jnp.where(accept, cape_cand, cape)
    committed = jnp.where(accept, cand_cost, cur_cost)
    better = committed < bestc
    best_new = jnp.where(better, gt_new, best)
    bestc_new = jnp.where(better, committed, bestc)
    return gt_new, dp_new, dist_new, cape_new, best_new, bestc_new


def _delta_block_kernel(
    gt_ref, dp_ref, dist_ref, cape_ref, best_ref, bestc_ref,
    i_ref, r_ref, mt_ref, m_ref, u_ref, temps_ref,
    d_ref, knn_ref, scal_ref,
    gt_out, dp_out, dist_out, cape_out, best_out, bestc_out,
    *, length, has_knn, n_steps,
):
    """n_steps fused delta steps with ALL state VMEM-resident — one
    kernel launch per block instead of per move (the per-step pallas
    dispatch plus HBM state round-trip was ~40% of the step at B=16k)."""
    lhat, t = gt_ref.shape
    nhat = d_ref.shape[0]
    d = d_ref[:]
    knn = knn_ref[:]
    cap0 = scal_ref[0, 0]
    wcap = scal_ref[0, 1]
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (lhat, t), 0)

    def body(k, carry):
        gt, dp, dist, cape, best, bestc = carry
        i_row = i_ref[pl.ds(k, 1), :]
        r_row = r_ref[pl.ds(k, 1), :]
        mt_row = mt_ref[pl.ds(k, 1), :]
        m_row = m_ref[pl.ds(k, 1), :]
        u_row = u_ref[pl.ds(k, 1), :]
        temp = temps_ref[0, k]
        return _step_body(
            gt, dp, dist, cape, best, bestc,
            i_row, r_row, mt_row, m_row, u_row, temp,
            d, knn, cap0, wcap, iota_l,
            length=length, lhat=lhat, t=t, nhat=nhat, has_knn=has_knn,
        )

    carry = (
        gt_ref[:], dp_ref[:], dist_ref[:], cape_ref[:],
        best_ref[:], bestc_ref[:],
    )
    gt, dp, dist, cape, best, bestc = jax.lax.fori_loop(
        0, n_steps, body, carry
    )
    gt_out[:] = gt
    dp_out[:] = dp
    dist_out[:] = dist
    cape_out[:] = cape
    best_out[:] = best
    bestc_out[:] = bestc


@functools.partial(
    jax.jit, static_argnames=("length", "tile_b", "has_knn", "interpret")
)
def delta_block(
    gt_t, dp_t, dist, cape, best_t, best_c,
    i, r, mt, m, u, temps, d_bf16, knn_f32, scal,
    *, length, tile_b, has_knn, interpret=False,
):
    """A whole block of fused delta steps in one kernel launch.

    i/r/mt/m/u: (n_steps, B); temps: (1, n_steps) f32 in SMEM; scal:
    (1, 2) f32 [cap0, wcap]. Other arguments as delta_step."""
    lhat, b = gt_t.shape
    n_steps = i.shape[0]
    grid = b // tile_b
    kernel = functools.partial(
        _delta_block_kernel, length=length, has_knn=has_knn, n_steps=n_steps
    )
    tall = pl.BlockSpec((lhat, tile_b), lambda g: (0, g))
    row = pl.BlockSpec((1, tile_b), lambda g: (0, g))
    steps = pl.BlockSpec((n_steps, tile_b), lambda g: (0, g))
    # At the n<=512 gate boundary (lhat = 1024) the block's state +
    # streams overshoot the default 16 MB scoped-vmem cap by ~1 MB;
    # v5e has 128 MiB physical VMEM, so raise the cap (same rationale
    # as sa_delta_tw.delta_tw_block — launches stay 512 steps).
    params = None if interpret else pltpu.CompilerParams(
        vmem_limit_bytes=100 * 1024 * 1024
    )
    out = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            tall, tall, row, row, tall, row,
            steps, steps, steps, steps, steps,
            pl.BlockSpec((1, n_steps), lambda g: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(d_bf16.shape, lambda g: (0, 0)),
            pl.BlockSpec(knn_f32.shape, lambda g: (0, 0)),
            pl.BlockSpec((1, 2), lambda g: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=[tall, tall, row, row, tall, row],
        out_shape=[
            jax.ShapeDtypeStruct((lhat, b), jnp.int32),
            jax.ShapeDtypeStruct((lhat, b), jnp.float32),
            jax.ShapeDtypeStruct((1, b), jnp.float32),
            jax.ShapeDtypeStruct((1, b), jnp.float32),
            jax.ShapeDtypeStruct((lhat, b), jnp.int32),
            jax.ShapeDtypeStruct((1, b), jnp.float32),
        ],
        compiler_params=params,
        interpret=interpret,
    )(gt_t, dp_t, dist, cape, best_t, best_c, i, r, mt, m, u, temps,
      d_bf16, knn_f32, scal)
    return out


def _dp_init_kernel(gt_ref, dem_ref, dp_out, *, exact_f32):
    """dp[k, b] = demands[gt[k, b]] — per-position one-hot matvecs
    against the demand vector (VMEM-resident; no gather).

    A fori_loop, NOT a Python unroll: unrolled, the 2048 per-row
    matmuls at the n=1024 gate boundary kept every row's temporaries
    live and the register allocator spilled 174 MB of scoped VMEM
    (round-5 hardware failure at lhat=2048); the loop body reuses one
    row's worth."""
    lhat, t = gt_ref.shape
    nhat = dem_ref.shape[1]
    dem_col = dem_ref[:].T  # (N-hat, 1)
    dt = jnp.float32 if exact_f32 else jnp.bfloat16
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (t, nhat), 1)

    def body(k, _):
        oh = (gt_ref[pl.ds(k, 1), :].T == iota_n).astype(dt)
        val = jnp.dot(oh, dem_col.astype(dt),
                      preferred_element_type=jnp.float32)  # (T, 1)
        dp_out[pl.ds(k, 1), :] = val.T
        return 0

    jax.lax.fori_loop(0, lhat, body, 0)


@functools.partial(jax.jit, static_argnames=("tile_b", "exact_f32", "interpret"))
def dp_init(gt_t, dem_row, *, tile_b, exact_f32=False, interpret=False):
    """(L-hat, B) tours -> (L-hat, B) per-position attribute values, on
    device (dem_row holds demands for the capacity state; the TW path
    reuses it for service/ready/due).

    Exists because both XLA alternatives are terrible at B=16k: the
    (B, L, N) one-hot einsum moves ~2 GB of intermediates, and a host
    fancy-index round-trips the whole state through the TPU tunnel.
    The bf16 default is exact as long as the values are integers <= 256
    (callers gate demands via demand_scale); exact_f32 runs the matvec
    in f32 for arbitrary attribute values (TW ready/due) at init-only
    cost.
    """
    lhat, b = gt_t.shape
    return pl.pallas_call(
        functools.partial(_dp_init_kernel, exact_f32=exact_f32),
        grid=(b // tile_b,),
        in_specs=[
            pl.BlockSpec((lhat, tile_b), lambda g: (0, g)),
            pl.BlockSpec(dem_row.shape, lambda g: (0, 0)),
        ],
        out_specs=pl.BlockSpec((lhat, tile_b), lambda g: (0, g)),
        out_shape=jax.ShapeDtypeStruct((lhat, b), jnp.float32),
        interpret=interpret,
    )(gt_t, dem_row)


@functools.partial(
    jax.jit, static_argnames=("length", "tile_b", "has_knn", "interpret")
)
def delta_step(
    gt_t, dp_t, dist, cape, best_t, best_c,
    i, r, mt, m, u, d_bf16, knn_f32, scal,
    *, length, tile_b, has_knn, interpret=False,
):
    """One fused SA step over all chains, best tracking included.

    gt_t/dp_t/best_t: (L-hat, B) i32/f32/i32 transposed tour, demand and
    best-so-far state; dist/cape/best_c/i/r/mt/m/u: (1, B); d_bf16:
    (N-hat, N-hat) bf16; knn_f32: (N-hat, K) f32 (ignored when
    has_knn=False — pass a dummy); scal: (1, 3) f32 [temp, cap0, wcap].
    Returns the committed (gt_t, dp_t, dist, cape, best_t, best_c).
    """
    lhat, b = gt_t.shape
    grid = b // tile_b
    kernel = functools.partial(
        _delta_step_kernel, length=length, has_knn=has_knn
    )
    tall = pl.BlockSpec((lhat, tile_b), lambda g: (0, g))
    row = pl.BlockSpec((1, tile_b), lambda g: (0, g))
    out = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            tall, tall, row, row, tall, row,
            row, row, row, row, row,
            pl.BlockSpec(d_bf16.shape, lambda g: (0, 0)),
            pl.BlockSpec(knn_f32.shape, lambda g: (0, 0)),
            pl.BlockSpec((1, 3), lambda g: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=[tall, tall, row, row, tall, row],
        out_shape=[
            jax.ShapeDtypeStruct((lhat, b), jnp.int32),
            jax.ShapeDtypeStruct((lhat, b), jnp.float32),
            jax.ShapeDtypeStruct((1, b), jnp.float32),
            jax.ShapeDtypeStruct((1, b), jnp.float32),
            jax.ShapeDtypeStruct((lhat, b), jnp.int32),
            jax.ShapeDtypeStruct((1, b), jnp.float32),
        ],
        interpret=interpret,
    )(gt_t, dp_t, dist, cape, best_t, best_c, i, r, mt, m, u, d_bf16, knn_f32, scal)
    return out
