"""Pallas TPU kernel: fused delta-evaluated SA steps for TIME-DEPENDENT
durations (the reference's `time_of_day` contract, src/solver.py:7;
`startTimes`, api/parameters.py:12).

VERDICT round-4 item 6: the delta fast path excluded the TD class — the
one the service contract most directly names — because a leg's travel
time depends on its departure time, and the departure times form a
sequential recurrence with no associative reformulation (core.cost.
_td_hot_batch's scan). A per-move in-kernel timeline would serialize
~L sublane steps per step and forfeit the delta path's whole advantage.

The design here keeps every per-move computation vectorized by
splitting the objective into an exact part and a POSITION-FROZEN
surrogate part, resynced at launch boundaries:

  * with the exact rank-R factorization durations[t] = sum_r
    factors[r, t] * basis[r] (Instance.td_rank, detected at build), a
    leg's travel is  sum_r f[r, s_k] * basis[r][u, v]  where s_k is the
    departure-time slice at position k;
  * the R per-position BASIS-leg arrays lgr[r][k] = basis[r][g[k],
    g[k+1]] are maintained EXACTLY under moves — the same sublane-roll
    machinery + O(1) junction fixes as the TW kernel's leg array, with
    the pair lookups riding one stacked one-hot matmul against the
    (N-hat, R*N-hat) lane-concatenation of the basis tables;
  * the per-position factor weights fw[r][k] = factors[r, s_k] are
    FROZEN at their last-resync values and enter the kernel as
    constants: the surrogate distance is sum_k sum_r fw[r][k] *
    lgr[r][k] — one elementwise product + column-sum per move, no
    sequential anything. (Position-frozen beats leg-frozen: a leg moved
    from late to early in the tour should be priced at the early
    departure profile, which is exactly what freezing BY POSITION does.)
  * every <= 512-step launch boundary, the driver recomputes the TRUE
    timeline of the committed tours (one lax.scan over positions in
    XLA — amortized 1/512 of a full evaluation per move), refreshes fw,
    and re-prices the committed cost row in the fresh surrogate basis;
    the final champion/elite ranking is EXACT via the one-hot TD path.

  The surrogate's only approximation is acceptance noise: between
  resyncs a move is priced at slices up to 512 steps stale. Capacity
  excess stays exact (same machinery as the untimed kernel), tours/
  demands/basis-legs re-derive exactly from the final state (pinned by
  tests), and the reported result is exactly priced by construction.

Gates (sa._delta_supported): factorized TD (td_rank in 1..2), every
slice symmetric (reverse reuses interior basis legs), no TW, no
makespan, uniform fleet + scalable demands, and n_nodes <= 512 — a
TD-SPECIFIC bound, tighter than the shared delta-path n <= 1024: the
untimed kernel was bit-checked on hardware at n=1001 when the shared
bound was raised in round 5, but this surrogate path has only ever
been validated to 512, so the 512-1024 range stays gated off until a
coverage point exists there (ADVICE round 5; the driver also scales
its chain tile down with both padded length and rank to respect the
scoped-VMEM cap). Ids stay in one bf16-exact range. Start times may
vary per vehicle (they only enter the RESYNC timeline, which is exact
XLA).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from vrpms_tpu.kernels.sa_delta import (
    _flip_sublanes,
    _PALLAS_OK,
    _cap_excess_of,
    _roll_up_perlane,
    _value_at,
    _value_at_f,
)
from vrpms_tpu.kernels.sa_delta_tw import _values_at_stacked

if _PALLAS_OK:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu


def _pair_lookup_stacked_cat(d_cat, rr, u_rows, v_rows, nhat):
    """basis_r[u_k, v_k] for K pairs x R basis tables, via ONE stacked
    one-hot matmul against the (N-hat, R*N-hat) lane-concat of the
    tables -> list of R lists of (1, T).

    rows = onehot(u) @ d_cat is (K*T, R*N-hat): section r holds
    basis_r[u, :]; the v selection repeats per section."""
    k = len(u_rows)
    t = u_rows[0].shape[1]
    u_stack = jnp.concatenate([u.T for u in u_rows], axis=0)  # (K*T, 1)
    v_stack = jnp.concatenate([v.T for v in v_rows], axis=0)
    iota_n = jax.lax.broadcasted_iota(jnp.int32, (k * t, nhat), 1)
    u_oh = (u_stack == iota_n).astype(jnp.bfloat16)
    rows = jnp.dot(u_oh, d_cat, preferred_element_type=jnp.float32)
    v_oh = (v_stack == iota_n).astype(jnp.float32)
    out = []
    for r in range(rr):
        vals = jnp.sum(
            rows[:, r * nhat : (r + 1) * nhat] * v_oh, axis=1, keepdims=True
        )
        out.append([vals[j * t : (j + 1) * t].T for j in range(k)])
    return out


def _td_step_body(
    gt, dp, lgr, cost, best, bestc,
    i_row, r_row, mt_row, m_row, u_row, temp,
    d_cat, knn, fw, cap0, wcap, iota_l,
    *, length, lhat, t, nhat, rr, has_knn,
):
    """One fused TD delta step on VALUE arrays. `lgr` is the lane-axis
    concatenation of the R basis-leg arrays ((L-hat, R*T)); `fw` the
    matching FROZEN factor-weight concat (constant within a launch).
    Proposal decode is identical to sa_delta._step_body."""
    if has_knn:
        a_for_knn = _value_at(gt, i_row, iota_l)
        iota_n = jax.lax.broadcasted_iota(jnp.int32, (t, nhat), 1)
        a_oh = (a_for_knn.T == iota_n).astype(jnp.bfloat16)
        rows = jnp.dot(a_oh, knn, preferred_element_type=jnp.float32)
        kw = knn.shape[1]
        iota_k = jax.lax.broadcasted_iota(jnp.int32, (t, kw), 1)
        r_oh = (r_row.T == iota_k).astype(jnp.float32)
        bnode = jnp.sum(rows * r_oh, axis=1, keepdims=True)
        bnode = bnode.astype(jnp.int32).T
        match = gt == bnode
        j_row = jnp.min(jnp.where(match, iota_l, lhat), axis=0, keepdims=True)
    else:
        j_row = r_row
    j_row = jnp.clip(j_row, 1, length - 2)

    lo = jnp.minimum(i_row, j_row)
    hi = jnp.maximum(i_row, j_row)
    span = hi - lo + 1
    mm = jnp.minimum(m_row, span - 1)
    mt = mt_row

    a_, b0, x2, b1, x_, y2, c_, e_ = _values_at_stacked(
        gt,
        [lo - 1, lo, lo + 1, lo + mm - 1, lo + mm, hi - 1, hi, hi + 1],
        iota_l,
    )

    # 7 junction pairs x R basis tables, one stacked matmul
    per_r = _pair_lookup_stacked_cat(
        d_cat, rr,
        [a_, b0, a_, c_, b1, c_, y2],
        [c_, e_, x_, b0, e_, x2, b0],
        nhat,
    )

    in_win = (iota_l >= lo) & (iota_l <= hi)
    mask = lhat - 1

    def apply_move(arr, flipped, lo_, hi_, mm_, span_, in_win_, iota_):
        rho_rev = (lhat - 1 - (lo_ + hi_)) & mask
        rev = jnp.where(in_win_, _roll_up_perlane(flipped, rho_rev, lhat), arr)
        fwd = _roll_up_perlane(arr, mm_ & mask, lhat)
        wrap = _roll_up_perlane(arr, (mm_ - span_) & mask, lhat)
        rot = jnp.where(
            in_win_, jnp.where(iota_ + mm_ <= hi_, fwd, wrap), arr
        )
        return rev, rot

    def flip(arr):
        # exact sublane reversal (sa_delta._flip_sublanes): the MXU
        # antidiagonal flip truncates values > 256 at large lhat
        return _flip_sublanes(arr, lhat)

    def moved(arr, lo_, hi_, mm_, span_, mt_, in_win_, iota_, is_int=False):
        flipped = flip(arr)
        if is_int:
            flipped = flipped.astype(jnp.int32)
        rev, rot = apply_move(arr, flipped, lo_, hi_, mm_, span_, in_win_, iota_)
        at_lo = (
            _value_at(arr, lo_, iota_) if is_int else _value_at_f(arr, lo_, iota_)
        )
        at_hi = (
            _value_at(arr, hi_, iota_) if is_int else _value_at_f(arr, hi_, iota_)
        )
        swp = jnp.where(
            iota_ == lo_, at_hi, jnp.where(iota_ == hi_, at_lo, arr)
        )
        return jnp.where(mt_ == 0, rev, jnp.where(mt_ == 1, rot, swp))

    cand = moved(gt, lo, hi, mm, span, mt, in_win, iota_l, is_int=True)
    dp_c = moved(dp, lo, hi, mm, span, mt, in_win, iota_l)

    # basis-leg arrays: same rolls with the window one row shorter (the
    # TW kernel's leg machinery, replicated across the R lane sections),
    # then the per-r junction fixes
    repr_ = lambda x: jnp.concatenate([x] * rr, axis=1)  # noqa: E731
    lo_r, hi_r = repr_(lo), repr_(hi)
    mm_r, span_r, mt_r = repr_(mm), repr_(span), repr_(mt)
    iota_lr = repr_(iota_l)
    in_win_lg = (iota_lr >= lo_r) & (iota_lr <= hi_r - 1)
    lg_rev, lg_rot = apply_move(
        lgr, flip(lgr), lo_r, hi_r - 1, mm_r, span_r, in_win_lg, iota_lr
    )
    lgr_c = jnp.where(mt_r == 0, lg_rev, jnp.where(mt_r == 1, lg_rot, lgr))
    rot_valid = (mt == 1) & (span >= 2) & (mm >= 1)
    swap_gen = mt == 2
    fixed = []
    for r in range(rr):
        (d_ac, d_be, d_ax, d_cb, d_b1e, d_cx2, d_y2b) = per_r[r]
        lg_c = lgr_c[:, r * t : (r + 1) * t]
        fix_lo1 = jnp.where(rot_valid, d_ax, d_ac)
        fix_hi = jnp.where(rot_valid, d_b1e, d_be)
        lg_c = jnp.where(iota_l == lo - 1, fix_lo1, lg_c)
        lg_c = jnp.where(iota_l == hi, fix_hi, lg_c)
        lg_c = jnp.where(rot_valid & (iota_l == hi - mm), d_cb, lg_c)
        lg_c = jnp.where(swap_gen & (iota_l == lo), d_cx2, lg_c)
        lg_c = jnp.where(swap_gen & (iota_l == hi - 1), d_y2b, lg_c)
        # adjacent swap IS the reverse: one junction leg at lo
        lg_c = jnp.where(
            swap_gen & (hi == lo + 1) & (iota_l == lo), d_cb, lg_c
        )
        fixed.append(lg_c)
    lgr_c = jnp.concatenate(fixed, axis=1)

    # surrogate distance: frozen factor weights x exact basis legs,
    # summed over positions then over ranks
    dist_c = jnp.sum(fw * lgr_c, axis=0, keepdims=True)  # (1, rr*t)
    if rr > 1:
        dist_c = sum(dist_c[:, r * t : (r + 1) * t] for r in range(rr))
    cape_c = _cap_excess_of(cand, dp_c, cap0, lhat)
    cand_cost = dist_c + wcap * cape_c
    delta = cand_cost - cost
    accept = (delta < 0.0) | (u_row < jnp.exp(jnp.minimum(-delta / temp, 0.0)))

    gt_n = jnp.where(accept, cand, gt)
    dp_n = jnp.where(accept, dp_c, dp)
    lgr_n = jnp.where(repr_(accept), lgr_c, lgr)
    cost_n = jnp.where(accept, cand_cost, cost)
    better = cost_n < bestc
    best_n = jnp.where(better, gt_n, best)
    bestc_n = jnp.where(better, cost_n, bestc)
    return gt_n, dp_n, lgr_n, cost_n, best_n, bestc_n


def _td_block_kernel(
    gt_ref, dp_ref, lgr_ref, cost_ref, best_ref, bestc_ref,
    i_ref, r_ref, mt_ref, m_ref, u_ref, temps_ref,
    dcat_ref, knn_ref, fw_ref, scal_ref,
    gt_o, dp_o, lgr_o, cost_o, best_o, bestc_o,
    *, length, rr, has_knn, n_steps,
):
    """n_steps fused TD delta steps, all state VMEM-resident."""
    lhat, t_r = gt_ref.shape
    t = t_r  # gt is (lhat, tile); lgr/fw are (lhat, rr*tile)
    nhat = dcat_ref.shape[0]
    d_cat = dcat_ref[:]
    knn = knn_ref[:]
    fw = fw_ref[:]
    cap0 = scal_ref[0, 0]
    wcap = scal_ref[0, 1]
    iota_l = jax.lax.broadcasted_iota(jnp.int32, (lhat, t), 0)

    def body(k, carry):
        gt, dp, lgr, cost, best, bestc = carry
        return _td_step_body(
            gt, dp, lgr, cost, best, bestc,
            i_ref[pl.ds(k, 1), :], r_ref[pl.ds(k, 1), :],
            mt_ref[pl.ds(k, 1), :], m_ref[pl.ds(k, 1), :],
            u_ref[pl.ds(k, 1), :], temps_ref[0, k],
            d_cat, knn, fw, cap0, wcap, iota_l,
            length=length, lhat=lhat, t=t, nhat=nhat, rr=rr,
            has_knn=has_knn,
        )

    carry = (
        gt_ref[:], dp_ref[:], lgr_ref[:], cost_ref[:], best_ref[:],
        bestc_ref[:],
    )
    gt, dp, lgr, cost, best, bestc = jax.lax.fori_loop(
        0, n_steps, body, carry
    )
    gt_o[:] = gt
    dp_o[:] = dp
    lgr_o[:] = lgr
    cost_o[:] = cost
    best_o[:] = best
    bestc_o[:] = bestc


@functools.partial(
    jax.jit, static_argnames=("length", "rr", "tile_b", "has_knn", "interpret")
)
def delta_td_block(
    gt_t, dp_t, lgr_t, cost, best_t, best_c,
    i, r, mt, m, u, temps, d_cat_bf16, knn_f32, fw_t, scal,
    *, length, rr, tile_b, has_knn, interpret=False,
):
    """A whole block of fused TD delta steps in one kernel launch.

    State: gt/dp/best_t are (L-hat, B); lgr_t and fw_t are (L-hat, R*B)
    lane-concats (section r = basis-leg values / frozen factor weights
    of rank r); cost/best_c are (1, B). d_cat_bf16 is the (N-hat,
    R*N-hat) basis-table concat; scal (1, 2) SMEM [cap0_scaled,
    wcap*g].
    """
    lhat, b = gt_t.shape
    n_steps = i.shape[0]
    grid = b // tile_b
    kernel = functools.partial(
        _td_block_kernel, length=length, rr=rr, has_knn=has_knn,
        n_steps=n_steps,
    )
    tall = pl.BlockSpec((lhat, tile_b), lambda g: (0, g))
    # lgr/fw tiles: R sections of tile_b lanes each, gathered from the
    # section-strided (L-hat, R*B) layout — index mapping picks section
    # offsets per grid step, so the R sections of one chain tile are
    # contiguous in the block
    tall_r = pl.BlockSpec(
        (lhat, rr * tile_b), lambda g: (0, g)
    )
    row = pl.BlockSpec((1, tile_b), lambda g: (0, g))
    steps = pl.BlockSpec((n_steps, tile_b), lambda g: (0, g))
    tall_i32 = jax.ShapeDtypeStruct((lhat, b), jnp.int32)
    tall_f32 = jax.ShapeDtypeStruct((lhat, b), jnp.float32)
    tall_f32_r = jax.ShapeDtypeStruct((lhat, rr * b), jnp.float32)
    row_f32 = jax.ShapeDtypeStruct((1, b), jnp.float32)
    params = None
    if not interpret:
        params = pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            tall, tall, tall_r, row, tall, row,
            steps, steps, steps, steps, steps,
            pl.BlockSpec((1, n_steps), lambda g: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec(d_cat_bf16.shape, lambda g: (0, 0)),
            pl.BlockSpec(knn_f32.shape, lambda g: (0, 0)),
            tall_r,
            pl.BlockSpec((1, 2), lambda g: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=[tall, tall, tall_r, row, tall, row],
        out_shape=[
            tall_i32, tall_f32, tall_f32_r, row_f32, tall_i32, row_f32,
        ],
        compiler_params=params,
        interpret=interpret,
    )(gt_t, dp_t, lgr_t, cost, best_t, best_c,
      i, r, mt, m, u, temps, d_cat_bf16, knn_f32, fw_t, scal)


def td_step(
    gt_t, dp_t, lgr_t, cost, best_t, best_c,
    i, r, mt, m, u, temp, d_cat_bf16, knn_f32, fw_t, scal,
    *, length, rr, tile_b, has_knn, interpret=False,
):
    """Single-step convenience wrapper over delta_td_block (tests)."""
    temps = jnp.asarray([[temp]], jnp.float32)
    return delta_td_block(
        gt_t, dp_t, lgr_t, cost, best_t, best_c,
        i[None], r[None], mt[None], m[None], u[None], temps,
        d_cat_bf16, knn_f32, fw_t, scal,
        length=length, rr=rr, tile_b=tile_b, has_knn=has_knn,
        interpret=interpret,
    )
