"""Pallas TPU kernel: fused giant-tour objective (distance + capacity).

The XLA one-hot path (core.cost.objective_hot_batch) is HBM-bound: the
(B, L, N) one-hot and X = P @ D intermediates round-trip ~0.8 GB per
sweep at B=4096. This kernel keeps the whole evaluation in VMEM per
batch-tile: build the position one-hot, run the leg-selection matmul on
the MXU, contract against the next-position one-hot, and reduce per-route
loads — nothing but the (B, L) tours and the (B,) costs touch HBM.

Semantics match objective_hot_batch's fast path exactly (same bf16
selection argument: one-hot contractions select single elements, so the
only rounding is the durations matrix itself in bf16). Untimed instances
only; callers fall back to the XLA paths otherwise (see
core.cost.resolve_eval_mode).

Layout: tours are processed TRANSPOSED — work arrays are (L̂, TILE_B)
with chains on the 128-lane minor axis — and padded: L̂/N̂ round L/N up
to the MXU-friendly 128 multiple. Padding is semantically free: pad
positions hold depot zeros (D[0,0] == 0, demands[0] == 0) and pad nodes
are never selected by a one-hot.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.cost import CostWeights

try:  # pallas imports fail on some CPU-only builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


def pallas_available() -> bool:
    return _PALLAS_OK


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _eval_kernel(gt_ref, d_ref, dem_ref, cap_ref, wcap_ref, cost_ref, *, n_vehicles):
    """One batch-tile: gt (L̂, TILE_B) transposed tours -> cost (1, TILE_B)."""
    lhat = gt_ref.shape[0]
    tile_b = gt_ref.shape[1]
    nhat = d_ref.shape[0]
    gt = gt_ref[:]  # (L̂, TILE_B) int32

    # One-hot over nodes in flat (l, b) ordering: row p = l * TILE_B + b.
    flat = gt.reshape(lhat * tile_b, 1)
    node_iota = jax.lax.broadcasted_iota(jnp.int32, (lhat * tile_b, nhat), 1)
    p_all = (flat == node_iota).astype(jnp.bfloat16)  # (L̂*T, N̂)

    # X[p, m] = D[node(p), m] — exact bf16 row selection on the MXU.
    x_all = jnp.dot(p_all, d_ref[:], preferred_element_type=jnp.bfloat16)

    # legs[p] = D[node(p), node(p + one position)] ; +1 position == +TILE_B
    # rows in (l, b) ordering. Pad legs are depot self-loops (cost 0).
    prod = x_all[: (lhat - 1) * tile_b] * p_all[tile_b:]
    legs = jnp.sum(prod.astype(jnp.float32), axis=1)  # ((L̂-1)*T,)
    dist = jnp.sum(legs.reshape(lhat - 1, tile_b), axis=0)  # (TILE_B,)

    # Per-position demand: nd[p] = demands[node(p)] (f32 matvec).
    nd = jnp.dot(
        p_all.astype(jnp.float32), dem_ref[:].reshape(nhat, 1),
        preferred_element_type=jnp.float32,
    ).reshape(lhat, tile_b)

    # rid[l] = (# zeros at positions <= l) - 1 via a triangular MXU matmul
    # (counts are small integers — exact in bf16 up to 256).
    is_zero = (gt == 0).astype(jnp.bfloat16)  # (L̂, T)
    row_i = jax.lax.broadcasted_iota(jnp.int32, (lhat, lhat), 0)
    col_i = jax.lax.broadcasted_iota(jnp.int32, (lhat, lhat), 1)
    tri = (col_i <= row_i).astype(jnp.bfloat16)
    rid = (
        jnp.dot(tri, is_zero, preferred_element_type=jnp.float32)
        .astype(jnp.int32)
        - 1
    )  # (L̂, T); pad rows exceed V-1 and drop out of every load below

    # Loads: route v's demand, excess past its capacity.
    def body(v, excess):
        mask = rid == v
        load = jnp.sum(jnp.where(mask, nd, 0.0), axis=0)  # (TILE_B,)
        return excess + jnp.maximum(load - cap_ref[0, v], 0.0)

    excess = jax.lax.fori_loop(
        0, n_vehicles, body, jnp.zeros((tile_b,), jnp.float32)
    )
    cost_ref[0, :] = dist + wcap_ref[0, 0] * excess


def _pad_static(inst: Instance):
    n = inst.n_nodes
    nhat = _round_up(n, 128)
    d = jnp.zeros((nhat, nhat), jnp.bfloat16).at[:n, :n].set(
        inst.durations[0].astype(jnp.bfloat16)
    )
    dem = jnp.zeros((nhat,), jnp.float32).at[:n].set(inst.demands)
    vhat = _round_up(inst.n_vehicles, 8)
    cap = jnp.full((1, vhat), 1e18, jnp.float32).at[0, : inst.n_vehicles].set(
        inst.capacities
    )
    return d, dem, cap


@functools.partial(jax.jit, static_argnames=("tile_b", "n_vehicles", "interpret"))
def _run(giants_t, d, dem, cap, wcap, *, tile_b, n_vehicles, interpret=False):
    lhat, b = giants_t.shape
    grid = b // tile_b
    cost = pl.pallas_call(
        functools.partial(_eval_kernel, n_vehicles=n_vehicles),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((lhat, tile_b), lambda i: (0, i)),
            pl.BlockSpec(d.shape, lambda i: (0, 0)),
            pl.BlockSpec(dem.shape, lambda i: (0,)),
            pl.BlockSpec(cap.shape, lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, tile_b), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, b), jnp.float32),
        interpret=interpret,
    )(giants_t, d, dem, cap, wcap)
    return cost[0]


def pallas_objective_batch(
    giants: jax.Array,
    inst: Instance,
    w: CostWeights,
    tile_b: int = 32,
    transposed: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Fused-TPU batched objective; drop-in for objective_hot_batch.

    giants: (B, L) int32 — or (L, B) with transposed=True to skip the
    relayout when the caller keeps SA state in kernel layout. B must be
    a multiple of tile_b (solvers size their chain batches accordingly).
    """
    if not _PALLAS_OK:
        raise RuntimeError("pallas unavailable in this environment")
    if inst.has_tw or inst.time_dependent:
        raise ValueError("pallas objective covers the untimed fast path only")
    gt = giants if transposed else giants.T
    lhat = _round_up(gt.shape[0], 8)
    if gt.shape[1] % tile_b:
        raise ValueError(f"batch {gt.shape[1]} not a multiple of tile_b {tile_b}")
    gt = jnp.pad(gt, ((0, lhat - gt.shape[0]), (0, 0)))
    d, dem, cap = _pad_static(inst)
    wcap = jnp.asarray(w.cap, jnp.float32).reshape(1, 1)
    return _run(
        gt, d, dem, cap, wcap,
        tile_b=tile_b, n_vehicles=inst.n_vehicles, interpret=interpret,
    )
