"""Pallas TPU kernel: fused giant-tour objective (distance + capacity).

The XLA one-hot path (core.cost.objective_hot_batch) is HBM-bound: the
(B, L, N) one-hot and X = P @ D intermediates round-trip ~0.5 GB per
sweep at B=4096 because XLA never fuses through a dot. This kernel keeps
the whole evaluation in VMEM per batch-tile: it walks the tour in
position *chunks*, building only a (CHUNK*TILE_B, N̂) one-hot at a time,
runs the leg-selection matmul on the MXU, contracts against the
next-position one-hot, and reduces per-route loads — nothing but the
(L, B) tours and the (B,) costs touch HBM.

Semantics match objective_hot_batch's fast path (same bf16 selection
argument: one-hot contractions select single elements, so the only
rounding is the durations matrix itself in bf16). Untimed instances
only; callers fall back to the XLA paths otherwise (see
core.cost.resolve_eval_mode).

Mosaic constraints that shaped the code (probed on v5e, jax 0.9):
  * cross-layout reshapes — (C, T) -> (C*T, 1) flattens and their
    inverses — do not lower; 2-D transposes DO. One-hots are therefore
    built per position from a transposed chunk column and stacked with
    `jnp.concatenate` along sublanes, never reshaped.
  * matmul accumulators must be 32-bit (bf16 inputs are fine).
  * `jnp.take_along_axis(tab, idx, axis=0)` advertises a
    `tpu.dynamic_gather` lowering when tab/idx/out share one 2-D shape,
    but this environment's Mosaic backend crashes compiling it — so no
    in-kernel table lookups (demands ride in a column of D instead) and
    the SA move-apply stays an XLA one-hot einsum outside the kernel.

Layout: tours are processed TRANSPOSED — work arrays are (L̂, TILE_B)
with chains on the 128-lane minor axis — and padded: L̂ rounds L up to a
chunk multiple plus one trailing all-depot chunk (so the "next node" read
never overflows), N̂ rounds N up to the MXU-friendly 128 multiple.
Padding is semantically free: pad positions hold depot zeros (D[0,0] ==
0, demands[0] == 0, and pad rows accumulate route ids past V-1 so the
capacity loop never sees them) and pad nodes are never selected by a
one-hot.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from vrpms_tpu.core.instance import Instance
from vrpms_tpu.core.cost import CostWeights

try:  # pallas imports fail on some CPU-only builds
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # pragma: no cover
    _PALLAS_OK = False


def pallas_available() -> bool:
    return _PALLAS_OK


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _position_onehots(gt_ref, start, count, nhat):
    """Per-position one-hots for tour positions [start, start+count).

    Returns `count` blocks of (TILE_B, N̂) bf16, chains on sublanes.
    Built transpose-then-compare because flatten reshapes don't lower;
    callers stack with jnp.concatenate when they need a matmul lhs.
    """
    tile_b = gt_ref.shape[1]
    rows = gt_ref[pl.ds(start, count), :]  # (count, T) int32
    cols = rows.T  # (T, count) — supported 2-D transpose
    iota = jax.lax.broadcasted_iota(jnp.int32, (tile_b, nhat), 1)
    return [
        (cols[:, i : i + 1] == iota).astype(jnp.bfloat16) for i in range(count)
    ]


_NEG_BIG = -1e18


def _shift_down(a, k, fill):
    """Rows shifted down by k along axis 0, top filled with `fill`.

    Sublane shifts only — lane-axis shifts lower to cross-lane permutes
    and measured ~1 ms/sweep slower for the load scan below.
    """
    rows = a.shape[0]
    pad = jnp.full((k, a.shape[1]), fill, a.dtype)
    return jnp.concatenate([pad, a[: rows - k]], axis=0)


def eval_tours_homog(gt_ref, d_ref, cap0, wcap, *, chunk):
    """Homogeneous-capacity objective: (L̂, TILE_B) block -> (1, TILE_B).

    Fast path for uniform-capacity fleets (the CVRP benchmark norm) and
    TSP. Per chunk of `chunk` positions it runs one small MXU matmul per
    position (concatenating one-hots into a bigger lhs measurably loses
    to the copies it costs) and handles route loads with a *parallel*
    segmented scan — profiled 2.2 ms/sweep cheaper than the naive
    per-position register recurrence, whose serial dependency chain
    stalls the VPU:

      * demands ride in a spare padded column of D, so the per-position
        demand is a free byproduct column of the leg matmul;
      * within a chunk, cumulative demand C is a 3-level shift tree and
        "C at the most recent route-closing depot zero" is a max-scan of
        where(z, C, -BIG) — valid because demands are nonnegative, so C
        is nondecreasing;
      * a depot zero at position i contributes relu(C_i - C_lastclose -
        Q) to the excess; only two (1, T) carries cross chunks.

    Trailing pad rows are depot zeros and only ever close empty routes.
    """
    lhat = gt_ref.shape[0]
    tile_b = gt_ref.shape[1]
    nhat = d_ref.shape[0]
    n_chunks = lhat // chunk
    d = d_ref[:]

    def body(c, carry):
        acc, excess, cum, lc = carry
        start = c * chunk
        rows = gt_ref[pl.ds(start, chunk + 1), :]  # (C+1, T) int32
        # One compare per position; position i is prev for leg i and
        # next for leg i-1 — each one-hot is used twice.
        ohs = _position_onehots(gt_ref, start, chunk + 1, nhat)
        nd_rows = []
        for i in range(chunk):
            # X[b, m] = D[node_i(b), m] — exact row selection on the MXU
            # (bf16 inputs, f32 accumulator as Mosaic requires).
            x = jnp.dot(ohs[i], d, preferred_element_type=jnp.float32)
            # Leg costs accumulate as one FMA into a wide (T, N̂) buffer;
            # the lane reduction happens ONCE after the loop instead of
            # per position (hundreds of VPU reductions saved per tile).
            acc = acc + x * ohs[i + 1].astype(jnp.float32)
            nd_rows.append(x[:, nhat - 1 : nhat].T)  # demand column
        nd = jnp.concatenate(nd_rows, axis=0)  # (C, T) f32
        z = rows[:chunk] == 0  # (C, T) route-closing depot zeros

        # Inclusive prefix demand within the chunk (log-depth shifts).
        p = nd
        k = 1
        while k < chunk:
            p = p + _shift_down(p, k, 0.0)
            k *= 2
        cdem = cum + p  # running cumulative demand C
        # Max-scan of C at closes == C at the most recent close <= i.
        m = jnp.where(z, cdem, _NEG_BIG)
        k = 1
        while k < chunk:
            m = jnp.maximum(m, _shift_down(m, k, _NEG_BIG))
            k *= 2
        lc_exc = jnp.maximum(_shift_down(m, 1, _NEG_BIG), lc)
        contrib = jnp.where(
            z, jnp.maximum(cdem - lc_exc - cap0, 0.0), 0.0
        )
        excess = excess + jnp.sum(contrib, axis=0, keepdims=True)
        cum = cdem[chunk - 1 : chunk]
        lc = jnp.maximum(lc, m[chunk - 1 : chunk])
        return acc, excess, cum, lc

    zero_acc = jnp.zeros((tile_b, nhat), jnp.float32)
    zero_row = jnp.zeros((1, tile_b), jnp.float32)
    acc, excess, cum, lc = jax.lax.fori_loop(
        0, n_chunks - 1, body, (zero_acc, zero_row, zero_row, zero_row)
    )
    dist = jnp.sum(acc, axis=1, keepdims=True)  # the one deferred reduction
    # The loop stops short of the trailing all-depot pad chunk; close any
    # still-open route here.
    excess = excess + jnp.maximum(cum - lc - cap0, 0.0)
    return dist.T + wcap * excess


def eval_tours(gt_ref, d_ref, dem_ref, cap_ref, wcap, nd_ref, *, n_vehicles, chunk):
    """Objective of every tour in a (L̂, TILE_B) block -> (1, TILE_B) f32.

    General path: per-vehicle capacities via a route-id triangular matmul
    over an (L̂, TILE_B) per-position demand scratch (nd_ref). The
    uniform-capacity fast path above avoids the scratch entirely.
    """
    lhat = gt_ref.shape[0]
    tile_b = gt_ref.shape[1]
    nhat = d_ref.shape[0]
    n_chunks = lhat // chunk
    d = d_ref[:]
    dem_col = dem_ref[:].reshape(nhat, 1)

    def body(c, dist):
        start = c * chunk
        # chunk+1 one-hots; position i serves as prev for leg i and next
        # for leg i-1, so each is built once and used twice. The final
        # chunk's successors live in the trailing all-depot pad chunk, so
        # start+chunk stays in bounds and those legs cost D[0,0]=0.
        ohs = _position_onehots(gt_ref, start, chunk + 1, nhat)
        p_oh = jnp.concatenate(ohs[:-1], axis=0)  # (C*T, N̂)
        n_oh = jnp.concatenate(ohs[1:], axis=0)
        # X[p, m] = D[node(p), m] — exact row selection on the MXU
        # (bf16 inputs, f32 accumulator as Mosaic requires).
        x = jnp.dot(p_oh, d, preferred_element_type=jnp.float32)
        legs = jnp.sum(x * n_oh.astype(jnp.float32), axis=1, keepdims=True)
        # Per-position demand of the chunk, stored for the load pass.
        nd = jnp.dot(
            p_oh.astype(jnp.float32), dem_col, preferred_element_type=jnp.float32
        )  # (C*T, 1)
        for i in range(chunk):
            blk = slice(i * tile_b, (i + 1) * tile_b)
            dist = dist + legs[blk]
            nd_ref[pl.ds(start + i, 1), :] = nd[blk].T
        return dist

    dist = jax.lax.fori_loop(
        0, n_chunks - 1, body, jnp.zeros((tile_b, 1), jnp.float32)
    )
    # Demands of the trailing pad chunk are all depot zeros; the load pass
    # below masks by rid < V anyway, but keep the scratch fully defined.
    nd_ref[pl.ds(lhat - chunk, chunk), :] = jnp.zeros(
        (chunk, tile_b), jnp.float32
    )

    # rid[l] = (# zeros at positions <= l) - 1 via a triangular MXU matmul
    # (counts are small integers — exact in bf16 up to 256).
    gt = gt_ref[:]
    is_zero = (gt == 0).astype(jnp.bfloat16)  # (L̂, T)
    row_i = jax.lax.broadcasted_iota(jnp.int32, (lhat, lhat), 0)
    col_i = jax.lax.broadcasted_iota(jnp.int32, (lhat, lhat), 1)
    tri = (col_i <= row_i).astype(jnp.bfloat16)
    rid = (
        jnp.dot(tri, is_zero, preferred_element_type=jnp.float32)
        .astype(jnp.int32)
        - 1
    )  # (L̂, T); pad rows exceed V-1 and drop out of every load below

    # Loads: route v's demand, excess past its capacity.
    nd_all = nd_ref[:]

    def cap_body(v, excess):
        mask = rid == v
        load = jnp.sum(jnp.where(mask, nd_all, 0.0), axis=0, keepdims=True)
        return excess + jnp.maximum(load - cap_ref[0, v], 0.0)

    excess = jax.lax.fori_loop(
        0, n_vehicles, cap_body, jnp.zeros((1, tile_b), jnp.float32)
    )
    return dist.T + wcap * excess


def _eval_kernel(gt_ref, d_ref, dem_ref, cap_ref, wcap_ref, cost_ref, nd_ref,
                 *, n_vehicles, chunk):
    cost_ref[0:1, :] = eval_tours(
        gt_ref, d_ref, dem_ref, cap_ref, wcap_ref[0, 0], nd_ref,
        n_vehicles=n_vehicles, chunk=chunk,
    )


def _eval_kernel_homog(gt_ref, d_ref, scal_ref, cost_ref, *, chunk):
    # No dem input: on this path demands ride in D's packed last column.
    cost_ref[0:1, :] = eval_tours_homog(
        gt_ref, d_ref, scal_ref[0, 0], scal_ref[0, 1], chunk=chunk
    )


def demand_scale(demands) -> float | None:
    """Largest uniform divisor g making demands/g bf16-exact integers.

    The homogeneous-capacity kernel packs demands into a bf16 column of D
    (pad_static), and the delta path's dp_init rides bf16 matvecs — both
    exact only for integers <= 256. Real instances often carry LARGE
    integer demands with a common factor (E-n22-k4: 100..2500, gcd 100),
    so scaling by the gcd restores exactness without touching semantics:
    capacity scales with them and the excess scales back by g at the
    weight (ADVICE round 3: the unscaled bf16 rounding let slightly
    infeasible tours rank as feasible champions). Returns None when no
    such g exists (non-integral or irreducibly > 256 demands) — callers
    then use the f32-exact general kernel or the XLA one-hot path.
    """
    import numpy as np

    if isinstance(demands, jax.core.Tracer):
        return None
    dem = np.asarray(demands, np.float64)
    if dem.size == 0 or not np.all(np.isfinite(dem)) or np.any(dem < 0):
        return None
    ints = np.rint(dem)
    if not np.allclose(dem, ints, rtol=0.0, atol=1e-9):
        return None
    if ints.max() <= 256:
        return 1.0
    g = int(np.gcd.reduce(ints.astype(np.int64)))
    if g <= 0 or ints.max() / g > 256:
        return None
    return float(g)


def pad_static(inst: Instance, dem_scale: float = 1.0):
    """Durations/demands/capacities padded to kernel shapes (N̂, V̂).

    The last padded column of D carries the demand vector (bf16) scaled
    by 1/dem_scale (see demand_scale — the caller folds the factor back
    into capacity and the excess weight), so row selection yields each
    node's demand for free alongside its leg row; legs never read that
    column because no tour contains node N̂-1 (N̂ is bumped a full
    lane-tile when N is already a 128 multiple).
    """
    n = inst.n_nodes
    nhat = _padded_n(n)
    d = jnp.zeros((nhat, nhat), jnp.bfloat16).at[:n, :n].set(
        inst.durations[0].astype(jnp.bfloat16)
    )
    dem = jnp.zeros((nhat,), jnp.float32).at[:n].set(inst.demands)
    d = d.at[:, nhat - 1].set((dem / dem_scale).astype(jnp.bfloat16))
    vhat = _round_up(inst.n_vehicles, 8)
    cap = jnp.full((1, vhat), 1e18, jnp.float32).at[0, : inst.n_vehicles].set(
        inst.capacities
    )
    return d, dem, cap


def padded_length(length: int, chunk: int) -> int:
    """Position-axis pad: chunk multiple + one all-depot successor chunk."""
    return _round_up(length, chunk) + chunk


@functools.partial(
    jax.jit, static_argnames=("tile_b", "n_vehicles", "chunk", "interpret")
)
def _run(giants_t, d, dem, cap, wcap, *, tile_b, n_vehicles, chunk, interpret=False):
    lhat, b = giants_t.shape
    grid = b // tile_b
    cost = pl.pallas_call(
        functools.partial(_eval_kernel, n_vehicles=n_vehicles, chunk=chunk),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((lhat, tile_b), lambda i: (0, i)),
            pl.BlockSpec(d.shape, lambda i: (0, 0)),
            pl.BlockSpec(dem.shape, lambda i: (0,)),
            pl.BlockSpec(cap.shape, lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, tile_b), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, b), jnp.float32),
        scratch_shapes=[pltpu.VMEM((lhat, tile_b), jnp.float32)],
        interpret=interpret,
    )(giants_t, d, dem, cap, wcap)
    return cost[0]


@functools.partial(jax.jit, static_argnames=("tile_b", "chunk", "interpret"))
def _run_homog(giants_t, d, scal, *, tile_b, chunk, interpret=False):
    lhat, b = giants_t.shape
    grid = b // tile_b
    cost = pl.pallas_call(
        functools.partial(_eval_kernel_homog, chunk=chunk),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((lhat, tile_b), lambda i: (0, i)),
            pl.BlockSpec(d.shape, lambda i: (0, 0)),
            pl.BlockSpec((1, 2), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, tile_b), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, b), jnp.float32),
        interpret=interpret,
    )(giants_t, d, scal)
    return cost[0]


def _homogeneous_capacity(inst: Instance):
    """Concrete scalar capacity when the fleet is uniform, else None.

    Solvers call this with concrete (non-traced) instances — the pallas
    dispatch happens at trace time, so data-dependent inspection is safe
    there; traced capacities fall back to the general kernel.
    """
    caps = inst.capacities
    if isinstance(caps, jax.core.Tracer) or isinstance(
        inst.demands, jax.core.Tracer
    ):
        return None
    import numpy as np

    c = np.asarray(caps)
    uniform = bool(np.all(c == c[0]))
    # The max-scan load trick needs nondecreasing cumulative demand.
    nonneg = bool(np.all(np.asarray(inst.demands) >= 0))
    return float(c[0]) if (uniform and nonneg) else None


_VMEM_BUDGET = 9 * 2**20  # conservative share of the ~16 MB v5e VMEM


def _vmem_estimate(tb, ch, nhat, lhat, het) -> int:
    """Rough peak VMEM of one kernel tile, in bytes.

    Calibrated against what actually compiles on v5e at N̂=256: 1024/8
    (~8 MB) fits, 1024/16 and 2048/8 (~12+ MB) crash the compiler.
    """
    est = (
        (ch + 1) * tb * nhat * 2  # bf16 one-hot blocks live across a chunk
        + 2 * tb * nhat * 4       # x + deferred-reduction acc (f32)
        + lhat * tb * 4           # the tours block
        + nhat * nhat * 2         # durations (bf16)
    )
    if het:  # general kernel extras: nd scratch, tri matmul, rid
        est += lhat * tb * 4 + lhat * lhat * 2 + lhat * tb * 4
    return est


def _auto_tile(batch: int, nhat: int, lhat: int, het: bool):
    """Fastest-measured (tile_b, chunk) that divides the batch AND fits
    the VMEM model, or None when nothing does (huge-N instances —
    callers then fall back to the XLA one-hot path).

    Preference order per v5e measurements: 1024/8 > 512/16 > 256/16 >
    128/16, with 128/8 as the smallest-footprint last resort (every
    entry verified to actually compile on v5e — several nearby configs,
    e.g. 1024/4, 256/8 and 2048/*, are unverified or crash Mosaic).
    """
    for tb, ch in (
        (1024, 8), (512, 16), (512, 8), (256, 16), (128, 16), (128, 8)
    ):
        if batch % tb == 0 and _vmem_estimate(tb, ch, nhat, lhat, het) <= _VMEM_BUDGET:
            return tb, ch
    return None


def _padded_n(n: int) -> int:
    nhat = _round_up(n, 128)
    return nhat + 128 if nhat == n else nhat


def pallas_supported(inst: Instance, batch: int) -> bool:
    """Can pallas_objective_batch handle this instance/batch? Mirrors
    every precondition the kernel raises on, including the VMEM fit, so
    dispatchers can fall back to XLA instead of failing at compile."""
    if not _PALLAS_OK or inst.has_tw or inst.time_dependent:
        return False
    if inst.n_real is not None:
        # tier-padded instances (core.tiers): the kernel's route logic
        # keys on literal zeros and does not model phantom separators
        return False
    if batch % 128:
        return False
    length = inst.n_customers + inst.n_vehicles + 1
    # "het" here means "takes the general kernel" — true heterogeneous
    # fleets AND uniform fleets whose demands have no bf16-exact scaling.
    het = (
        _homogeneous_capacity(inst) is None
        or demand_scale(inst.demands) is None
    )
    # lhat depends on the chunk chosen; bound it by the largest pad
    return (
        _auto_tile(batch, _padded_n(inst.n_nodes), length + 2 * 16, het)
        is not None
    )


def pallas_objective_batch(
    giants: jax.Array,
    inst: Instance,
    w: CostWeights,
    tile_b: int | None = None,
    chunk: int | None = None,
    transposed: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Fused-TPU batched objective; drop-in for objective_hot_batch.

    giants: (B, L) int32 — or (L, B) with transposed=True to skip the
    relayout when the caller keeps SA state in kernel layout. B must be
    a multiple of 128 (the TPU lane width — Mosaic requires minor block
    dims of 128); tile_b/chunk default to the measured-best choice for
    the batch size.
    """
    if not _PALLAS_OK:
        raise RuntimeError("pallas unavailable in this environment")
    if inst.has_tw or inst.time_dependent:
        raise ValueError("pallas objective covers the untimed fast path only")
    gt = giants if transposed else giants.T
    if tile_b is None or chunk is None:
        cap0_known = (
            _homogeneous_capacity(inst) is not None
            and demand_scale(inst.demands) is not None
        )
        auto = _auto_tile(
            gt.shape[1], _padded_n(inst.n_nodes), gt.shape[0] + 2 * 16,
            het=not cap0_known,
        )
        if auto is None:
            raise ValueError(
                f"no pallas tile fits VMEM for batch {gt.shape[1]}, "
                f"{inst.n_nodes} nodes (use the XLA one-hot path)"
            )
        tile_b, chunk = tile_b or auto[0], chunk or auto[1]
    lhat = padded_length(gt.shape[0], chunk)
    if gt.shape[1] % tile_b:
        raise ValueError(f"batch {gt.shape[1]} not a multiple of tile_b {tile_b}")
    gt = jnp.pad(gt, ((0, lhat - gt.shape[0]), (0, 0)))
    cap0 = _homogeneous_capacity(inst)
    # bf16-exactness of the packed demand column (see demand_scale);
    # unscalable demands take the general kernel, whose f32 demand input
    # is exact for any values.
    g = demand_scale(inst.demands) if cap0 is not None else None
    d, dem, cap = pad_static(inst, dem_scale=g if g is not None else 1.0)
    if cap0 is not None and g is not None:
        # excess computes in demand/g units against capacity/g; folding g
        # into the weight returns it to real units: w*g*(excess/g).
        scal = jnp.stack(
            [jnp.float32(cap0 / g), jnp.asarray(w.cap, jnp.float32) * g]
        ).reshape(1, 2)
        return _run_homog(
            gt, d, scal, tile_b=tile_b, chunk=chunk, interpret=interpret
        )
    wcap = jnp.asarray(w.cap, jnp.float32).reshape(1, 1)
    return _run(
        gt, d, dem, cap, wcap,
        tile_b=tile_b, n_vehicles=inst.n_vehicles, chunk=chunk,
        interpret=interpret,
    )
